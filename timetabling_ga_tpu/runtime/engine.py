"""The run engine: host orchestration of the island GA.

The TPU-native re-design of ga.cpp main() (ga.cpp:370-613). Where the
reference interleaves MPI bootstrap, OpenMP breeding loops and ad-hoc
logging in one function, the engine is a host loop over *dispatches*: each
dispatch is one fully on-device jit call covering one or more epochs
(migration_period generations per island + ring migration each, see
parallel/islands.py). The runner returns a per-GENERATION (hcv, scv) best
trace per island, so the JSONL logEntry protocol sees every mid-epoch
improvement (ga.cpp:203-228 granularity) while the host reads back exactly
one array per dispatch — no per-epoch scalar fetches (they cost seconds on
tunneled devices; BASELINE.md methodology note).

Timing semantics (Control/Timer parity):
  - the wall-clock bound -t applies per try, reset at the top of each
    trial (beginTry/resetTime, ga.cpp:163-167; Control.cpp:62-68);
  - the generation budget is exact: the final dispatch is clamped to the
    remaining generations instead of overshooting to a multiple of
    migration_period;
  - logEntry times are interpolated linearly across a dispatch's wall
    time (generations inside one dispatch are not individually host-
    timestampable; the interpolation error is bounded by one dispatch).

Observability (--trace, SURVEY section 5): per-phase host timings
(init / dispatch / fetch / checkpoint) bracketed by data-fetch fences are
emitted as {"phase": ...} JSONL records — an extension record type; the
reference protocol's three record types are unchanged and remain
byte-compatible.

Dispatch pipeline (the control-vs-telemetry fence rule). Every host-side
read of device data in this loop is one of two kinds:

  CONTROL — its value decides WHAT the engine dispatches next (the
  post-feasibility phase switch, the stall-kick trigger, a checkpoint
  snapshot, every timing probe that feeds the budget predictor). These
  MUST be real data-fetch fences (BASELINE.md round-5 fence audit:
  block_until_ready can early-ack on the tunneled device), and the
  engine may not run ahead of them.

  TELEMETRY — its value is only REPORTED (logEntry emission from the
  per-generation trace, phase records, checkpoint npz serialization).
  These must NOT stall the dispatch stream: the device idling through a
  log write is the host gap BENCH_r05 measured.

The run loop is a depth-2 asynchronous pipeline built on that split:
dispatch N+1 is enqueued immediately after chunk N's trace transfer is
started (`copy_to_host_async`), and chunk N's telemetry is processed
while N+1 executes; JSONL emission and checkpoint serialization run on a
background writer thread (jsonl.AsyncWriter) behind a bounded queue,
drained on exit and on error. Pipelining engages only when every
control path is a no-op for the run (single process, no post config, no
profiler bracket) — otherwise the loop stays serial, because control
reads must fence. Population buffers are donated between dispatches
(`donate` — islands._donate), so the big state tensors are aliased
rather than copied; tt-analyze TT203 guards the
no-read-after-donation discipline.

In-run fault recovery (README "Fault tolerance"). The tunneled device's
sick windows kill dispatches with UNAVAILABLE and hang fetch RPCs
mid-stream (BASELINE.md round-4, BENCH_r05); before this layer the only
defense was retrying WHOLE runs from outside the engine. A _Supervisor
now keeps a rolling in-memory host snapshot of the last control-fenced
state (the same tuple checkpoint.save takes), classifies every
dispatch/fetch failure through retry.is_transient (cause chain
included), and on a transient error tears down the poisoned device
buffers, re-resolves the mesh, purges the compiled programs bound to
it, rehydrates from the snapshot (durable-checkpoint fallback), and
resumes the generation loop — the lost wall time stays charged against
the trial budget. Every classified control-fence read runs under a
deadline watchdog (--fetch-timeout) so a hung fetch becomes a
recoverable timeout, and repeated failures inside a window walk a
degradation ladder: pipelined -> serial -> halved dispatch chunks.
Recovery events are {"faultEntry": ...} JSONL records;
runtime/faults.py injects every failure mode deterministically on the
CPU backend (TT_FAULTS) so tier-1 exercises each path.

Observability (tt-obs; README "Observability"). Under --obs every hot-
path phase (dispatch / fetch / process / checkpoint / init / polish /
lahc / recover) emits a host-side timing span as a {"spanEntry": ...}
record riding the SAME AsyncWriter — spans are telemetry by
construction and never fence. `tt trace` exports them as Chrome
trace-event JSON. Counters and gauges (dispatches, gens/sec, host-gap
ms/gen, device-busy fraction, recoveries, writer queue occupancy) live
in the process metrics registry (obs/metrics.py) regardless of --obs;
--obs additionally snapshots the registry as {"metricsEntry": ...}
records (every --metrics-every dispatches and at each try's end).
`--trace-mode deltas|stats` moves the telemetry REDUCTION on device
(parallel/islands.py _compress_trace): the runner ships per-island
best-delta events (+ streamed moments under `stats`) instead of the
full per-generation trace array, shrinking the fetched leaf from
O(gens) to O(improvements) per island while the emitted bestEver
stream stays identical to `full` (an emitted generation is by
definition a dispatch-local improvement; tests/test_obs.py pins the
A/B across modes, pipelining, and obs).
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time

import jax
import numpy as np

from timetabling_ga_tpu.obs import cost as obs_cost
from timetabling_ga_tpu.obs import metrics as obs_metrics
from timetabling_ga_tpu.obs import quality as obs_quality
from timetabling_ga_tpu.obs.spans import NULL_TRACER, SpanTracer
from timetabling_ga_tpu.ops import ga
from timetabling_ga_tpu.parallel import islands
from timetabling_ga_tpu.problem import load_tim_file
from timetabling_ga_tpu.runtime import checkpoint as ckpt
from timetabling_ga_tpu.runtime import control_channel
from timetabling_ga_tpu.runtime import dispatch_core as dcore
from timetabling_ga_tpu.runtime import faults
from timetabling_ga_tpu.runtime import jsonl
from timetabling_ga_tpu.runtime import retry
from timetabling_ga_tpu.runtime.config import RunConfig
from timetabling_ga_tpu.runtime.dispatch_core import FetchTimeout  # noqa: F401 (re-export: the supervised region and tests import it from here)

INT_MAX = 2 ** 31 - 1
# a reported best below this is feasible (reported form = hcv*1e6 + scv,
# jsonl.reported_best; ga.cpp:191)
FEASIBLE_LIMIT = 1_000_000

# Compiled-program caches, shared across engine.run calls — now owned
# by the dispatch core (runtime/dispatch_core.py) so the serve path's
# lane programs and the run loop's island programs live under one
# purge rule; aliased here (the SAME dict objects) because callers and
# tests clear/iterate them through the engine module.
# Every program cached here is wrapped by the cost observatory
# (obs/cost.py instrument): an AOT-dispatching proxy that times each
# lower+compile, extracts the executable's cost/memory analyses into
# the compile.* / cost.* metric families (and costEntry records under
# --obs), and counts warm dispatches — the compile-hit rate the serve
# path steers on. TT_COST_OBS=0 bypasses the wrapping (plain jit).
_RUNNER_CACHE: dict = dcore.RUNNER_CACHE
_INIT_CACHE: dict = dcore.INIT_CACHE

_mesh_key = dcore.mesh_key


def _pow2_floor(n: int) -> int:
    """Largest power of two <= n (n >= 1). The engine quantizes every
    static dispatch's epoch count with this, and precompile builds
    exactly the quantized shapes — one shared helper so the
    compiled-shape contract cannot drift."""
    return 1 << (n.bit_length() - 1)


def _shape_sig(problem):
    """Instance-shape signature for the compiled-program caches.

    jax.jit compiles PER INPUT SHAPE, so a cache hit on (mesh, gacfg,
    dispatch shape) alone does NOT mean 'no compile': the same runner
    object retraces for a differently-shaped instance, and treating that
    first call as warm would time the compile into the persisted sec/gen
    and sec/sweep estimates (poisoning every later budget decision for
    that instance — found in round-3 review). The shape signature makes
    warmness per-instance-shape."""
    return (problem.n_events, problem.n_rooms, problem.n_students,
            problem.n_days, problem.slots_per_day)


# fresh device copy of a state pytree, sharding preserved — see
# dispatch_core.clone_state for the donation discipline it serves
_clone = dcore.clone_state


def cached_runner(mesh, gacfg: ga.GAConfig, n_epochs: int, gens: int,
                  sig, n_islands: int, donate: bool = False,
                  trace_mode: str = "full", quality: bool = False):
    """Returns (runner, was_cached). was_cached=False means this
    (program, instance shape) pair is fresh, so its first call will pay
    an XLA compile. `donate` is part of the cache key (as in every
    cached_* factory here): the donating and non-donating jits are
    DIFFERENT executables, and colliding them would hand a
    buffer-deleting program to a caller that reuses its input.
    `trace_mode` likewise: full/deltas/stats runners return
    differently-shaped telemetry leaves (islands._compress_trace), and
    `quality` likewise: the quality observatory's runners append the
    packed quality block to the leaf (README "Search-quality
    observatory")."""
    k = (_mesh_key(mesh), gacfg, n_epochs, gens, sig, n_islands, donate,
         trace_mode, quality)
    r = _RUNNER_CACHE.get(k)
    if r is not None:
        return r, True
    r = obs_cost.instrument(
        islands.make_island_runner(mesh, gacfg, n_epochs=n_epochs,
                                   gens_per_epoch=gens,
                                   n_islands=n_islands, donate=donate,
                                   trace_mode=trace_mode,
                                   quality=quality), "runner")
    _RUNNER_CACHE[k] = r
    return r, False


def cached_dynamic_runner(mesh, gacfg: ga.GAConfig, max_gens: int, sig,
                          n_islands: int, donate: bool = False,
                          trace_mode: str = "full",
                          quality: bool = False):
    """Tail-dispatch runner with a RUNTIME generation count (one compile
    serves every n_gens <= max_gens), used to spend the last slice of a
    wall-clock budget instead of idling through it."""
    k = ("dyn", _mesh_key(mesh), gacfg, max_gens, sig, n_islands, donate,
         trace_mode, quality)
    r = _RUNNER_CACHE.get(k)
    if r is not None:
        return r, True
    r = obs_cost.instrument(
        islands.make_island_runner_dynamic(mesh, gacfg, max_gens,
                                           n_islands=n_islands,
                                           donate=donate,
                                           trace_mode=trace_mode,
                                           quality=quality),
        "dyn_runner")
    _RUNNER_CACHE[k] = r
    return r, False


def cached_init(mesh, pop_size: int, gacfg: ga.GAConfig,
                n_islands: int):
    k = (_mesh_key(mesh), pop_size, gacfg, n_islands)
    f = _INIT_CACHE.get(k)
    if f is None:
        init_fn = lambda pa, key: islands.init_island_population(
            pa, key, mesh, pop_size, gacfg, n_islands=n_islands)
        init_fn.__name__ = init_fn.__qualname__ = \
            f"init_pop{pop_size}_i{n_islands}"
        f = obs_cost.instrument(jax.jit(init_fn), "init")
        _INIT_CACHE[k] = f
    return f


def cached_lane_runner(mesh, gacfg: ga.GAConfig, max_gens: int,
                       n_lanes: int, donate: bool = False,
                       trace_mode: str = "full", quality: bool = False):
    """Multi-tenant lane program (islands.make_lane_runner) for the
    serve scheduler: one compiled program per (mesh, config, quantum
    bound, lane count) serves EVERY job whose padded instance shares
    the bucket shape — the compile-cache key is the bucket, not the
    instance (serve/bucket.py). Lives in _RUNNER_CACHE so recovery's
    _purge_programs covers it like every other compiled program.
    `trace_mode` and `quality` are part of the key (different telemetry
    leaf shapes, like cached_runner)."""
    k = ("lane", _mesh_key(mesh), gacfg, max_gens, n_lanes, donate,
         trace_mode, quality)
    r = _RUNNER_CACHE.get(k)
    if r is not None:
        return r, True
    # the observatory's per-signature accounting makes serve's compile
    # story measurable: the lane program's input SIGNATURE is the shape
    # bucket (pad_problem), so compile.count.lane_runner counts bucket
    # compiles and compile.cache_hits counts bucket-warm dispatches —
    # the compile-hit rate bucket-affine routing steers on
    r = obs_cost.instrument(
        islands.make_lane_runner(mesh, gacfg, max_gens, n_lanes,
                                 donate=donate, trace_mode=trace_mode,
                                 quality=quality),
        "lane_runner")
    _RUNNER_CACHE[k] = r
    return r, False


def cached_lane_init(mesh, pop_size: int, gacfg: ga.GAConfig,
                     n_lanes: int):
    """Per-lane init program (islands.make_lane_init), cached like
    cached_init."""
    k = ("lane-init", _mesh_key(mesh), pop_size, gacfg, n_lanes)
    f = _INIT_CACHE.get(k)
    if f is None:
        f = obs_cost.instrument(
            islands.make_lane_init(mesh, pop_size, gacfg, n_lanes),
            "lane_init")
        _INIT_CACHE[k] = f
    return f


# Hard ceiling on one fused dispatch's predicted wall time. The
# tunneled device kills kernels that run too long ('UNAVAILABLE: TPU
# device error — often a kernel fault'): the comp05s post-phase runner
# at 4 fused epochs crossed that watchdog while 2 epochs stayed under
# it, and the converge while_loops' data-dependent pass counts made the
# failure nondeterministic across runs (round-4 diagnosis: every
# component passed in isolation; the step-by-step precompile died
# exactly at post/n_ep=4). Dispatches are therefore sized so
# sec_per_gen * gens <= this cap — long enough to amortize the ~70 ms
# dispatch + trace-fetch overhead, far under the watchdog. The 30 s
# default is this tunneled device's limit, not a law of nature: on
# hardware without a long-kernel watchdog, raise (or effectively
# disable) it via TT_DISPATCH_CAP_S to fuse bigger dispatches
# (ADVICE round 4).
DISPATCH_CAP_S = float(os.environ.get("TT_DISPATCH_CAP_S", "30.0"))

# Measured seconds-per-generation, persisted across engine.run calls with
# the same (mesh, config, problem shape) so a warm-up run's measurement
# bounds even the FIRST dispatch of a later timed run.
_SPG_CACHE: dict = {}
# Largest n_epochs precompile actually built per (mesh, gacfg,
# fingerprint) under DISPATCH_CAP_S — timed runs never dispatch beyond
# it (a bigger shape would both compile mid-budget and risk the
# watchdog).
_MAX_EP_CACHE: dict = {}
# Likewise for seconds-per-sweep-pass of the init polish runner.
_SPS_CACHE: dict = {}
# Measured final-fetch cost (slots/rooms/hcv/scv round trip), reserved
# out of the dispatch budget so -t covers the whole try INCLUDING the
# endTry fetch (VERDICT round-3 weak #2: ~5 s overruns traced to work
# outside the predictor).
_FETCH_CACHE: dict = {}


def _spg_for(cur_key, cur, gacfg, spg_key):
    """Seconds-per-generation estimate for the active phase config.

    On a cache miss for the POST config (e.g. a plain CLI run that never
    called precompile), fall back to the repair config's estimate scaled
    by the LS-depth ratio — post generations are more expensive roughly
    in proportion to sweeps x pivot count, and an un-clamped first
    dispatch after the switch would otherwise blow through -t (plus the
    mid-run compile, which only precompile can avoid)."""
    est = _SPG_CACHE.get(cur_key)
    if est is not None or cur is gacfg:
        return est
    base = _SPG_CACHE.get(spg_key)
    if base is None:
        return None
    ratio = max(1.0, cur.ls_sweeps / max(gacfg.ls_sweeps, 1))
    if gacfg.ls_hot_k > 0 and cur.ls_hot_k == 0:
        ratio *= 2.0   # full-pivot passes cost more than top-K passes
    return base * ratio


def _sync_vals(*vals):
    """Multi-host schedule agreement (ADVICE round 3): every process
    must take the SAME dispatch decisions (chunk sizes, epoch counts,
    break/continue) or their collective program sequences diverge near
    the -t boundary and the run deadlocks. Decisions are computed from
    per-process clocks, then overridden with process 0's values.

    tt-accord: agreement rides the control side channel
    (control_channel.agree, process-0-wins over the coordination
    service's KV store) — host-side, OFF the device path, so schedule
    agreement still works while the collective program is poisoned or
    a peer is dead (the channel classifies that instead of hanging).
    --no-accord falls back to the PR-1 `broadcast_one_to_all` device
    collective. Identity on single-process runs either way."""
    if jax.process_count() > 1:
        ch = control_channel.active()
        if ch is not None:
            return tuple(int(v)
                         for v in ch.agree("s", [int(v) for v in vals]))
        from jax.experimental import multihost_utils
        arr = multihost_utils.broadcast_one_to_all(
            np.asarray(vals, np.int64))
        return tuple(int(v) for v in arr)
    return tuple(int(v) for v in vals)


def cached_kick_runner(mesh, gacfg: ga.GAConfig, sig, n_islands: int,
                       donate: bool = False):
    """Stall-kick program (islands.make_kick_runner): reseed the worst
    half of each island from mutated copies of its best. The traced
    program depends only on (pop_size, p1/p2/p3) of `gacfg`; the kick
    fires in the POST phase, so callers build it from the post config —
    whose pop_size may be the shrunk one (post_pop_size)."""
    k = ("kick", _mesh_key(mesh), gacfg.pop_size, gacfg.p1, gacfg.p2,
         gacfg.p3, sig, n_islands, donate)
    r = _RUNNER_CACHE.get(k)
    if r is not None:
        return r, True
    r = obs_cost.instrument(
        islands.make_kick_runner(mesh, gacfg, n_islands=n_islands,
                                 donate=donate), "kick")
    _RUNNER_CACHE[k] = r
    return r, False


# Measured seconds-per-LAHC-step (walker-ensemble step, not per
# candidate), persisted like _SPG_CACHE so a precompiled probe bounds
# the first timed chunk.
_LAHC_SPS_CACHE: dict = {}


def _lahc_key(mesh, gacfg: ga.GAConfig, hist_len: int, k_cands: int,
              fingerprint):
    return ("lahc", _mesh_key(mesh), gacfg.pop_size, gacfg.p1, gacfg.p2,
            gacfg.p3, hist_len, k_cands, fingerprint)


def cached_lahc_runners(mesh, gacfg: ga.GAConfig, hist_len: int,
                        k_cands: int, sig, n_islands: int,
                        donate: bool = False,
                        with_moments: bool = False):
    """(init, run, finalize) LAHC endgame programs
    (islands.make_lahc_runners). The traced programs depend only on
    (pop_size, p1/p2/p3, hist_len, k_cands) of the POST config, whose
    pop_size may be the shrunk one. `with_moments` (--trace-mode stats)
    appends walker-ensemble moment rows to the run program's stats
    fetch and is a DIFFERENT traced program, hence part of the key."""
    k = ("lahc", _mesh_key(mesh), gacfg.pop_size, gacfg.p1, gacfg.p2,
         gacfg.p3, hist_len, k_cands, sig, n_islands, donate,
         with_moments)
    r = _RUNNER_CACHE.get(k)
    if r is None:
        init_r, run_r, fin_r = islands.make_lahc_runners(
            mesh, gacfg, hist_len, k_cands, n_islands, donate=donate,
            with_moments=with_moments)
        r = (obs_cost.instrument(init_r, "lahc_init"),
             obs_cost.instrument(run_r, "lahc_run"),
             obs_cost.instrument(fin_r, "lahc_fin"))
        _RUNNER_CACHE[k] = r
    return r


def cached_shrink_runner(mesh, pop_in: int, pop_out: int,
                         n_islands: int):
    """Elite truncation at the post-feasibility switch (post_pop_size);
    see islands.make_shrink_runner."""
    k = ("shrink", _mesh_key(mesh), pop_in, pop_out, n_islands)
    r = _RUNNER_CACHE.get(k)
    if r is None:
        r = obs_cost.instrument(
            islands.make_shrink_runner(mesh, pop_in, pop_out,
                                       n_islands), "shrink")
        _RUNNER_CACHE[k] = r
    return r


def cached_polish_runner(mesh, gacfg: ga.GAConfig, sig,
                         n_islands: int, donate: bool = False,
                         with_passes: bool = False):
    """Init-polish runner with a RUNTIME sweep count (one compile serves
    every chunk size); see islands.make_polish_runner. `with_passes`
    (--trace-mode stats) adds the sweep-pass-count stats row and is a
    DIFFERENT traced program, hence part of the key."""
    k = ("polish", _mesh_key(mesh), gacfg, sig, n_islands, donate,
         with_passes)
    r = _RUNNER_CACHE.get(k)
    if r is not None:
        return r, True
    r = obs_cost.instrument(
        islands.make_polish_runner(mesh, gacfg, n_islands=n_islands,
                                   donate=donate,
                                   with_passes=with_passes), "polish")
    _RUNNER_CACHE[k] = r
    return r, False


def build_ga_config(cfg: RunConfig) -> ga.GAConfig:
    """Map run flags to breeding hyper-parameters.

    The reference's LS budget counts candidate evaluations
    (stepCount, Solution.cpp:471-769); one of our LS rounds evaluates
    `ls_candidates` candidates, so rounds = maxSteps / ls_candidates keeps
    the candidate budget comparable."""
    max_steps = cfg.resolved_max_steps()
    ls_rounds = max(1, max_steps // cfg.ls_candidates)
    return ga.GAConfig(
        pop_size=cfg.pop_size,
        p1=cfg.p1, p2=cfg.p2, p3=cfg.p3,
        ls_steps=ls_rounds, ls_candidates=cfg.ls_candidates,
        ls_delta=not cfg.ls_full_eval,
        ls_mode=cfg.ls_mode, ls_sweeps=cfg.ls_sweeps,
        ls_swap_block=cfg.ls_swap_block,
        ls_block_events=cfg.ls_block_events,
        ls_sideways=cfg.ls_sideways,
        ls_hot_k=cfg.ls_hot_k,
        ls_converge=cfg.ls_converge, init_sweeps=cfg.init_sweeps,
        rooms_mode=cfg.rooms_mode,
        multi_objective=cfg.nsga2,
    )


def build_post_config(cfg: RunConfig, gacfg: ga.GAConfig):
    """Post-feasibility breeding config, or None when no post_* flag is
    set. The reference's localSearch changes character once feasible —
    phase 2 polishes scv to a local optimum with ALL partners
    (Solution.cpp:619-768) — so the engine mirrors that with a second
    compiled runner it switches to at the first dispatch after the
    global best reaches feasibility (VERDICT round-3 next #3)."""
    if (cfg.post_ls_sweeps is None and cfg.post_swap_block is None
            and cfg.post_hot_k is None and cfg.post_sideways is None
            and cfg.post_pop_size is None and cfg.post_lahc <= 0):
        return None
    post = dataclasses.replace(
        gacfg,
        pop_size=(cfg.post_pop_size if cfg.post_pop_size is not None
                  else gacfg.pop_size),
        ls_sweeps=(cfg.post_ls_sweeps if cfg.post_ls_sweeps is not None
                   else gacfg.ls_sweeps),
        ls_swap_block=(cfg.post_swap_block
                       if cfg.post_swap_block is not None
                       else gacfg.ls_swap_block),
        ls_hot_k=(cfg.post_hot_k if cfg.post_hot_k is not None
                  else gacfg.ls_hot_k),
        ls_sideways=(cfg.post_sideways if cfg.post_sideways is not None
                     else gacfg.ls_sideways))
    if cfg.post_lahc > 0:
        # the LAHC endgame needs a phase switch even when every GA post
        # field is inherited unchanged (post == gacfg); the post config
        # then only supplies pop size + move probabilities to the
        # walker programs
        return post
    return None if post == gacfg else post


# one dispatched-but-not-yet-retired chunk of the pipelined run loop
# (see _run_tries) — dispatch_core.Chunk, aliased for the tests and
# callers that build chunks through the engine module
_Chunk = dcore.Chunk


def run_counters() -> dict:
    """Back-compat view of the process robustness counters, now held by
    the obs metrics registry (`engine.recoveries`, `faults.injected` —
    obs/metrics.py REGISTRY). Callers (bench.py) snapshot before/after
    a measurement and record the delta, exactly as they did when these
    were module globals."""
    return {"recoveries": int(
                obs_metrics.REGISTRY.counter("engine.recoveries").value),
            "faults_injected": faults.injected_total()}


# program purge + rolling-snapshot fault-recovery policy: extracted to
# the dispatch core (one purge rule and one supervisor policy for the
# run loop AND the serve path), aliased here because the recovery
# tests monkeypatch them through the engine module — _run_tries
# resolves `_Supervisor` at call time for exactly that reason
_purge_programs = dcore.purge_programs
_Snapshot = dcore.Snapshot
_Supervisor = dcore.Supervisor


def purge_programs(mesh) -> None:
    """Public program purge for the serve-path fault recovery
    (serve/scheduler.py _recover_quantum): the per-job analogue of the
    run supervisor applies the same rule — after a transient device
    failure, every compiled program bound to the mesh (including the
    cached lane runners/inits) may reference poisoned state and is
    rebuilt on the next dispatch."""
    dcore.purge_programs(mesh)


_DISTRIBUTED_DONE = False


def maybe_init_distributed(cfg: RunConfig) -> None:
    """Multi-host entry point — the role MPI_Init plays for the
    reference (ga.cpp:373-380). Called before any device use; the island
    mesh then spans every process's devices, with migration riding ICI
    within a slice and DCN across hosts (SURVEY section 5, distributed
    comm backend).

    Launch (one command per host, like mpirun's per-rank launch):
        host0: tt -i x.tim --coordinator host0:1234 \
                  --num-processes 2 --process-id 0
        host1: tt -i x.tim --coordinator host0:1234 \
                  --num-processes 2 --process-id 1
    On TPU pods, `--distributed` alone auto-detects all three values
    from the environment. Idempotent: repeated engine.run calls in one
    process initialize once."""
    global _DISTRIBUTED_DONE
    if _DISTRIBUTED_DONE or not (cfg.distributed or cfg.coordinator):
        return
    if cfg.backend == "cpu":
        # multi-process CPU (the 2-process e2e tier, and any host-only
        # rehearsal of a pod launch) needs cross-process collectives
        # explicitly enabled — the backend default is 'none', which
        # fails every multi-process computation with INVALID_ARGUMENT.
        # Must happen BEFORE backend init; guarded because the flag's
        # name/values have moved across jax versions.
        try:
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
    kwargs = {}
    if cfg.coordinator is not None:
        kwargs = dict(coordinator_address=cfg.coordinator,
                      num_processes=cfg.num_processes,
                      process_id=cfg.process_id)
    jax.distributed.initialize(**kwargs)
    _DISTRIBUTED_DONE = True


# The fetch machinery — the control-fence watchdog (`_fetch`), the
# packed one-round-trip readbacks, and the resume-side rehydrate — is
# the dispatch core's (runtime/dispatch_core.py): one sanctioned fence
# surface for the run loop, the serve scheduler, and the fleet drive
# loop, and the sync-helper set tt-analyze's taint rules key on.
# Aliased under the established engine names (analysis sync_helpers
# config, tests, and the recovery handler all reach them here).
_reshard_state = dcore.reshard_state
_fetch = dcore.fetch
_fetch_final = dcore.fetch_final
_fetch_state = dcore.fetch_state


# --- the resumable run-chunk surface ---------------------------------
# The serve scheduler (timetabling_ga_tpu/serve/scheduler.py) drives the
# engine's machinery one CHUNK at a time: place a host snapshot on the
# mesh (reshard_state), dispatch one quantum through a cached_* program,
# fence, and take the next host snapshot (fetch_state) — exactly the
# park/resume cycle the PR-3 fault supervisor already performs around
# failures, exposed as the public chunk-step API so a scheduler can
# preempt and resume jobs at every control-fence boundary.

def fetch_state(state) -> ga.PopState:
    """Public host-snapshot fetch: one packed device round trip (see
    dispatch_core.fetch_state). The returned all-numpy PopState is the
    same tuple checkpoint.save takes and reshard_state re-places."""
    return dcore.fetch_state(state)


def reshard_state(state: ga.PopState, mesh) -> ga.PopState:
    """Public rehydrate: place a host (numpy) PopState back onto the
    mesh as global island/lane-sharded arrays (see
    dispatch_core.reshard_state)."""
    return dcore.reshard_state(state, mesh)


def _setup(cfg: RunConfig):
    """Shared run setup: load the instance, build mesh + breeding config
    + cache keys. precompile and _run_tries MUST agree on these (the
    compiled-program and sec/gen caches are keyed on them), so both call
    this one helper."""
    problem = load_tim_file(cfg.input)
    if cfg.auto_tune:
        # production defaults are size-tuned (the reference scales its
        # LS budget with problem type the same way, ga.cpp:389-397);
        # explicit user flags and non-default fields are never touched,
        # and a second call is a no-op (tuned values are non-default)
        cfg.apply_tuned_defaults(problem.n_events)
    pa = problem.device_arrays()
    devices = jax.devices()
    n_islands = cfg.islands if cfg.islands is not None else len(devices)
    if n_islands <= len(devices):
        mesh = islands.make_mesh(n_islands)
    else:
        # more islands than devices: each device carries
        # n_islands/n_devices vmapped LOCAL islands (islands.
        # local_islands) — the analogue of mpirun oversubscribing ranks
        # onto nodes, which is how the reference's island count scales
        # past the node count (ga.cpp:379). Rounded down to a multiple
        # of the device count so shards stay uniform.
        n_dev = len(devices)
        if n_islands % n_dev:
            down = (n_islands // n_dev) * n_dev
            print(f"warning: {n_islands} islands is not a multiple of "
                  f"{n_dev} devices; using {down}", file=sys.stderr)
            n_islands = down
        mesh = islands.make_mesh(n_dev)
    gacfg = build_ga_config(cfg)
    gacfg_post = build_post_config(cfg, gacfg)
    if (cfg.checkpoint and gacfg_post is not None
            and gacfg_post.pop_size != gacfg.pop_size):
        # parse_args refuses the flag combination; this guards
        # programmatic construction the same way (the mid-run shape
        # change cannot round-trip a checkpoint/resume cycle)
        raise ValueError("post_pop_size with checkpoint is unsupported")
    if gacfg_post is not None and not (
            1 <= gacfg_post.pop_size <= gacfg.pop_size):
        # post-tune validation (parse_args can only check when the user
        # pinned both flags): a post population larger than the repair
        # one has no elite rows to grow from, below 1 it has no rows at
        # all — either way the shard reshape would fail with an opaque
        # XLA error instead of this message
        raise ValueError(
            f"post_pop_size {gacfg_post.pop_size} must be in "
            f"[1, pop_size={gacfg.pop_size}]")
    fingerprint = ckpt.config_fingerprint(problem, gacfg, n_islands)
    spg_key = (_mesh_key(mesh), gacfg, fingerprint)
    return (problem, pa, mesh, n_islands, gacfg, gacfg_post, fingerprint,
            spg_key)


def precompile(cfg: RunConfig) -> None:
    """Compile every program a timed run of `cfg` can dispatch — init,
    the static epoch runner(s), and the dynamic tail runner — into the
    module-level caches, and seed the seconds-per-generation estimate.

    The engine only ever dispatches: cached_init, the static runner at
    power-of-two n_ep x migration_period (both budget-clamping paths
    quantize to that), and the dynamic tail runner — exactly the set
    built here.

    Fixed-wall-clock comparisons call this outside the budget so the
    timed run is measured like the reference binary: compiled ahead of
    time (mpicxx does its compiling before the race too)."""
    if cfg.backend == "cpu":
        jax.config.update("jax_platforms", "cpu")
    dcore.set_fetch_timeout(cfg.fetch_timeout)
    maybe_init_distributed(cfg)
    (problem, pa, mesh, n_islands, gacfg, gacfg_post, fingerprint,
     spg_key) = _setup(cfg)
    sig = _shape_sig(problem)
    donate = cfg.donate

    key = jax.random.key(0)
    # one subkey per warm-up program: the compile calls' outputs are
    # discarded, but reusing one key across consumers is exactly the
    # pattern tt-analyze TT401 bans — the lint gate runs over this file
    wk = jax.random.split(key, 6)
    gacfg_init = dataclasses.replace(gacfg, init_sweeps=0)
    state = cached_init(mesh, cfg.pop_size, gacfg_init,
                        n_islands)(pa, wk[0])
    jax.block_until_ready(state)
    # measure the endTry fetch cost (the packed single-round-trip
    # readback) so timed runs can reserve it out of the dispatch
    # budget. Measured TWICE, keeping the minimum: the first
    # device->host transfer in a process pays one-time tunnel/DMA
    # setup that inflated the reserve enough to swallow a whole 30 s
    # budget (the engine then stopped at t=1.7 s having done nothing —
    # round-4 probe regression)
    dts = []
    for _ in range(2):
        t0 = time.monotonic()
        _fetch_final(state, n_islands, cfg.pop_size)
        dts.append(time.monotonic() - t0)
    _FETCH_CACHE[(_mesh_key(mesh), sig, cfg.pop_size,
                  n_islands)] = min(dts)
    # phase-config -> warm-up state: the post phase may run a SMALLER
    # population (post_pop_size elite truncation); its programs must be
    # warmed with the shrunk shape, and the shrink program itself must
    # be compiled (it runs at the in-budget phase switch)
    state_for = {gacfg: state}
    if gacfg_post is not None:
        if gacfg_post.pop_size != gacfg.pop_size:
            shrink = cached_shrink_runner(
                mesh, gacfg.pop_size, gacfg_post.pop_size, n_islands)
            st_post = shrink(state)
            jax.block_until_ready(st_post)
            state_for[gacfg_post] = st_post
            # warm the SHRUNK-shape endTry fetch too: the final fetch of
            # a post_pop_size run uses the post population's shape, and
            # an unwarmed concat would pay its compile inside -t beyond
            # the measured reserve
            _fetch_final(st_post, n_islands, gacfg_post.pop_size)
        else:
            state_for[gacfg_post] = state
    # With a LAHC endgame the post phase never dispatches GA programs
    # (the engine enters the walker loop at the switch and consumes the
    # whole remaining budget there), so the post config's GA ladder /
    # polish / kick programs would be dead compiles — build the LAHC
    # programs instead (below) and keep the GA builds repair-only.
    post_ga = gacfg_post if cfg.post_lahc <= 0 else None
    if cfg.post_lahc > 0 and gacfg_post is not None:
        init_r, run_r, fin_r = cached_lahc_runners(
            mesh, gacfg_post, cfg.post_lahc, cfg.post_lahc_k, sig,
            n_islands, donate,
            with_moments=(cfg.trace_mode == "stats"))
        lkey = _lahc_key(mesh, gacfg_post, cfg.post_lahc,
                         cfg.post_lahc_k, fingerprint)
        # donating programs: state_for's entry is needed again below, so
        # init consumes a clone, and each later call consumes the
        # previous call's output (never a buffer donation already ate)
        ls1 = init_r(pa, _clone(state_for[gacfg_post]))
        ls1, stats0 = run_r(pa, wk[1], ls1, 64)     # compile
        # fences here MUST be data fetches, not block_until_ready: on
        # the tunneled device block_until_ready can acknowledge before
        # the computation completes (BASELINE.md round-5 fence audit),
        # and a near-zero probe timing would size the first endgame
        # chunk ~100x past the wall-clock budget
        _fetch(stats0)
        if lkey not in _LAHC_SPS_CACHE:
            t0 = time.monotonic()
            ls1, stats = run_r(pa, jax.random.key(1), ls1, 256)
            _fetch(stats)
            _LAHC_SPS_CACHE[lkey] = (time.monotonic() - t0) / 256
        _fetch(fin_r(ls1).penalty)
    # polish runners for BOTH phase configs: the init polish uses the
    # repair config's, the budget-tail polish (see _run_tries) uses the
    # ACTIVE phase's — and neither may compile inside a timed budget
    for g in ([gacfg] if post_ga is None else [gacfg, post_ga]):
        if gacfg.init_sweeps <= 0 and g.ls_mode != "sweep":
            continue
        g_spg_key = (_mesh_key(mesh), g, fingerprint)
        polish, pwarm = cached_polish_runner(
            mesh, g, sig, n_islands, donate,
            with_passes=(cfg.trace_mode == "stats"))
        # timing fences are data fetches of the stats output, not
        # block_until_ready, which can early-ack on the tunneled device
        # (BASELINE.md round-5 fence audit) — a near-zero sec/sweep
        # would size polish chunks past the budget
        st_p, pstats = polish(pa, wk[2], _clone(state_for[g]), 1)
        _fetch(pstats)
        if not pwarm or g_spg_key not in _SPS_CACHE:
            t0 = time.monotonic()
            _fetch(polish(pa, jax.random.key(1), st_p, 1)[1])
            sps = time.monotonic() - t0
            prev = _SPS_CACHE.get(g_spg_key)
            _SPS_CACHE[g_spg_key] = (sps if prev is None
                                     else 0.7 * sps + 0.3 * prev)
    # stall-kick program (worst-half reseed; dispatched by timed runs
    # when the post phase plateaus — must not compile mid-budget). Built
    # from the POST config: that is the phase it fires in, and the post
    # population may be the shrunk one
    if (cfg.kick_stall > 0 and post_ga is not None
            and post_ga.pop_size >= 2):
        kicker, _ = cached_kick_runner(mesh, post_ga, sig, n_islands,
                                       donate)
        jax.block_until_ready(
            kicker(pa, wk[3], _clone(state_for[post_ga]), 3))
    # static dispatches always run gens = migration_period (shorter
    # remainders go through the dynamic runner), at pow2 n_ep; compile
    # exactly those — for BOTH phase configs when a post-feasibility
    # switch is configured (the switch must not compile mid-budget)
    gens = cfg.migration_period
    max_ep = (_pow2_floor(max(cfg.epochs_per_dispatch, 1))
              if cfg.generations >= cfg.migration_period else 0)
    for g in ([gacfg] if post_ga is None else [gacfg, post_ga]):
        g_spg_key = (_mesh_key(mesh), g, fingerprint)
        # the warm-up chain consumes a clone (donating runners delete
        # their inputs; state_for[g] may be shared with other warm-ups),
        # then each call feeds on the previous call's returned state
        g_state = _clone(state_for[g])
        # dynamic runner FIRST: one generation is the smallest dispatch
        # the engine can make, so it doubles as the safe sec/gen probe
        # for configs whose FULL epoch would outrun the watchdog (a
        # deep post config at a long migration_period — e.g. p3 sweeps
        # at migration_period 10 — dies inside even the n_ep=1 static
        # shape; executing that shape to measure it is the bug)
        dyn, _ = cached_dynamic_runner(mesh, g, cfg.migration_period,
                                       sig, n_islands, donate,
                                       cfg.trace_mode, cfg.quality)
        g_state, tr0, _ = dyn(pa, wk[4], g_state, 1)
        _fetch(tr0)
        spg_est = _SPG_CACHE.get(g_spg_key)
        if spg_est is None:
            t0 = time.monotonic()
            g_state, tr0, _ = dyn(pa, jax.random.key(1), g_state, 1)
            _fetch(tr0)
            # 1 generation + dispatch/migration overhead: an
            # OVERESTIMATE of sec/gen, used only to gate the static
            # builds below (conservative = never builds a shape the
            # watchdog would kill)
            spg_est = time.monotonic() - t0
        n_ep = 1
        max_built = 0
        while n_ep <= max_ep:
            if spg_est * gens * n_ep > DISPATCH_CAP_S:
                # a fused dispatch this large would risk the device's
                # long-kernel watchdog — don't even build the shape
                break
            runner, warm = cached_runner(mesh, g, n_ep, gens, sig,
                                         n_islands, donate,
                                         cfg.trace_mode, cfg.quality)
            g_state, tr2, _ = runner(pa, wk[5], g_state)
            _fetch(tr2)
            if not warm:
                # the timing call MUST differ from the compile call:
                # tunneled devices deduplicate byte-identical repeat
                # computations (BASELINE.md methodology note), which once
                # made this measure ~2e-5 s/gen and let a 146 s dispatch
                # through a 60 s budget — so re-run with a different key
                t0 = time.monotonic()
                g_state, tr2, _ = runner(pa, jax.random.key(1), g_state)
                _fetch(tr2)
                spg = (time.monotonic() - t0) / (n_ep * gens)
                prev = _SPG_CACHE.get(g_spg_key)
                _SPG_CACHE[g_spg_key] = (spg if prev is None
                                         else 0.7 * spg + 0.3 * prev)
                spg_est = _SPG_CACHE[g_spg_key]
            max_built = n_ep
            n_ep *= 2
        if max_built == 0 and g_spg_key not in _SPG_CACHE:
            # even one epoch predicts over the cap: timed runs go
            # through the dynamic runner with capped generation counts,
            # which needs a sec/gen estimate — store the conservative
            # dyn-probe value (overhead fraction is negligible for
            # generations this heavy)
            _SPG_CACHE[g_spg_key] = spg_est
        if max_ep >= 1:
            # max_ep == 0 means the GENERATION BUDGET is below one
            # epoch (a smoke run), not that the watchdog refused static
            # shapes — recording 0 would force every later same-config
            # run in this process onto the dynamic runner
            _MAX_EP_CACHE[g_spg_key] = max_built


def run(cfg: RunConfig, out=None) -> int:
    """Execute the configured run; emit the JSONL protocol on `out`.

    Returns the global best reported evaluation (scv if feasible else
    hcv*1e6+scv), the quantity the reference's runEntry reports.
    """
    if cfg.backend == "cpu":
        jax.config.update("jax_platforms", "cpu")
    dcore.set_fetch_timeout(cfg.fetch_timeout)
    if cfg.ls_time_limit != 99999.0:
        # -l is formally retired on this path: the fixed-shape batched LS
        # is bounded by candidate count (-m maxSteps), not wall clock —
        # a deterministic budget where the reference's was temporal
        # (Solution.cpp:499). Warn instead of silently ignoring.
        print("warning: -l (LS time limit) is retired on the TPU path; "
              "the local search is bounded by -m (maxSteps) candidate "
              "evaluations instead", file=sys.stderr)

    maybe_init_distributed(cfg)

    # fault-injection plan (RunConfig.faults, falling back to the
    # TT_FAULTS env var) installed per run: invocation counters reset
    # here, so a plan's site indices are deterministic within one run.
    # Process coordinates first — `site@proc` scoping filters entries
    # at parse time, and parse needs to know which process this is
    # (faults.py is stdlib-only and cannot ask jax itself)
    faults.set_process(jax.process_index(), jax.process_count())
    faults.install(faults.active_spec(cfg.faults))

    # tt-accord: open the control side channel for this run (a
    # per-process no-op object single-process, the coordination-service
    # KV backend under a live coordinator). Installed module-globally
    # so dispatch_core.fetch guards its multi-host allgathers through
    # it; closed (heartbeat stopped, registry cleared) in the finally.
    channel = control_channel.install(
        control_channel.open_channel(cfg.accord, cfg.peer_timeout))

    # single-controller reporting: process 0 has the global view (every
    # island's solution records and the runEntry), so other processes
    # stay silent instead of duplicating the protocol — and must not
    # even OPEN -o (on a shared filesystem they would truncate the file
    # process 0 is writing)
    is_main = not (jax.process_count() > 1 and jax.process_index() != 0)
    close_out = False
    if not is_main:
        import io
        out = io.StringIO()
    elif out is None:
        if cfg.output:
            out = open(cfg.output, "w")
            close_out = True
        else:
            out = sys.stdout

    writer = None
    obs_srv = None
    mem_poller = None
    prof_cap = None
    hist_ring = None
    flight = None
    try:
        # all record emission (and checkpoint serialization, via
        # submit()) rides the background writer thread so the dispatch
        # loop never stalls on host I/O; close() drains the bounded
        # queue on clean exit AND on error, so `out` is complete the
        # moment run() returns or raises. On the error path a queued
        # telemetry failure must not REPLACE the run's own exception
        # (retry logic matches on the propagating error), so close()
        # only re-raises when nothing else is in flight.
        # tt-flight (obs/history.py + obs/flight.py): the history
        # sampler rides its own daemon thread whenever any obs surface
        # is on; the incident recorder tees the record stream (on the
        # WRITER thread — ingestion costs nothing on the dispatch
        # path) and dumps bundles from ITS own thread. Both are
        # die/hang-isolated (fault sites `history`/`flight_dump`) and
        # the JSONL stream is bit-identical with them on or off.
        from timetabling_ga_tpu.obs import flight as obs_flight
        hist_ring, flight, sink = obs_flight.wire(cfg, out,
                                                  process="engine")
        writer = jsonl.AsyncWriter(sink)
        # obs wiring: the span tracer emits through the SAME writer
        # (spans are telemetry; the writer thread serializes them), and
        # the registry's writer gauges re-bind to THIS run's writer —
        # pull gauges, sampled at snapshot time
        tracer = SpanTracer(writer, enabled=cfg.obs)
        if flight is not None:
            flight.bind_tracer(tracer)
            flight.start()
        obs_metrics.REGISTRY.gauge_fn("writer.queue_depth", writer.qsize)
        obs_metrics.REGISTRY.gauge_fn(
            "writer.records", lambda: writer.records_written)
        # cost observatory (obs/cost.py): compile accounting runs
        # always; costEntry record emission binds to THIS run's writer
        # only under --obs (the stream is identical either way —
        # costEntry is a timing record, and obs-off binds nothing)
        obs_cost.OBSERVATORY.bind(writer if cfg.obs else None,
                                  now=tracer.now)
        if (cfg.obs or cfg.obs_listen) and cfg.mem_poll_every > 0:
            # device memory telemetry OFF the dispatch path: its own
            # daemon thread samples memory_stats() (a host-sync hazard
            # anywhere near dispatch — tt-analyze TT603) into the
            # device.mem_* gauges /readyz reads
            mem_poller = obs_cost.MemPoller(
                obs_cost.jax_memory_stats_fn(),
                cfg.mem_poll_every).start()
        if cfg.profile_for > 0 or cfg.obs_listen:
            # on-demand profiler capture, driven from its own worker
            # thread; the dispatch loop only ticks a counter
            prof_cap = obs_cost.ProfileCapture(
                lambda d: jax.profiler.start_trace(d),
                jax.profiler.stop_trace,
                default_dir=cfg.profile_dir)
            # tt-prof: finished captures attribute themselves on the
            # capture worker — sidecar write, per-phase device-time
            # parse, prof.phase_seconds.* gauges, and (under --obs)
            # the profEntry record through THIS run's writer
            from timetabling_ga_tpu.obs import prof as obs_prof
            prof_cap.on_complete = obs_prof.capture_hook(
                writer if cfg.obs else None, now=tracer.now)
            if cfg.profile_for > 0:
                prof_cap.trigger(cfg.profile_for)
        if cfg.obs_listen:
            # the pull front (obs/http.py): /metrics OpenMetrics with
            # exemplars, /healthz probing THIS run's writer thread,
            # /readyz from registry state. Daemon-thread listener — it
            # shares nothing with the dispatch loop but the registry
            # lock, and it writes NO records (the JSONL stream is
            # identical with it on or off).
            from timetabling_ga_tpu.obs import http as obs_http
            obs_srv = obs_http.ObsServer(
                cfg.obs_listen,
                probes={"process": lambda: True,
                        "writer": writer.alive},
                profile=prof_cap, history=hist_ring).start()
        try:
            ret = _run_tries(cfg, writer, tracer, profiler=prof_cap)
        except BaseException:
            writer.close(raise_error=False)
            raise
        writer.close()
        return ret
    finally:
        if obs_srv is not None:
            obs_srv.close()
        if prof_cap is not None:
            prof_cap.close()
        if mem_poller is not None:
            mem_poller.close()
        # tt-flight teardown AFTER the listener (no handler may race a
        # closing ring) and BEFORE the fault-plan uninstall (the
        # recorder thread's sites must stay deterministic to the end)
        if flight is not None:
            flight.close()
        if hist_ring is not None:
            hist_ring.close()
        # unbind the observatory's costEntry emitter: the global must
        # not hold this run's writer (same rule as the pull gauges)
        obs_cost.OBSERVATORY.unbind()
        # unbind the writer pull gauges: the registry is process-global,
        # so a bound closure would keep THIS run's writer (and its
        # output stream) alive for the process lifetime. Freeze at the
        # final counts instead. (writer is None if AsyncWriter
        # construction itself failed — nothing was bound.)
        if writer is not None:
            obs_metrics.REGISTRY.freeze(
                "writer.records", writer.records_written)
            obs_metrics.REGISTRY.freeze("writer.queue_depth", 0.0)
        # stop the accord heartbeat and clear the channel registry:
        # peers observing this process between runs must see silence,
        # not a stale beat, and later single-process work (precompile,
        # serve) must not guard through a dead channel
        if channel is not None:
            channel.close()
        control_channel.install(None)
        # uninstall the fault plan: leftover unfired entries must not
        # ambush later non-run code (precompile, direct checkpoint
        # saves, other writers) outside any supervised region. Triggered
        # counts roll into the process total first (see faults.install).
        faults.install(None)
        if close_out:
            out.close()


def _phase(out, enabled: bool, name: str, trial: int, seconds: float,
           **extra) -> None:
    if enabled:
        jsonl.phase_record(out, name, trial, seconds, **extra)


def _polish_chunks(out, cfg, pa, polish, state, base_key, t_try, reserve,
                   sec_per_sweep, n_islands, best_seen, emitted, trial,
                   phase_name, max_sweeps, sideways, warm,
                   sps_cache_key=None, tracer=NULL_TRACER):
    """Budget-aware chunked polish loop, shared by the initial-population
    polish (ga.cpp:429-434 analogue) and the budget-tail polish. Chunks
    of up to 4 runtime-counted sweep passes are dispatched while (a) the
    pass budget `max_sweeps` (None = unbounded) is not exhausted, (b)
    the next chunk is predicted to fit the remaining -t budget (1.25
    safety factor: a converge chunk's cost varies with how many passes
    actually run, and an underestimate is a budget overshoot), and (c)
    the population keeps improving — the penalty-sum stall rule: with
    sideways acceptance a flat chunk may be a plateau walk rather than
    the fixed point, so two flat chunks conclude convergence; without
    it one does.

    Every chunk costs ONE stacked (pen, hcv, scv) host fetch (separate
    fetches are multi-second round trips on tunneled devices, VERDICT
    round-3 weak #3), feeds new bests into the logEntry stream
    (feasibility reached during a polish must be visible to
    time-to-feasible measurement; the reference logs its init-LS bests
    the same way, ga.cpp:203-228), and re-estimates sec-per-sweep by
    EWMA. The estimate is written back to _SPS_CACHE only when
    `sps_cache_key` is given AND the chunk ran warm: the init polish
    owns the cache entry, while tail-polish timings of converged
    populations early-exit and would deflate it ~4x, poisoning later
    runs' budget decisions. Multi-host: chunk sizes go through
    _sync_vals so every process dispatches the same schedule.

    Returns (state, sec_per_sweep)."""
    done = 0
    prev_sum = None
    stalls = 0
    while max_sweeps is None or done < max_sweeps:
        remaining_t = (cfg.time_limit - reserve
                       - (time.monotonic() - t_try))
        chunk = 4 if max_sweeps is None else min(4, max_sweeps - done)
        if sec_per_sweep is not None and sec_per_sweep > 0:
            fit = int(remaining_t / (1.25 * sec_per_sweep))
            chunk = 0 if fit < 1 else min(chunk, fit)
        elif remaining_t <= 0:
            chunk = 0
        else:
            # no sec/sweep estimate yet: cap the unpredicted chunk at 1
            # pass (mirroring precompile's single-pass probe) so a deep
            # converge chunk at comp scale cannot overshoot -t before
            # the first measurement exists (ADVICE round 4)
            chunk = min(chunk, 1)
        chunk, = _sync_vals(chunk)
        if chunk < 1:
            break
        tp0 = time.monotonic()
        faults.maybe_fail("dispatch")
        state, stats = polish(pa, jax.random.fold_in(base_key, done),
                              state, chunk)
        stats = _fetch(stats)
        tp1 = time.monotonic()
        _phase(out, cfg.trace, phase_name, trial, tp1 - tp0, sweeps=chunk)
        tracer.record(phase_name, tp0, tp1 - tp0, cat="device",
                      sweeps=chunk)
        if stats.shape[0] > 3:
            # --trace-mode stats: row 3 is the per-device executed
            # sweep-pass count (islands.make_polish_runner with_passes)
            # broadcast across its shard columns — the on-device
            # convergence signal. Record the slowest device's count and
            # slice the extras off before the (3, ...) protocol reads.
            obs_metrics.REGISTRY.gauge("engine.polish_passes").set(
                int(stats[3].max()))
            if stats.shape[0] >= 4 + islands.TRACE_N_MOMENTS:
                # rows 4.. are bitcast float32 population moments
                # (mean/var/min/max of reported values per device) —
                # the polish/tail-polish endgame's stats-mode telemetry
                mom = np.ascontiguousarray(
                    stats[4:4 + islands.TRACE_N_MOMENTS]
                ).view(np.float32)
                reg = obs_metrics.REGISTRY
                reg.gauge("engine.polish_best_mean").set(
                    float(mom[0].mean()))
                reg.gauge("engine.polish_best_min").set(
                    float(mom[2].min()))
                reg.gauge("engine.polish_best_max").set(
                    float(mom[3].max()))
            stats = stats[:3]
        if warm:
            sps = (tp1 - tp0) / chunk
            sec_per_sweep = (sps if sec_per_sweep is None
                             else 0.7 * sps + 0.3 * sec_per_sweep)
            if sps_cache_key is not None:
                _SPS_CACHE[sps_cache_key] = sec_per_sweep
        warm = True
        done += chunk
        hcv_a = stats[1].reshape(n_islands, -1)
        scv_a = stats[2].reshape(n_islands, -1)
        for i in range(n_islands):
            rep = jsonl.reported_best(hcv_a[i, 0], scv_a[i, 0])
            if rep < best_seen[i]:
                best_seen[i] = rep
            if rep < emitted[i]:
                emitted[i] = rep
                jsonl.log_entry(out, i, 0, rep, tp1 - t_try)
        cur_sum = int(stats[0].astype(np.int64).sum())
        if prev_sum is not None and cur_sum >= prev_sum:
            stalls += 1
            if stalls >= 2 or sideways == 0.0:
                break
        else:
            stalls = 0
        prev_sum = cur_sum
    return state, sec_per_sweep


def _lahc_loop(out, cfg, pa, mesh, state, base_key, t_try, reserve,
               n_islands, best_seen, emitted, trial, gacfg_post, sig,
               fingerprint, tracer=NULL_TRACER):
    """Late-Acceptance Hill Climbing endgame (--post-lahc): consume the
    try's remaining wall-clock budget with LAHC walker chunks, then
    return the best snapshots as a PopState for the endTry fetch.

    Entered at the post-feasibility phase switch in place of the GA
    generation loop: each elite row (after the post_pop_size shrink)
    becomes an independent walker (ops/lahc.py). Chunks are sized from
    the measured sec/step like every other dispatch (DISPATCH_CAP_S +
    remaining-budget bound, schedule agreed across hosts via
    _sync_vals); each chunk costs ONE (3, n_islands) stats fetch that
    feeds the logEntry stream. No stall rule: late acceptance is the
    diversification — a flat chunk means the history ring is still
    draining, not a fixed point (the reference's phase-2 analogue is
    running its scv walk until the clock, Solution.cpp:499/619-768)."""
    init_r, run_r, fin_r = cached_lahc_runners(
        mesh, gacfg_post, cfg.post_lahc, cfg.post_lahc_k, sig,
        n_islands, cfg.donate,
        with_moments=(cfg.trace_mode == "stats"))
    lkey = _lahc_key(mesh, gacfg_post, cfg.post_lahc, cfg.post_lahc_k,
                     fingerprint)
    lstate = init_r(pa, state)
    sec_per_step = _LAHC_SPS_CACHE.get(lkey)
    # no cached estimate means precompile never probed this program, so
    # the first chunk pays its XLA compile — discard that chunk's timing
    # entirely (the _polish_chunks warm rule): recording it would poison
    # the persisted estimate and shrink every later chunk to overhead-
    # dominated slivers
    warm = sec_per_step is not None
    it = 0
    while True:
        remaining_t = (cfg.time_limit - reserve
                       - (time.monotonic() - t_try))
        if sec_per_step is not None and sec_per_step > 0:
            n = int(min(remaining_t / 1.1, DISPATCH_CAP_S)
                    / sec_per_step)
        else:
            # no estimate (--no-precompile): a small probe chunk, whose
            # own timing seeds the estimate for the next chunk
            n = 256 if remaining_t > 0 else 0
        n, = _sync_vals(n)
        if n < 1:
            break
        t0 = time.monotonic()
        faults.maybe_fail("dispatch")
        lstate, stats = run_r(pa, jax.random.fold_in(base_key, it),
                              lstate, n)
        stats = _fetch(stats)              # blocks on the dispatch
        dt = time.monotonic() - t0
        _phase(out, cfg.trace, "lahc", trial, dt, steps=n)
        tracer.record("lahc", t0, dt, cat="device", steps=n)
        if stats.shape[0] > 3:
            # --trace-mode stats: rows 3.. are bitcast float32 walker-
            # ensemble moments (mean/var/min/max of best-so-far reported
            # values per island — islands.make_lahc_runners
            # with_moments). The endgame stops being a telemetry blind
            # spot: the gauges move every chunk, and the (3, ...) rows
            # the protocol reads are untouched.
            mom = np.ascontiguousarray(
                stats[3:3 + islands.TRACE_N_MOMENTS]).view(np.float32)
            mreg = obs_metrics.REGISTRY
            mreg.gauge("engine.lahc_best_mean").set(float(mom[0].mean()))
            mreg.gauge("engine.lahc_best_min").set(float(mom[2].min()))
            mreg.gauge("engine.lahc_best_max").set(float(mom[3].max()))
            stats = stats[:3]
        if warm:
            sps = dt / n
            sec_per_step = (sps if sec_per_step is None
                            else 0.7 * sps + 0.3 * sec_per_step)
            _LAHC_SPS_CACHE[lkey] = sec_per_step
        warm = True
        for i in range(n_islands):
            rep = jsonl.reported_best(stats[1][i], stats[2][i])
            if rep < best_seen[i]:
                best_seen[i] = rep
            if rep < emitted[i]:
                emitted[i] = rep
                jsonl.log_entry(out, i, 0, rep,
                                time.monotonic() - t_try)
        it += 1
    state = fin_r(lstate)
    _fetch(state.penalty)      # real fence (block_until_ready early-acks)
    return state


def _run_tries(cfg: RunConfig, out, tracer=NULL_TRACER,
               profiler=None) -> int:
    t0 = time.monotonic()
    mreg = obs_metrics.REGISTRY
    trace_mode = cfg.trace_mode
    # search-quality observatory (README "Search-quality observatory"):
    # the generation runners append the packed quality block to the
    # telemetry leaf, and the leaf's EVENT half uses the effective
    # packing (a full trace upgrades to deltas under quality —
    # islands.effective_trace_mode; the record stream is unchanged)
    quality = cfg.quality
    ev_mode = islands.effective_trace_mode(trace_mode, quality)
    # stats mode also rides the polish runner: one extra stats row
    # carries the executed sweep-pass count (the same single fetch)
    with_passes = trace_mode == "stats"
    # Runners come from the module-level compiled-program cache (keyed on
    # mesh + gacfg + dispatch shape), so repeated engine.run calls with
    # the same configuration — e.g. a warm-up run followed by a timed
    # race run — share one compilation. The per-generation time estimate
    # is keyed on the full config fingerprint (instance dims + breeding
    # params + island layout), so a measurement from one problem is never
    # trusted for a differently-shaped one.
    (problem, pa, mesh, n_islands, gacfg, gacfg_post, fingerprint,
     spg_key) = _setup(cfg)
    sig = _shape_sig(problem)
    # init runs WITHOUT the fused polish (init_sweeps=0): the polish is
    # dispatched in budget-aware chunks right after (see below)
    gacfg_init = dataclasses.replace(gacfg, init_sweeps=0)
    seed = cfg.resolved_seed()
    # -t must cover the endTry fetch too: reserve its measured cost out
    # of every dispatch-fitting decision (1.0 s prior when unmeasured).
    # Capped at a quarter of the budget: an implausibly large measured
    # reserve (first-fetch tunnel setup, transient stall) must degrade
    # to a bounded overshoot risk, not to the run doing NOTHING with
    # its budget
    reserve = _FETCH_CACHE.get(
        (_mesh_key(mesh), sig, cfg.pop_size, n_islands), 1.0)
    reserve = min(reserve, 0.25 * cfg.time_limit)
    _phase(out, cfg.trace, "load", 0, time.monotonic() - t0)

    global_best = INT_MAX
    # The reference's try loop is legacy Control behavior (Control.cpp:
    # 188-246) unused by the MPI binary; we honor -n but default it to 1.
    for trial in range(cfg.tries):
        t_try = time.monotonic()   # per-try clock (beginTry, ga.cpp:163)
        key = jax.random.key(seed + trial)
        # k_init and k_polish are SEPARATE subkeys: init folds island
        # indices into its key and the polish loop folds chunk offsets
        # into its key, so sharing one key makes fold_in(k, island=0)
        # collide with fold_in(k, done=0) — correlated streams
        # (tt-analyze TT401 caught the original shared-key version)
        k_init, k_polish, key = jax.random.split(key, 3)

        gens_done = 0
        best_seen = None
        state = None
        host_loaded = None     # host copy for the supervisor's snapshot
        if cfg.resume and cfg.checkpoint:
            try:
                state, key, gens_done, best_seen, saved_seed = ckpt.load(
                    cfg.checkpoint, fingerprint)
                host_loaded = state
                state = _reshard_state(state, mesh)
                if saved_seed is not None:
                    if cfg.seed is not None and cfg.seed != saved_seed:
                        raise ValueError(
                            f"checkpoint was written with seed "
                            f"{saved_seed}, but -s {cfg.seed} given — "
                            f"refusing to mix RNG streams")
                    seed = saved_seed   # default seed adopts the saved one
            except FileNotFoundError:
                state = None
            # multi-host: every process must take the SAME resume path
            # (the loaded and fresh-init paths dispatch different
            # mesh-wide programs). A checkpoint visible to only some
            # processes (non-shared filesystem) must fail fast, not
            # deadlock at the first mismatched collective launch.
            loaded = int(state is not None)
            agreed, = _sync_vals(loaded)
            if agreed != loaded:
                raise RuntimeError(
                    "--resume: the checkpoint file is visible on some "
                    "processes but not others — multi-host resume needs "
                    "the checkpoint on a filesystem all hosts share")
        if best_seen is None:
            best_seen = [INT_MAX] * n_islands
        # the EMISSION floor: same values as best_seen except after a
        # supervisor recovery, where best_seen rewinds to the snapshot
        # (control replay) while emitted keeps the live stream's floor
        # (no duplicate logEntries) — see _process
        emitted = list(best_seen)
        if state is None:
            # SUPERVISED INIT (ROADMAP PR-3 follow-up): failures during
            # cached_init or the init polish happen BEFORE the first
            # supervisor snapshot exists, so the in-run recovery matrix
            # cannot cover them — instead of propagating, retry the
            # whole init a bounded number of times. Re-running with the
            # SAME k_init/k_polish reproduces the identical trajectory,
            # and the emitted floor keeps replayed polish bests from
            # re-emitting, so a recovered run's records match an
            # uninjected run's modulo timing and fault records (the
            # same determinism contract as the supervisor's;
            # tests/test_faults.py init-site tests pin it). Disabled
            # along with the rest of recovery at --max-recoveries 0.
            init_tries = 1 + (2 if cfg.max_recoveries > 0
                              and jax.process_count() == 1 else 0)
            for init_attempt in range(init_tries):
                try:
                    t = time.monotonic()
                    faults.maybe_fail("init")
                    # key reuse across retry ATTEMPTS is the point:
                    # the replayed init must reproduce the identical
                    # trajectory (determinism contract)
                    # tt-analyze: ignore[TT402]
                    state = cached_init(mesh, cfg.pop_size, gacfg_init,
                                        n_islands)(pa, k_init)
                    _fetch(state.penalty)   # real fence: the init phase
                    #                         record must not bleed into
                    #                         the polish bracket
                    #                         (block_until_ready early-
                    #                         acks on the tunnel)
                    _phase(out, cfg.trace, "init", trial,
                           time.monotonic() - t)
                    tracer.record("init", t, time.monotonic() - t,
                                  cat="device")
                    # Initial-population LS polish (ga.cpp:429-434),
                    # CHUNKED so the wall clock is checked between
                    # dispatches — one fused 30-pass converge polish at
                    # comp scale can otherwise eat a whole budget in a
                    # single unboundable dispatch. The runner takes the
                    # sweep count at runtime (one compile, any chunk);
                    # the loop stops at the pass budget, at the
                    # population-wide fixed point (penalty sum stops
                    # dropping — convergence inside a chunk implies the
                    # next chunk is a no-op), or when the next chunk is
                    # predicted not to fit the time budget.
                    if gacfg.init_sweeps > 0:
                        polish, pwarm = cached_polish_runner(
                            mesh, gacfg, sig, n_islands, cfg.donate,
                            with_passes)
                        # same deliberate reuse as k_init above
                        # tt-analyze: ignore[TT402]
                        state, _ = _polish_chunks(
                            out, cfg, pa, polish, state, k_polish,
                            t_try, reserve, _SPS_CACHE.get(spg_key),
                            n_islands, best_seen, emitted, trial,
                            "polish", gacfg.init_sweeps,
                            gacfg.ls_sideways, pwarm,
                            sps_cache_key=spg_key, tracer=tracer)
                    break
                except Exception as e:
                    if (init_attempt + 1 >= init_tries
                            or not retry.is_transient(e)):
                        raise
                    jsonl.fault_entry(
                        out, getattr(e, "tt_site", "init"), "recover",
                        e, trial, init_attempt + 1, 0,
                        time.monotonic() - t_try, init=True)
                    # teardown mirrors the supervisor's: drop poisoned
                    # buffers, purge the mesh's compiled programs,
                    # rebuild, and re-place the problem data
                    islands.delete_state(state)
                    state = None
                    _purge_programs(mesh)
                    mesh = islands.make_mesh(min(n_islands,
                                                 len(jax.devices())))
                    pa = problem.device_arrays()

        epochs_done = 0
        epochs_at_ckpt = 0
        # two-phase breeding: `cur` starts as the repair config and
        # switches to gacfg_post at the first dispatch boundary after
        # the global best reaches feasibility (both programs are warm —
        # precompile builds them together)
        cur, cur_key = gacfg, spg_key
        lahc_done = False
        if (gacfg_post is not None
                and min(best_seen) < FEASIBLE_LIMIT):
            # feasibility already reached during the init polish
            cur = gacfg_post
            cur_key = (_mesh_key(mesh), cur, fingerprint)
            if cur.pop_size != gacfg.pop_size:
                # endgame elite truncation (post_pop_size); the shrink
                # program is precompiled and the decision derives from
                # best_seen — identical on every process
                state = cached_shrink_runner(
                    mesh, gacfg.pop_size, cur.pop_size, n_islands)(state)
            _phase(out, cfg.trace, "phase-switch", trial, 0.0, at_gen=0)
            if cfg.post_lahc > 0:
                key, k_lahc = jax.random.split(key)
                state = _lahc_loop(
                    out, cfg, pa, mesh, state, k_lahc, t_try, reserve,
                    n_islands, best_seen, emitted, trial, cur, sig,
                    fingerprint, tracer=tracer)
                lahc_done = True
        sec_per_gen = _spg_for(cur_key, cur, gacfg, spg_key)
        time_stopped = False
        # stall detector (quality observatory): fed once per retired
        # dispatch with (control best, most-collapsed island's Hamming
        # diversity); drives engine.stalled, the /readyz `stalled`
        # reason, faultEntry stall records, and --auto-kick-on-stall
        stall_det = None
        if quality and cfg.stall_window > 0:
            stall_det = obs_quality.StallDetector(cfg.stall_window,
                                                  cfg.stall_hamming)
        mreg.gauge("engine.stalled").set(0.0)
        kick_stall = 0
        kick_best = min(best_seen)
        kick_streak = 0     # kicks since the last improvement: each one
        #                     escalates the perturbation depth (3, 6,
        #                     12, 16 moves) — re-converging to the same
        #                     basin means the previous depth was too
        #                     shallow to escape it
        # the run supervisor: rolling host snapshot + recovery policy
        # (README "Fault tolerance"). The initial snapshot costs one
        # state fetch on the fresh-init path (a resume already holds
        # the host copy, as long as the init-time phase switch did not
        # reshape or advance the state); every later snapshot rides a
        # checkpoint fence for free.
        sup = _Supervisor(cfg)
        # readiness gauges (the pull front's /readyz derives NOT-READY
        # from these alone — obs/http.py readiness()): the ladder level
        # and the remaining recovery budget are registry state from the
        # first dispatch on
        mreg.gauge("engine.degrade_level").set(sup.level)
        mreg.gauge("engine.recovery_budget_configured").set(
            cfg.max_recoveries)
        mreg.gauge("engine.recovery_budget_remaining").set(
            cfg.max_recoveries)
        if sup.enabled:
            if (host_loaded is not None and cur is gacfg
                    and not lahc_done):
                host0 = host_loaded
            else:
                host0 = _fetch_state(state)
            sup.snapshot(state=host0, key=ckpt.key_data(key),
                         gens_done=gens_done, epochs_done=0,
                         epochs_at_ckpt=0, best_seen=list(best_seen),
                         post=(gacfg_post is not None
                               and cur is gacfg_post),
                         kick=(kick_stall, kick_best, kick_streak),
                         lahc_done=lahc_done)
        profiled = False
        # Depth-2 asynchronous dispatch pipeline (module docstring):
        # chunk N+1 is enqueued BEFORE chunk N's trace is fenced, and
        # chunk N's telemetry is processed while N+1 executes on the
        # device. Enabled only when every between-dispatch CONTROL read
        # is absent from the run:
        #   - a post config makes the phase switch (and the stall kick)
        #     read chunk N's trace before choosing chunk N+1's PROGRAM;
        #   - multi-host trace fetches ride a process_allgather
        #     collective that must not interleave with the next
        #     dispatch's collectives;
        #   - the profiler bracket is a measurement path (start/stop
        #     must tightly enclose exactly one dispatch).
        # Checkpoints do run pipelined: the snapshot fetch is its own
        # fence (it blocks on the in-flight chunk), and the npz
        # serialization rides the writer thread.
        # --auto-kick-on-stall makes the stall decision a CONTROL read
        # (it picks whether the next dispatch is a kick program), so it
        # serializes the loop exactly like a post config does; the
        # detector WITHOUT auto-kick is pure telemetry and pipelines
        pipelined_cfg = bool(cfg.pipeline and gacfg_post is None
                             and jax.process_count() == 1
                             and cfg.trace_profile is None
                             and not (quality
                                      and cfg.auto_kick_on_stall))
        n_dispatch = 0
        last_fence = None  # wall time of the previous chunk's fence
        host_gap_s = 0.0   # device-idle time between chunks (obs gauges
        #                    host_gap_ms_per_gen / device_busy_frac —
        #                    the numbers bench.py's pipeline A/B derives
        #                    offline, live)
        overflow_warned = False
        t_loop = time.monotonic()

        def _process(chunk, inflight=None):
            """Retire one dispatched chunk: fence its trace fetch, emit
            telemetry, update the sec/gen estimate, and run the control
            checks (phase switch / kick / checkpoint). Serial mode calls
            this immediately after the chunk's own dispatch — exactly
            the classic loop-body order; pipelined mode calls it with
            the NEXT chunk already enqueued (passed as `inflight`), so
            everything below overlaps device compute."""
            nonlocal state, key, cur, cur_key, sec_per_gen, lahc_done
            nonlocal kick_stall, kick_best, kick_streak, profiled
            nonlocal epochs_at_ckpt, last_fence, host_gap_s
            nonlocal overflow_warned
            (td0, n_ep, gens_run, dyn_gens, trace_dev, warm,
             do_prof, flow, chunk_cost) = chunk   # _Chunk fields
            tf0 = time.monotonic()
            trace = _fetch(trace_dev, tracer=tracer,
                           flow=flow or None)  # blocks on the dispatch
            td1 = time.monotonic()
            tracer.record("fetch", tf0, td1 - tf0, cat="engine",
                          gens=gens_run, flow=flow)
            if do_prof:
                jax.profiler.stop_trace()
                profiled = True
                _phase(out, True, "profile", trial, td1 - td0,
                       dir=cfg.trace_profile)
            # when this chunk actually STARTED on the device: in serial
            # mode its enqueue time; in pipelined mode the previous
            # chunk's fence (the device was still running chunk N-1 at
            # enqueue). Used for both the budget predictor's cost
            # (enqueue-to-fence in pipelined mode would span ~two
            # chunks and double the sec/gen estimate) and the logEntry
            # time interpolation (anchoring at enqueue would timestamp
            # bests up to one dispatch earlier than they occurred,
            # flattering time-to-feasible)
            t_start = (last_fence
                       if pipe.enabled and last_fence is not None
                       else td0)
            dt = td1 - t_start
            if last_fence is not None:
                # device-idle gap between the previous fence and this
                # chunk's enqueue (<= 0 pipelined: the next chunk was
                # already running) — the live form of bench.py's
                # pipeline-A/B host-gap metric
                host_gap_s += max(0.0, td0 - last_fence)
            last_fence = td1
            _phase(out, cfg.trace, "dispatch", trial, dt,
                   epochs=n_ep, gens=gens_run)
            tracer.record("dispatch", t_start, dt, cat="device",
                          epochs=n_ep, gens=gens_run, flow=flow)
            mreg.counter("engine.dispatches").inc()
            mreg.counter("engine.gens").inc(gens_run)
            # the exemplar joins a latency-histogram spike on the
            # scrape dashboard back to its dispatch ordinal (the
            # spanEntry/phase records carry the same index implicitly
            # via stream order)
            mreg.histogram("engine.dispatch_seconds").observe(
                dt, exemplar={"dispatch": str(n_dispatch)})
            if dt > 0:
                mreg.gauge("engine.gens_per_sec").set(gens_run / dt)
            # live roofline: the program's compile-time FLOP/byte
            # counts (obs/cost.py — free at compile, a recompile
            # hazard anywhere else: TT603) over the chunk's own
            # measured wall time — bench's kernel_cost placement,
            # per dispatch, while the run is still going
            obs_cost.set_live_roofline(chunk_cost, dt)
            loop_s = td1 - t_loop
            if loop_s > 0:
                mreg.gauge("engine.device_busy_frac").set(
                    max(0.0, 1.0 - host_gap_s / loop_s))
            if gens_done > 0:
                mreg.gauge("engine.host_gap_ms_per_gen").set(
                    1e3 * host_gap_s / gens_done)
            if warm and (gens_run >= cfg.migration_period or dt >= 5.0):
                # compiling dispatches are excluded: compile time would
                # inflate the estimate, and the poisoned value would both
                # end this run early and persist into later runs. Tiny
                # dynamic tails are excluded too: their wall time is
                # dominated by fixed dispatch/migration/fetch overhead,
                # which would inflate the per-generation estimate — but
                # a dispatch that ran >= 5 s is overhead-free enough to
                # measure REGARDLESS of generation count, which is the
                # only feedback path in the watchdog-capped dyn regime
                # (gens_run < migration_period on every dispatch there;
                # without this the run would trust the one-generation
                # precompile probe forever, and generation cost is
                # data-dependent)
                spg = dt / gens_run
                sec_per_gen = (spg if sec_per_gen is None
                               else 0.7 * spg + 0.3 * sec_per_gen)
                _SPG_CACHE[cur_key] = sec_per_gen

            # per-generation logEntry emission from the device-side
            # trace — pure telemetry (writes ride the writer thread).
            # best_seen is the CONTROL floor (phase switch, kick,
            # checkpoint); emitted is the EMISSION floor. They are
            # equal except after a recovery, where best_seen rewinds to
            # the snapshot (so replayed control decisions land at the
            # same generations as an uninjected run) while emitted
            # stays at the live stream's floor (so replayed chunks do
            # not re-emit records the pre-failure stream already has).
            # trace_mode full: events = every generation, the floors
            # select the improvements. deltas/stats: the device already
            # selected the dispatch-local improvements (gen indices ride
            # along), so the floors skip exactly what they would have
            # skipped on the full trace — the record stream is identical
            # across modes (tests/test_obs.py pins it).
            # the shared telemetry decode (dispatch_core): quality
            # split, dynamic-tail trim, event decode under the
            # effective packing, and on-device event-capacity overflow
            # surfacing — one implementation with the scheduler's park
            # path
            events, ev_moments, qrows, overflow_warned = \
                dcore.decode_telemetry(
                    trace, quality, trace_mode, metrics=mreg,
                    overflow_counter="engine.trace_delta_overflow",
                    overflow_warned=overflow_warned,
                    dyn_gens=dyn_gens)
            total = gens_run
            for i in range(n_islands):
                for g, h, s in events[i]:
                    rep = jsonl.reported_best(h, s)
                    if rep < best_seen[i]:
                        best_seen[i] = rep
                    if rep < emitted[i]:
                        emitted[i] = rep
                        tg = ((t_start - t_try)
                              + (g + 1) / total * (td1 - t_start))
                        jsonl.log_entry(out, i, 0, rep, tg)
            if ev_moments is not None:
                # streamed on-device moments of the per-generation best
                # (stats mode): aggregate across islands into gauges
                mreg.gauge("engine.trace_best_mean").set(
                    float(ev_moments[:, 0].mean()))
                mreg.gauge("engine.trace_best_min").set(
                    float(ev_moments[:, 2].min()))
                mreg.gauge("engine.trace_best_max").set(
                    float(ev_moments[:, 3].max()))
            q_agg = None
            if qrows is not None:
                # search-quality telemetry: decode the packed block
                # (numpy only — quality accounting stays ON DEVICE,
                # tt-analyze TT604) into the quality.* namespace.
                # Counters carry per-dispatch deltas, gauges the
                # dispatch's cross-island diversity view; both land on
                # /metrics with everything else, and --obs additionally
                # emits the flat qualityEntry record.
                q_agg = obs_quality.aggregate(obs_quality.decode_rows(
                    qrows))
                for name, v in q_agg["counters"].items():
                    mreg.counter(name).inc(v)
                for name, v in q_agg["gauges"].items():
                    mreg.gauge(name).set(v)
                if cfg.obs:
                    jsonl.quality_entry(
                        out, obs_quality.entry_payload(q_agg),
                        ts=tracer.now(), dispatch=n_dispatch)
            tracer.record("process", td1, time.monotonic() - td1,
                          cat="engine", gens=gens_run, flow=flow)
            if profiler is not None:
                # tick the on-demand capture (a lock-guarded counter —
                # the jax.profiler start/stop happen on ITS worker, so
                # a hung capture can never stall this loop)
                profiler.on_dispatch()
            if (cfg.obs and cfg.metrics_every > 0
                    and n_dispatch % cfg.metrics_every == 0):
                jsonl.metrics_entry(out, mreg.snapshot(),
                                    ts=tracer.now())

            # post-feasibility switch (reference phase-2 analogue): a
            # CONTROL read — it picks the next dispatch's program — so
            # pipelining is off whenever a post config exists, and this
            # runs strictly between dispatches. The decision reads
            # best_seen, which every process derives from the same
            # allgathered trace — no divergence risk
            if (cur is gacfg and gacfg_post is not None
                    and min(best_seen) < FEASIBLE_LIMIT):
                cur = gacfg_post
                cur_key = (_mesh_key(mesh), cur, fingerprint)
                if cur.pop_size != gacfg.pop_size:
                    state = cached_shrink_runner(
                        mesh, gacfg.pop_size, cur.pop_size,
                        n_islands)(state)
                sec_per_gen = _spg_for(cur_key, cur, gacfg, spg_key)
                _phase(out, cfg.trace, "phase-switch", trial, 0.0,
                       at_gen=gens_done)
                if cfg.post_lahc > 0:
                    # the endgame leaves the GA entirely: the remaining
                    # budget belongs to the LAHC walkers; return (the
                    # classic loop's `break`) — no kick, no checkpoint
                    key, k_lahc = jax.random.split(key)
                    state = _lahc_loop(
                        out, cfg, pa, mesh, state, k_lahc, t_try,
                        reserve, n_islands, best_seen, emitted, trial,
                        cur, sig, fingerprint, tracer=tracer)
                    lahc_done = True
                    return

            def _dispatch_kick() -> int:
                """THE kick dispatch, shared by the post-phase stall
                kick and the quality auto-kick (they differ only in
                trigger condition and bookkeeping around this core):
                reseed the worst half from mutated elites at the
                escalating depth, fence, record, count. precompile
                builds the program for the post-phase path; under
                --no-precompile (or the auto-kick outside the post
                phase) the first kick pays its XLA compile inside -t
                like every other program in that mode. Returns the
                depth used."""
                nonlocal state, key, kick_streak
                kicker, _kwarm = cached_kick_runner(
                    mesh, cur, sig, n_islands, cfg.donate)
                n_moves = min(3 << kick_streak, islands.KICK_MAX_MOVES)
                key, k_kick = jax.random.split(key)
                t = time.monotonic()
                faults.maybe_fail("dispatch")
                state = kicker(pa, k_kick, state, n_moves)
                _fetch(state.penalty)   # real fence for the phase
                #                         record (see init above)
                # context key is at_gen, NOT gens: `gens` on a phase
                # record means generations EXECUTED by that phase
                # (budget accounting sums it)
                _phase(out, cfg.trace, "kick", trial,
                       time.monotonic() - t, at_gen=gens_done,
                       moves=n_moves)
                tracer.record("kick", t, time.monotonic() - t,
                              cat="device", moves=n_moves)
                mreg.counter("engine.kicks").inc()
                kick_streak += 1
                return n_moves

            # stall kick (VERDICT round-4 next #5): in the post phase —
            # the scv-polish endgame where small seed 43 sat pinned on a
            # plateau for its whole budget — count consecutive dispatches
            # with no new global best; at cfg.kick_stall of them, reseed
            # the worst half of every island from mutated copies of its
            # best (islands.make_kick_runner; the single-island analogue
            # of migration's diversity injection, ga.cpp:522-535).
            # Control, like the phase switch: post config => serial.
            if (cur is gacfg_post and cfg.kick_stall > 0
                    and cur.pop_size >= 2):
                nb = min(best_seen)
                if nb < kick_best:
                    kick_stall = 0
                    kick_streak = 0
                else:
                    kick_stall += 1
                kick_best = nb
                # the budget check keeps -t honest: a kick straight
                # after the final dispatch would otherwise run past the
                # limit. It reads the PROCESS-LOCAL clock, so the
                # mesh-wide launch decision goes through _sync_vals like
                # every other dispatch decision (best_seen alone is
                # process-identical; the clock is not).
                kick_fits = (cfg.time_limit - reserve
                             - (time.monotonic() - t_try)) > 0
                do_kick, = _sync_vals(
                    kick_stall >= cfg.kick_stall and kick_fits)
                if do_kick:
                    _dispatch_kick()
                    kick_stall = 0

            # stall detector (quality observatory): a plateau of
            # cfg.stall_window dispatches with the most-collapsed
            # island's Hamming diversity at/below cfg.stall_hamming is
            # a STALL — surfaced via the engine.stalled gauge (a
            # /readyz `stalled` reason, obs/http.py) and a faultEntry
            # record. --auto-kick-on-stall additionally fires the
            # existing kick path — a CONTROL decision, so pipelining
            # is off whenever the flag is set (see `pipelined`).
            if stall_det is not None and q_agg is not None:
                hmin = q_agg["gauges"]["quality.diversity.hamming_min"]
                was_stalled = stall_det.stalled
                stalled = stall_det.update(min(best_seen), hmin)
                mreg.gauge("engine.stalled").set(1.0 if stalled else 0.0)
                if stalled and not was_stalled:
                    jsonl.fault_entry(
                        out, "quality", "stall",
                        f"no new best for {stall_det.streak} dispatches "
                        f"with diversity {hmin:.4f} <= "
                        f"{cfg.stall_hamming}", trial, sup.recoveries,
                        sup.level, time.monotonic() - t_try,
                        streak=stall_det.streak, hamming=round(hmin, 6))
                if (stalled and cfg.auto_kick_on_stall
                        and cur.pop_size >= 2):
                    kick_fits = (cfg.time_limit - reserve
                                 - (time.monotonic() - t_try)) > 0
                    do_kick, = _sync_vals(kick_fits)
                    if do_kick:
                        n_moves = _dispatch_kick()
                        jsonl.fault_entry(
                            out, "quality", "kick", "stall auto-kick",
                            trial, sup.recoveries, sup.level,
                            time.monotonic() - t_try, moves=n_moves)
                        # the kick re-diversified the population: the
                        # stall evidence is stale, re-arm the window
                        stall_det.reset()
                        mreg.gauge("engine.stalled").set(0.0)

            if (cfg.checkpoint
                    and epochs_done - epochs_at_ckpt
                    >= cfg.checkpoint_every):
                t = time.monotonic()
                # CONTROL half, on this thread: snapshot the CURRENT
                # state to host memory — a real data fence (pipelined,
                # it blocks on the in-flight chunk, whose generations
                # gens_done already counts, so counter and state agree).
                # Multi-host, _fetch allgathers the GLOBAL population (a
                # collective — all processes must participate); the file
                # holds global state so a resume can re-shard it onto
                # any process layout (the reference's wire format
                # likewise serves all ranks, ga.cpp:264-368).
                # TELEMETRY half, on the writer thread: the npz
                # serialization + fsync + rename, ordered behind the
                # records already queued.
                host_state = _fetch_state(state)
                key_host = ckpt.key_data(key)
                bs = list(best_seen)
                tr_fold = None
                if inflight is not None:
                    # `state`/`gens_done` already cover the in-flight
                    # chunk, but best_seen only covers chunks this
                    # function has retired — saving the stale list
                    # would let a resume re-emit a best the pre-crash
                    # stream logged AFTER this checkpoint (non-monotone
                    # merged stream). Fold the in-flight chunk's trace
                    # into the SAVED copy (its fetch rides the same
                    # fence _fetch_state just paid); the live best_seen
                    # stays untouched so the chunk's logEntries still
                    # emit normally when it retires.
                    tr_in = _fetch(inflight.trace)
                    # the snapshot keeps the EVENT half only (the
                    # quality block is per-dispatch telemetry a replay
                    # would double-count)
                    tr_in, _ = islands.split_quality(tr_in, quality)
                    if (inflight.dyn_gens is not None
                            and ev_mode == "full"):
                        tr_in = tr_in[:, :, :inflight.dyn_gens]
                    ev_in, _, _ = islands.trace_events(tr_in, ev_mode)
                    for i in range(n_islands):
                        for _g, h, s in ev_in[i]:
                            bs[i] = min(bs[i],
                                        jsonl.reported_best(h, s))
                    tr_fold = tr_in
                ck_flow = tracer.new_flow()
                if jax.process_count() <= 1 or jax.process_index() == 0:
                    job = (lambda hs=host_state, kh=key_host,
                           gd=gens_done, bs=bs, sd=seed:
                           ckpt.save(cfg.checkpoint, hs, kh, gd,
                                     fingerprint, bs, sd))
                    submit = getattr(out, "submit", None)
                    if submit is not None:
                        # the WRITER-thread half of the checkpoint: the
                        # npz serialization runs as a queued job, and
                        # its span (emitted from the worker thread —
                        # jsonl.AsyncWriter.write's direct path) shares
                        # the checkpoint's flow id, so the enqueue→write
                        # handoff is one arrow in `tt trace`
                        def _ckpt_job(job=job, f=ck_flow, gd=gens_done):
                            with tracer.span("ckpt-write", cat="writer",
                                             flow=f, gens=gd):
                                job()
                        submit(_ckpt_job)
                    else:
                        job()
                epochs_at_ckpt = epochs_done
                # the supervisor's rolling snapshot rides the same
                # fence: host_state/key/gens_done cover the in-flight
                # chunk (and bs folds its bests), so a later recovery
                # resumes exactly where an uninjected run's dispatch
                # stream would be. tr_fold carries the in-flight
                # chunk's trace so its logEntries (not yet emitted)
                # can be emitted at recovery time.
                sup.snapshot(state=host_state, key=key_host,
                             gens_done=gens_done,
                             epochs_done=epochs_done,
                             epochs_at_ckpt=epochs_done,
                             best_seen=bs,
                             post=(gacfg_post is not None
                                   and cur is gacfg_post),
                             kick=(kick_stall, kick_best, kick_streak),
                             inflight_trace=tr_fold)
                _phase(out, cfg.trace, "checkpoint", trial,
                       time.monotonic() - t)
                tracer.record("checkpoint", t, time.monotonic() - t,
                              cat="engine", gens=gens_done, flow=ck_flow)
                mreg.counter("engine.checkpoints").inc()

        # the depth-2 pipeline discipline lives in the dispatch core;
        # `enabled` is toggled by the degradation ladder below and in
        # _process's t_start anchoring
        pipe = dcore.DispatchPipeline(_process, enabled=pipelined_cfg)

        # ---- supervised region (in-run fault recovery) ----------------
        # Everything from here to the endTry fetch can die of a
        # transient device failure (an UNAVAILABLE dispatch kill, a hung
        # control-fence fetch): the supervisor classifies the error over
        # its cause chain, tears down poisoned device state, re-resolves
        # the mesh, rehydrates from the rolling host snapshot, and
        # re-enters. The lost wall time stays on the trial clock, so -t
        # covers the whole try INCLUDING its failures.
        while True:
            try:
                while not lahc_done and gens_done < cfg.generations:
                    if (sup.enabled and sup.level > 0
                            and sup.maybe_relax(time.monotonic())):
                        # recovery ladder step-back-UP after a clean
                        # WINDOW_S stretch (carried ROADMAP item): the
                        # gauge moves first so /readyz's `degraded`
                        # reason clears LIVE, the faultEntry `restore`
                        # record makes the step auditable offline, and
                        # level 0 re-enables the configured pipelining
                        mreg.gauge("engine.degrade_level").set(
                            sup.level)
                        jsonl.fault_entry(
                            out, "run", "restore", "clean stretch",
                            trial, sup.recoveries, sup.level,
                            time.monotonic() - t_try,
                            mode=("pipelined" if sup.level == 0 else
                                  "serial" if sup.level == 1 else
                                  f"chunk-1/{2 ** (sup.level - 1)}"))
                        if sup.level < 1:
                            pipe.enabled = pipelined_cfg
                    if pipe.pending is not None and sec_per_gen is None:
                        # no cost estimate for the in-flight chunk (e.g.
                        # --no-precompile before the first warm measurement):
                        # enqueueing a SECOND unmeasured dispatch could overrun
                        # -t by two chunks where the serial loop risks one, so
                        # retire the in-flight chunk first — the loop runs
                        # serially until a measurable chunk seeds the estimate
                        pipe.drain()
                    remaining_t = (cfg.time_limit - reserve
                                   - (time.monotonic() - t_try))
                    if pipe.pending is not None and sec_per_gen is not None:
                        # an in-flight chunk consumes budget the clock has not
                        # charged yet: reserve its predicted cost before sizing
                        # the next dispatch (the pipelined analogue of the
                        # serial loop's between-dispatch clock check)
                        remaining_t -= sec_per_gen * pipe.pending.gens_run
                    stop = remaining_t <= 0
                    if (sec_per_gen is not None
                            and sec_per_gen > DISPATCH_CAP_S):
                        # even ONE generation predicts past the device watchdog
                        # (deep post configs at comp scale can get there):
                        # dispatching it risks a mid-try device kill the engine
                        # cannot retry. Stop the generation loop and spend the
                        # budget in the finer-grained sweep tail polish below
                        # (ADVICE round 4).
                        stop = True
                    remaining = cfg.generations - gens_done
                    dyn_gens = None
                    gens = cfg.migration_period
                    if remaining >= cfg.migration_period:
                        n_ep = max(1, min(cfg.epochs_per_dispatch,
                                          remaining // cfg.migration_period))
                        # quantize to a power of two: together with the dynamic
                        # tail below, the static runner then only ever compiles
                        # (pow2 n_ep, migration_period) shapes — the exact set
                        # precompile() builds
                        n_ep = _pow2_floor(n_ep)
                        # never exceed what precompile built under the
                        # long-kernel watchdog cap (DISPATCH_CAP_S), and bound
                        # the dispatch's PREDICTED wall time by the same cap —
                        # an over-long fused dispatch dies as a device error
                        cap_ep = _MAX_EP_CACHE.get(cur_key)
                        if cap_ep:
                            n_ep = min(n_ep, cap_ep)
                        if sec_per_gen is not None and sec_per_gen > 0:
                            fit_cap = int(DISPATCH_CAP_S / (sec_per_gen * gens))
                            n_ep = max(1, min(n_ep, _pow2_floor(max(1, fit_cap))))
                        if cap_ep == 0 or (
                                sec_per_gen is not None and sec_per_gen > 0
                                and sec_per_gen * gens > DISPATCH_CAP_S):
                            # even ONE epoch predicts over the watchdog cap
                            # (or precompile refused to build any static shape,
                            # cap_ep == 0): fall through to the dynamic runner
                            # with however many generations fit — migration
                            # then closes the shortened epoch, a cadence
                            # change, but the alternative is a dispatch the
                            # device may kill
                            n_ep = 1
                            dyn_gens = gens
                            if sec_per_gen is not None and sec_per_gen > 0:
                                dyn_gens = max(1, min(
                                    gens, int(DISPATCH_CAP_S / sec_per_gen)))
                    else:
                        # clamped final dispatch: fewer than migration_period
                        # generations left — served by the dynamic-gens runner
                        # (no fresh static shape, no new compile). The watchdog
                        # cap applies here too: a 40-generation tail at 1 s/gen
                        # would otherwise be one over-cap fused dispatch
                        n_ep, dyn_gens = 1, remaining
                        if sec_per_gen is not None and sec_per_gen > 0:
                            dyn_gens = max(1, min(
                                dyn_gens, int(DISPATCH_CAP_S / sec_per_gen)))
                    scale = sup.dispatch_scale()
                    if scale < 1.0:
                        # degradation ladder level >= 2: halve the dispatch
                        # chunk (per level) under the DISPATCH_CAP_S
                        # machinery's dynamic runner — smaller dispatches
                        # both finish under a sick device's watchdog and
                        # lose less replayed work per kill
                        n_ep = 1
                        base_g = dyn_gens if dyn_gens is not None else gens
                        dyn_gens = max(1, int(base_g * scale))
                    if not stop and sec_per_gen is not None and sec_per_gen > 0:
                        # -t must HOLD: launch only work predicted to fit the
                        # remaining budget (the reference checks its clock before
                        # every LS candidate, Solution.cpp:499; our granularity
                        # is one dispatch, so bound the dispatch instead). The
                        # time-clamped n_ep stays a power of two (at most
                        # log2(epochs_per_dispatch) static shapes); when less
                        # than one full epoch fits, the TAIL runs through the
                        # dynamic-gens runner, whose generation count is a
                        # runtime argument — one compile, any tail size — so the
                        # budget's last slice still does useful evolution instead
                        # of idling (VERDICT round-2 weak 3: 8-9s of a 60s budget
                        # went unused).
                        g_fit = int(remaining_t / sec_per_gen)
                        if g_fit < 1:
                            stop = True
                        elif dyn_gens is not None:
                            dyn_gens = min(dyn_gens, g_fit)
                        else:
                            fit_ep = g_fit // gens
                            if fit_ep < 1:
                                n_ep, dyn_gens = 1, min(g_fit, gens)
                            elif fit_ep < n_ep:
                                n_ep = _pow2_floor(fit_ep)
                    # multi-host: the dispatch schedule (stop / shape / size)
                    # must be identical on every process — process 0 decides
                    stop, is_dyn, n_ep, dg = _sync_vals(
                        stop, dyn_gens is not None, n_ep,
                        0 if dyn_gens is None else dyn_gens)
                    if stop:
                        time_stopped = True
                        break
                    dyn_gens = dg if is_dyn else None

                    key, k_epoch = jax.random.split(key)
                    if dyn_gens is not None:
                        runner, warm = cached_dynamic_runner(
                            mesh, cur, cfg.migration_period, sig, n_islands,
                            cfg.donate, trace_mode, quality)
                        args = (pa, k_epoch, state, dyn_gens)
                        gens_run = dyn_gens
                    else:
                        runner, warm = cached_runner(mesh, cur, n_ep, gens,
                                                     sig, n_islands, cfg.donate,
                                                     trace_mode, quality)
                        args = (pa, k_epoch, state)
                        gens_run = n_ep * gens
                    # fault-injection point (runtime/faults.py `dispatch`
                    # site): the supervised region's except clause is the
                    # consumer — an injected UNAVAILABLE here exercises
                    # the same classify/rehydrate/resume path a real
                    # mid-run device kill takes
                    faults.maybe_fail("dispatch")
                    # --trace-profile: capture ONE warm dispatch per try with
                    # jax.profiler (device kernel timeline; SURVEY section 5's
                    # tracing gap). Warm only — profiling a compiling dispatch
                    # would record XLA compilation, not the program
                    do_prof = (cfg.trace_profile is not None and not profiled
                               and warm)
                    if do_prof:
                        jax.profiler.start_trace(cfg.trace_profile)
                    # one flow id per chunk: its dispatch (this thread),
                    # fetch-read (the watchdog thread) and process spans
                    # render as one connected chain in `tt trace`
                    flow_id = tracer.new_flow()
                    td0 = time.monotonic()
                    state, trace_dev, _gbest = runner(*args)
                    # start the trace's device->host transfer WITHOUT fencing:
                    # the tiny telemetry leaf streams over while the host moves
                    # on; the real fence is _process's _fetch, where the data
                    # is actually read
                    try:
                        trace_dev.copy_to_host_async()
                    except (AttributeError, RuntimeError):
                        pass           # transfer then simply happens at _fetch
                    gens_done += gens_run
                    epochs_done += n_ep
                    n_dispatch += 1
                    # a compiling dispatch's wall time is compile +
                    # execute: feeding it to the roofline gauges would
                    # crater them on every cold dispatch, so the chunk
                    # carries no cost then (compile.seconds owns that
                    # time under its own name)
                    chunk = _Chunk(td0, n_ep, gens_run, dyn_gens, trace_dev,
                                   warm, do_prof, flow_id,
                                   None if getattr(runner, "last_compiled",
                                                   False)
                                   else getattr(runner, "last_cost", None))
                    # pipelined: retire the PREVIOUS chunk with this one
                    # already running — its telemetry cost hides behind
                    # device compute instead of serializing the dispatch
                    # stream (dispatch_core.DispatchPipeline)
                    pipe.submit(chunk)

                pipe.drain()           # retire the in-flight chunk
                _phase(out, cfg.trace, "gen-loop", trial,
                       time.monotonic() - t_loop, dispatches=n_dispatch,
                       pipelined=pipe.enabled)

                # BUDGET-TAIL POLISH: the generation loop stops when not even
                # one more generation fits, stranding up to sec_per_gen seconds
                # — multi-second for deep-children configs (measured: 8 s of a
                # 60 s comp05s race). Sweep passes are an order finer-grained,
                # so the stranded slice runs LS-only polish over the whole
                # population instead of idling. The reference spends its last
                # slice the same way: the per-candidate clock check means the
                # final moments are pure local search (Solution.cpp:499). Only
                # dispatched when the runner is already compiled (precompile
                # builds it for both phase configs) and a measured sec/sweep
                # says a chunk fits.
                sec_per_sweep = (_SPS_CACHE.get(cur_key)
                                 if cur.ls_mode == "sweep" and time_stopped
                                 else None)
                if sec_per_sweep is not None and sec_per_sweep > 0:
                    polish, pwarm = cached_polish_runner(mesh, cur, sig,
                                                         n_islands, cfg.donate,
                                                         with_passes)
                    if pwarm:   # never compile inside the budget
                        key, k_tail = jax.random.split(key)
                        # no sps_cache_key: tail timings of converged
                        # populations early-exit and would deflate the init
                        # polish's shared estimate (see _polish_chunks)
                        state, _ = _polish_chunks(
                            out, cfg, pa, polish, state, k_tail, t_try,
                            reserve, sec_per_sweep, n_islands, best_seen,
                            emitted, trial, "tail-polish", None,
                            cur.ls_sideways, True, tracer=tracer)

                # final per-island solution records (endTry, ga.cpp:169-197).
                # P is the ACTIVE phase's population (the post phase may have
                # shrunk it to the elite rows)
                t = time.monotonic()
                P = cur.pop_size
                slots, rooms, hcv, scv = _fetch_final(state, n_islands, P)
                _phase(out, cfg.trace, "fetch", trial, time.monotonic() - t)
                tracer.record("fetch", t, time.monotonic() - t,
                              cat="engine", endTry=True)
                break
            except control_channel.PeerLost as e:
                # a peer PROCESS is gone (heartbeat silent past
                # --peer-timeout): no rehydrate brings it back and the
                # collective program would hang at its next rendezvous
                # forever. Emit the abort faultEntry, leave a final
                # durable checkpoint from the snapshot (process 0
                # only — the single-controller write discipline: on a
                # shared filesystem N processes must not race the
                # rename), and propagate — a classified clean exit,
                # never a hang. The checkpoint state is global (the
                # snapshot rode the last checkpoint fence's
                # allgather), so the rerun resumes on any topology.
                jsonl.fault_entry(
                    out, "accord", "abort", e, trial, sup.recoveries,
                    sup.level, time.monotonic() - t_try,
                    proc=jax.process_index(), agreed=False,
                    lostProc=e.proc)
                if (cfg.checkpoint and sup.snap is not None
                        and jax.process_index() == 0):
                    try:
                        ckpt.save(cfg.checkpoint, sup.snap.state,
                                  sup.snap.key, sup.snap.gens_done,
                                  fingerprint, sup.snap.best_seen,
                                  seed)
                    except Exception as e3:
                        print(f"warning: final abort checkpoint "
                              f"failed: {e3}", file=sys.stderr)
                raise
            except Exception as e:
                site = sup.classify(e)
                if site is None:
                    raise
                now = time.monotonic()
                # tt-accord: BEFORE any process diverges from the
                # collective program order, all processes adopt one
                # verdict over the side channel — the process that saw
                # the real error contributes its site, a process that
                # merely observed the fault flag defers (site
                # 'accord'), and any budget-exhausted process forces
                # the agreed abort. Single-process runs skip this
                # entirely (no extra fields, byte-identical stream).
                agreed = None
                ch = control_channel.active()
                if jax.process_count() > 1 and ch is not None:
                    agreed = sup.agree_on_fault(ch, site, e)
                    site = agreed.get("site") or site
                acc = ({} if agreed is None else
                       {"proc": jax.process_index(), "agreed": True,
                        "decider": agreed["decider"]})
                sup.recoveries += 1
                mreg.gauge("engine.recovery_budget_remaining").set(
                    max(0, cfg.max_recoveries - sup.recoveries))
                if (sup.recoveries > cfg.max_recoveries
                        or (agreed is not None
                            and agreed.get("action") == "abort")):
                    # recovery budget exhausted (here or, under
                    # accord, on ANY process — abort wins the merge):
                    # emit the abort record, leave a final durable
                    # checkpoint from the snapshot, and let the error
                    # propagate — run()'s finally drains the writer,
                    # so the stream is complete up to and including
                    # this record
                    jsonl.fault_entry(
                        out, site, "abort", e, trial,
                        sup.recoveries - 1, sup.level, now - t_try,
                        **acc)
                    if cfg.checkpoint and (agreed is None
                                           or jax.process_index() == 0):
                        try:
                            ckpt.save(cfg.checkpoint, sup.snap.state,
                                      sup.snap.key, sup.snap.gens_done,
                                      fingerprint, sup.snap.best_seen,
                                      seed)
                        except Exception as e3:
                            print(f"warning: final abort checkpoint "
                                  f"failed: {e3}", file=sys.stderr)
                    raise
                mreg.counter("engine.recoveries").inc()
                t_rec = time.monotonic()
                snap = sup.snap
                if (agreed is not None
                        and int(agreed.get("gens", -1)) >= 0
                        and int(agreed["gens"]) != snap.gens_done):
                    # snapshots are taken at shared control fences, so
                    # the agreed resume chunk must equal this
                    # process's — a divergence means the fence
                    # discipline broke somewhere, and resuming anyway
                    # would corrupt the collective program. Fail loud,
                    # never hang.
                    raise RuntimeError(
                        f"accord: agreed resume generation "
                        f"{agreed['gens']} != this process's snapshot "
                        f"generation {snap.gens_done} — diverged "
                        f"snapshots; refusing to resume") from e
                jsonl.fault_entry(
                    out, site, "recover", e, trial, sup.recoveries,
                    sup.level, now - t_try,
                    lostGens=max(0, gens_done - snap.gens_done), **acc)
                if sup.escalate(now):
                    # repeated failures inside the window: step the
                    # degradation ladder (1 = serial, >= 2 = halved
                    # dispatch chunks) and record the step
                    mreg.gauge("engine.degrade_level").set(sup.level)
                    jsonl.fault_entry(
                        out, site, "degrade", e, trial, sup.recoveries,
                        sup.level, now - t_try,
                        mode=("serial" if sup.level == 1 else
                              f"chunk-1/{2 ** (sup.level - 1)}"))
                if sup.level >= 1:
                    pipe.enabled = False
                # teardown: the failed dispatch may have donated (and
                # deleted) buffers, and whatever survives is in an
                # unknown state — drop it all, rebuild the mesh, purge
                # the compiled programs bound to it
                islands.delete_state(state)
                lost = pipe.abandon()
                if lost is not None:
                    islands.delete_state(lost.trace)
                _purge_programs(mesh)
                mesh = islands.make_mesh(min(n_islands,
                                             len(jax.devices())))
                pa = problem.device_arrays()
                try:
                    state = _reshard_state(snap.state, mesh)
                    _fetch(state.penalty)   # placement must prove
                    #                         itself NOW, not at the
                    #                         next dispatch
                except Exception as e2:
                    # the snapshot could not be re-placed (the device
                    # rejected it — "device-poisoned" snapshot): last
                    # resort is the durable checkpoint on disk
                    if not cfg.checkpoint:
                        raise
                    print(f"warning: snapshot rehydration failed "
                          f"({str(e2)[:120]}); falling back to the "
                          f"durable checkpoint", file=sys.stderr)
                    st2, k2, g2, b2, _s2 = ckpt.load(cfg.checkpoint,
                                                     fingerprint)
                    b2 = b2 if b2 is not None else [INT_MAX] * n_islands
                    mp = max(1, cfg.migration_period)
                    snap = _Snapshot(
                        state=st2, key=ckpt.key_data(k2), gens_done=g2,
                        epochs_done=g2 // mp, epochs_at_ckpt=g2 // mp,
                        best_seen=list(b2),
                        post=(gacfg_post is not None
                              and min(b2) < FEASIBLE_LIMIT),
                        kick=(0, min(b2), 0))
                    sup.snap = snap
                    state = _reshard_state(snap.state, mesh)
                    _fetch(state.penalty)
                # rehydrate the control-plane locals from the snapshot:
                # replayed control decisions then land at the same
                # generation counts as an uninjected run's
                key = jax.random.wrap_key_data(np.asarray(snap.key))
                gens_done = snap.gens_done
                epochs_done = snap.epochs_done
                epochs_at_ckpt = snap.epochs_at_ckpt
                best_seen[:] = list(snap.best_seen)
                cur = gacfg_post if snap.post else gacfg
                cur_key = (spg_key if cur is gacfg
                           else (_mesh_key(mesh), cur, fingerprint))
                sec_per_gen = _spg_for(cur_key, cur, gacfg, spg_key)
                kick_stall, kick_best, kick_streak = snap.kick
                lahc_done = snap.lahc_done
                time_stopped = False
                last_fence = None
                if snap.inflight_trace is not None:
                    # the snapshot covers a chunk whose logEntries were
                    # never emitted (it was in flight at the checkpoint
                    # fence): emit them now, in stream order, before
                    # resuming — emitted-floor gating keeps records the
                    # pre-failure stream already carries from repeating
                    ev_fl, _, _ = islands.trace_events(
                        snap.inflight_trace, ev_mode)
                    tnow = time.monotonic() - t_try
                    for i in range(n_islands):
                        for _g, h, s in ev_fl[i]:
                            rep = jsonl.reported_best(h, s)
                            if rep < best_seen[i]:
                                best_seen[i] = rep
                            if rep < emitted[i]:
                                emitted[i] = rep
                                jsonl.log_entry(out, i, 0, rep, tnow)
                tracer.record("recover", t_rec,
                              time.monotonic() - t_rec, cat="engine",
                              site=site, level=sup.level)
        total_time = time.monotonic() - t_try
        for i in range(n_islands):
            feas = hcv[i] == 0
            rep = jsonl.reported_best(hcv[i], scv[i])
            jsonl.solution_record(
                out, i, 0, total_time, rep, feas,
                timeslots=slots[i, 0].tolist() if feas else None,
                rooms=rooms[i, 0].tolist() if feas else None)

        # cluster-level best (setGlobalCost's Allreduce MIN, ga.cpp:
        # 234-257): first runEntry line
        trial_best = min(jsonl.reported_best(hcv[i], scv[i])
                         for i in range(n_islands))
        feasible = bool((hcv == 0).any())
        jsonl.run_entry(out, trial_best, feasible)
        # final runEntry with procsNum/threadsNum/totalTime appended
        # (ga.cpp:604-607)
        jsonl.run_entry(out, trial_best, feasible,
                        procs_num=n_islands, threads_num=cfg.threads,
                        total_time=total_time)
        if cfg.obs:
            # end-of-try registry snapshot: the last metricsEntry of a
            # try always reflects its final counter state
            jsonl.metrics_entry(out, mreg.snapshot(), ts=tracer.now())
        global_best = min(global_best, trial_best)

    return global_best
