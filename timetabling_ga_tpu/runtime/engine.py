"""The run engine: host orchestration of the island GA.

The TPU-native re-design of ga.cpp main() (ga.cpp:370-613). Where the
reference interleaves MPI bootstrap, OpenMP breeding loops and ad-hoc
logging in one function, the engine is a host loop over *dispatches*: each
dispatch is one fully on-device jit call covering one or more epochs
(migration_period generations per island + ring migration each, see
parallel/islands.py). The runner returns a per-GENERATION (hcv, scv) best
trace per island, so the JSONL logEntry protocol sees every mid-epoch
improvement (ga.cpp:203-228 granularity) while the host reads back exactly
one array per dispatch — no per-epoch scalar fetches (they cost seconds on
tunneled devices; BASELINE.md methodology note).

Timing semantics (Control/Timer parity):
  - the wall-clock bound -t applies per try, reset at the top of each
    trial (beginTry/resetTime, ga.cpp:163-167; Control.cpp:62-68);
  - the generation budget is exact: the final dispatch is clamped to the
    remaining generations instead of overshooting to a multiple of
    migration_period;
  - logEntry times are interpolated linearly across a dispatch's wall
    time (generations inside one dispatch are not individually host-
    timestampable; the interpolation error is bounded by one dispatch).

Observability (--trace, SURVEY section 5): per-phase host timings
(init / dispatch / fetch / checkpoint) bracketed by block_until_ready are
emitted as {"phase": ...} JSONL records — an extension record type; the
reference protocol's three record types are unchanged and remain
byte-compatible.
"""

from __future__ import annotations

import sys
import time

import jax
import numpy as np

from timetabling_ga_tpu.ops import ga
from timetabling_ga_tpu.parallel import islands
from timetabling_ga_tpu.problem import load_tim_file
from timetabling_ga_tpu.runtime import checkpoint as ckpt
from timetabling_ga_tpu.runtime import jsonl
from timetabling_ga_tpu.runtime.config import RunConfig

INT_MAX = 2 ** 31 - 1

# Compiled-program caches, shared across engine.run calls. A jitted
# island runner costs seconds to tens of seconds to compile at race
# scale; rebuilding it per run (as round 2 did, with a run-local dict)
# made every timed run recompile inside its own wall-clock budget even
# after a warm-up run with identical shapes. Keyed on the mesh's device
# identity plus every static that changes the traced program.
_RUNNER_CACHE: dict = {}
_INIT_CACHE: dict = {}


def _mesh_key(mesh):
    return tuple((d.platform, d.id) for d in mesh.devices.flat)


def cached_runner(mesh, gacfg: ga.GAConfig, n_epochs: int, gens: int):
    """Returns (runner, was_cached). was_cached=False means this runner
    object is fresh, so its first call will pay an XLA compile."""
    k = (_mesh_key(mesh), gacfg, n_epochs, gens)
    r = _RUNNER_CACHE.get(k)
    if r is not None:
        return r, True
    r = islands.make_island_runner(mesh, gacfg, n_epochs=n_epochs,
                                   gens_per_epoch=gens)
    _RUNNER_CACHE[k] = r
    return r, False


def cached_init(mesh, pop_size: int, gacfg: ga.GAConfig):
    k = (_mesh_key(mesh), pop_size, gacfg)
    f = _INIT_CACHE.get(k)
    if f is None:
        f = jax.jit(lambda pa, key: islands.init_island_population(
            pa, key, mesh, pop_size, gacfg))
        _INIT_CACHE[k] = f
    return f


# Measured seconds-per-generation, persisted across engine.run calls with
# the same (mesh, config, problem shape) so a warm-up run's measurement
# bounds even the FIRST dispatch of a later timed run.
_SPG_CACHE: dict = {}


def build_ga_config(cfg: RunConfig) -> ga.GAConfig:
    """Map run flags to breeding hyper-parameters.

    The reference's LS budget counts candidate evaluations
    (stepCount, Solution.cpp:471-769); one of our LS rounds evaluates
    `ls_candidates` candidates, so rounds = maxSteps / ls_candidates keeps
    the candidate budget comparable."""
    max_steps = cfg.resolved_max_steps()
    ls_rounds = max(1, max_steps // cfg.ls_candidates)
    return ga.GAConfig(
        pop_size=cfg.pop_size,
        p1=cfg.p1, p2=cfg.p2, p3=cfg.p3,
        ls_steps=ls_rounds, ls_candidates=cfg.ls_candidates,
        ls_delta=not cfg.ls_full_eval,
        ls_mode=cfg.ls_mode, ls_sweeps=cfg.ls_sweeps,
        ls_swap_block=cfg.ls_swap_block,
        ls_converge=cfg.ls_converge, init_sweeps=cfg.init_sweeps,
        rooms_mode=cfg.rooms_mode,
        multi_objective=cfg.nsga2,
    )


def run(cfg: RunConfig, out=None) -> int:
    """Execute the configured run; emit the JSONL protocol on `out`.

    Returns the global best reported evaluation (scv if feasible else
    hcv*1e6+scv), the quantity the reference's runEntry reports.
    """
    if cfg.backend == "cpu":
        jax.config.update("jax_platforms", "cpu")
    if cfg.ls_time_limit != 99999.0:
        # -l is formally retired on this path: the fixed-shape batched LS
        # is bounded by candidate count (-m maxSteps), not wall clock —
        # a deterministic budget where the reference's was temporal
        # (Solution.cpp:499). Warn instead of silently ignoring.
        print("warning: -l (LS time limit) is retired on the TPU path; "
              "the local search is bounded by -m (maxSteps) candidate "
              "evaluations instead", file=sys.stderr)

    close_out = False
    if out is None:
        if cfg.output:
            out = open(cfg.output, "w")
            close_out = True
        else:
            out = sys.stdout

    try:
        return _run_tries(cfg, out)
    finally:
        if close_out:
            out.close()


def _phase(out, enabled: bool, name: str, trial: int, seconds: float,
           **extra) -> None:
    if enabled:
        jsonl.phase_record(out, name, trial, seconds, **extra)


def _run_tries(cfg: RunConfig, out) -> int:
    t0 = time.monotonic()
    problem = load_tim_file(cfg.input)
    pa = problem.device_arrays()

    devices = jax.devices()
    n_islands = cfg.islands if cfg.islands is not None else len(devices)
    if n_islands > len(devices):
        print(f"warning: {n_islands} islands requested but only "
              f"{len(devices)} devices; using {len(devices)}",
              file=sys.stderr)
        n_islands = len(devices)
    mesh = islands.make_mesh(n_islands)

    gacfg = build_ga_config(cfg)
    seed = cfg.resolved_seed()
    fingerprint = ckpt.config_fingerprint(problem, gacfg, n_islands)
    _phase(out, cfg.trace, "load", 0, time.monotonic() - t0)

    # Runners come from the module-level compiled-program cache (keyed on
    # mesh + gacfg + dispatch shape), so repeated engine.run calls with
    # the same configuration — e.g. a warm-up run followed by a timed
    # race run — share one compilation. The per-generation time estimate
    # is keyed on the full config fingerprint (instance dims + breeding
    # params + island layout), so a measurement from one problem is never
    # trusted for a differently-shaped one.
    spg_key = (_mesh_key(mesh), gacfg, fingerprint)

    global_best = INT_MAX
    # The reference's try loop is legacy Control behavior (Control.cpp:
    # 188-246) unused by the MPI binary; we honor -n but default it to 1.
    for trial in range(cfg.tries):
        t_try = time.monotonic()   # per-try clock (beginTry, ga.cpp:163)
        key = jax.random.key(seed + trial)
        k_init, key = jax.random.split(key)

        gens_done = 0
        best_seen = None
        state = None
        if cfg.resume and cfg.checkpoint:
            try:
                state, key, gens_done, best_seen, saved_seed = ckpt.load(
                    cfg.checkpoint, fingerprint)
                if saved_seed is not None:
                    if cfg.seed is not None and cfg.seed != saved_seed:
                        raise ValueError(
                            f"checkpoint was written with seed "
                            f"{saved_seed}, but -s {cfg.seed} given — "
                            f"refusing to mix RNG streams")
                    seed = saved_seed   # default seed adopts the saved one
            except FileNotFoundError:
                state = None
        if state is None:
            t = time.monotonic()
            state = cached_init(mesh, cfg.pop_size, gacfg)(pa, k_init)
            jax.block_until_ready(state)
            _phase(out, cfg.trace, "init", trial, time.monotonic() - t)
        if best_seen is None:
            best_seen = [INT_MAX] * n_islands

        epochs_done = 0
        epochs_at_ckpt = 0
        sec_per_gen = _SPG_CACHE.get(spg_key)
        while gens_done < cfg.generations:
            remaining_t = cfg.time_limit - (time.monotonic() - t_try)
            if remaining_t <= 0:
                break
            remaining = cfg.generations - gens_done
            if remaining >= cfg.migration_period:
                n_ep = max(1, min(cfg.epochs_per_dispatch,
                                  remaining // cfg.migration_period))
                gens = cfg.migration_period
            else:
                n_ep, gens = 1, remaining      # clamped final dispatch
            if sec_per_gen is not None and sec_per_gen > 0:
                # -t must HOLD: launch only work predicted to fit the
                # remaining budget (the reference checks its clock before
                # every LS candidate, Solution.cpp:499; our granularity
                # is one dispatch, so bound the dispatch instead). A
                # final dispatch may start while at least half of it is
                # predicted to fit, bounding the overshoot by half a
                # minimal dispatch. The time-clamped n_ep is quantized to
                # a power of two so the run compiles at most
                # log2(epochs_per_dispatch) distinct dispatch shapes
                # instead of a fresh one per countdown value.
                fit = int(remaining_t / (sec_per_gen * gens))
                if fit < 1:
                    if remaining_t < 0.5 * sec_per_gen * gens:
                        break
                    n_ep = 1
                elif fit < n_ep:
                    n_ep = 1 << (fit.bit_length() - 1)
            runner, warm = cached_runner(mesh, gacfg, n_ep, gens)

            key, k_epoch = jax.random.split(key)
            td0 = time.monotonic()
            state, trace, _gbest = runner(pa, k_epoch, state)
            trace = np.asarray(trace)          # blocks on the dispatch
            td1 = time.monotonic()
            _phase(out, cfg.trace, "dispatch", trial, td1 - td0,
                   epochs=n_ep, gens=n_ep * gens)
            gens_done += n_ep * gens
            epochs_done += n_ep
            if warm:
                # compiling dispatches are excluded: compile time would
                # inflate the estimate, and the poisoned value would both
                # end this run early and persist into later runs
                spg = (td1 - td0) / (n_ep * gens)
                sec_per_gen = (spg if sec_per_gen is None
                               else 0.7 * spg + 0.3 * sec_per_gen)
                _SPG_CACHE[spg_key] = sec_per_gen

            # per-generation logEntry emission from the device-side trace
            flat = trace.reshape(n_islands, n_ep * gens, 2)
            total = n_ep * gens
            for i in range(n_islands):
                for g in range(total):
                    rep = jsonl.reported_best(flat[i, g, 0], flat[i, g, 1])
                    if rep < best_seen[i]:
                        best_seen[i] = rep
                        tg = (td0 - t_try) + (g + 1) / total * (td1 - td0)
                        jsonl.log_entry(out, i, 0, rep, tg)

            if (cfg.checkpoint
                    and epochs_done - epochs_at_ckpt >= cfg.checkpoint_every):
                t = time.monotonic()
                ckpt.save(cfg.checkpoint, state, key, gens_done,
                          fingerprint, best_seen, seed)
                epochs_at_ckpt = epochs_done
                _phase(out, cfg.trace, "checkpoint", trial,
                       time.monotonic() - t)

        # final per-island solution records (endTry, ga.cpp:169-197)
        t = time.monotonic()
        P = cfg.pop_size
        slots = np.asarray(state.slots).reshape(n_islands, P, -1)
        rooms = np.asarray(state.rooms).reshape(n_islands, P, -1)
        hcv = np.asarray(state.hcv).reshape(n_islands, P)[:, 0]
        scv = np.asarray(state.scv).reshape(n_islands, P)[:, 0]
        _phase(out, cfg.trace, "fetch", trial, time.monotonic() - t)
        total_time = time.monotonic() - t_try
        for i in range(n_islands):
            feas = hcv[i] == 0
            rep = jsonl.reported_best(hcv[i], scv[i])
            jsonl.solution_record(
                out, i, 0, total_time, rep, feas,
                timeslots=slots[i, 0].tolist() if feas else None,
                rooms=rooms[i, 0].tolist() if feas else None)

        # cluster-level best (setGlobalCost's Allreduce MIN, ga.cpp:
        # 234-257): first runEntry line
        trial_best = min(jsonl.reported_best(hcv[i], scv[i])
                         for i in range(n_islands))
        feasible = bool((hcv == 0).any())
        jsonl.run_entry(out, trial_best, feasible)
        # final runEntry with procsNum/threadsNum/totalTime appended
        # (ga.cpp:604-607)
        jsonl.run_entry(out, trial_best, feasible,
                        procs_num=n_islands, threads_num=cfg.threads,
                        total_time=total_time)
        global_best = min(global_best, trial_best)

    return global_best
