"""The run engine: host orchestration of the island GA.

The TPU-native re-design of ga.cpp main() (ga.cpp:370-613). Where the
reference interleaves MPI bootstrap, OpenMP breeding loops and ad-hoc
logging in one function, the engine is a host loop over *epochs*: each
epoch is one fully on-device dispatch (migration_period generations on
every island + ring migration, see parallel/islands.py), after which the
host reads back per-island bests to drive the JSONL protocol, the wall
clock bound (-t, Control.cpp:62-68), and checkpointing.
"""

from __future__ import annotations

import sys
import time

import jax
import numpy as np

from timetabling_ga_tpu.ops import ga
from timetabling_ga_tpu.parallel import islands
from timetabling_ga_tpu.problem import load_tim_file
from timetabling_ga_tpu.runtime import checkpoint as ckpt
from timetabling_ga_tpu.runtime import jsonl
from timetabling_ga_tpu.runtime.config import RunConfig

INT_MAX = 2 ** 31 - 1


def build_ga_config(cfg: RunConfig) -> ga.GAConfig:
    """Map run flags to breeding hyper-parameters.

    The reference's LS budget counts candidate evaluations
    (stepCount, Solution.cpp:471-769); one of our LS rounds evaluates
    `ls_candidates` candidates, so rounds = maxSteps / ls_candidates keeps
    the candidate budget comparable."""
    max_steps = cfg.resolved_max_steps()
    ls_rounds = max(1, max_steps // cfg.ls_candidates)
    return ga.GAConfig(
        pop_size=cfg.pop_size,
        p1=cfg.p1, p2=cfg.p2, p3=cfg.p3,
        ls_steps=ls_rounds, ls_candidates=cfg.ls_candidates,
        ls_delta=not cfg.ls_full_eval,
        multi_objective=cfg.nsga2,
    )


def run(cfg: RunConfig, out=None) -> int:
    """Execute the configured run; emit the JSONL protocol on `out`.

    Returns the global best reported evaluation (scv if feasible else
    hcv*1e6+scv), the quantity the reference's runEntry reports.
    """
    t_start = time.monotonic()
    if cfg.backend == "cpu":
        jax.config.update("jax_platforms", "cpu")

    close_out = False
    if out is None:
        if cfg.output:
            out = open(cfg.output, "w")
            close_out = True
        else:
            out = sys.stdout

    try:
        return _run_tries(cfg, out, t_start)
    finally:
        if close_out:
            out.close()


def _run_tries(cfg: RunConfig, out, t_start: float) -> int:
    problem = load_tim_file(cfg.input)
    pa = problem.device_arrays()

    devices = jax.devices()
    n_islands = cfg.islands if cfg.islands is not None else len(devices)
    if n_islands > len(devices):
        print(f"warning: {n_islands} islands requested but only "
              f"{len(devices)} devices; using {len(devices)}",
              file=sys.stderr)
        n_islands = len(devices)
    mesh = islands.make_mesh(n_islands)

    gacfg = build_ga_config(cfg)
    seed = cfg.resolved_seed()
    fingerprint = ckpt.config_fingerprint(problem, gacfg)

    runner = islands.make_island_runner(
        mesh, gacfg, n_epochs=1, gens_per_epoch=cfg.migration_period)

    global_best = INT_MAX
    # The reference's try loop is legacy Control behavior (Control.cpp:
    # 188-246) unused by the MPI binary; we honor -n but default it to 1.
    for trial in range(cfg.tries):
        key = jax.random.key(seed + trial)
        k_init, key = jax.random.split(key)

        gens_done = 0
        state = None
        if cfg.resume and cfg.checkpoint:
            try:
                state, key, gens_done = ckpt.load(cfg.checkpoint,
                                                  fingerprint)
            except FileNotFoundError:
                state = None
        if state is None:
            state = islands.init_island_population(
                pa, k_init, mesh, cfg.pop_size)

        best_seen = [INT_MAX] * n_islands
        epoch = 0
        while gens_done < cfg.generations:
            if time.monotonic() - t_start > cfg.time_limit:
                break
            key, k_epoch = jax.random.split(key)
            state, _trace, _gbest = runner(pa, k_epoch, state)
            gens_done += cfg.migration_period
            epoch += 1

            hcv = np.asarray(state.hcv).reshape(n_islands, -1)[:, 0]
            scv = np.asarray(state.scv).reshape(n_islands, -1)[:, 0]
            now = time.monotonic() - t_start
            for i in range(n_islands):
                rep = jsonl.reported_best(hcv[i], scv[i])
                if rep < best_seen[i]:
                    best_seen[i] = rep
                    jsonl.log_entry(out, i, 0, rep, now)

            if cfg.checkpoint and epoch % cfg.checkpoint_every == 0:
                ckpt.save(cfg.checkpoint, state, key, gens_done,
                          fingerprint)

        # final per-island solution records (endTry, ga.cpp:169-197)
        P = cfg.pop_size
        slots = np.asarray(state.slots).reshape(n_islands, P, -1)
        rooms = np.asarray(state.rooms).reshape(n_islands, P, -1)
        hcv = np.asarray(state.hcv).reshape(n_islands, P)[:, 0]
        scv = np.asarray(state.scv).reshape(n_islands, P)[:, 0]
        total_time = time.monotonic() - t_start
        for i in range(n_islands):
            feas = hcv[i] == 0
            rep = jsonl.reported_best(hcv[i], scv[i])
            jsonl.solution_record(
                out, i, 0, total_time, rep, feas,
                timeslots=slots[i, 0].tolist() if feas else None,
                rooms=rooms[i, 0].tolist() if feas else None)

        # cluster-level best (setGlobalCost's Allreduce MIN, ga.cpp:
        # 234-257): first runEntry line
        trial_best = min(jsonl.reported_best(hcv[i], scv[i])
                         for i in range(n_islands))
        feasible = bool((hcv == 0).any())
        jsonl.run_entry(out, trial_best, feasible)
        # final runEntry with procsNum/threadsNum/totalTime appended
        # (ga.cpp:604-607)
        jsonl.run_entry(out, trial_best, feasible,
                        procs_num=n_islands, threads_num=cfg.threads,
                        total_time=total_time)
        global_best = min(global_best, trial_best)

    return global_best
