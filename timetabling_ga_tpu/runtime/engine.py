"""The run engine: host orchestration of the island GA.

The TPU-native re-design of ga.cpp main() (ga.cpp:370-613). Where the
reference interleaves MPI bootstrap, OpenMP breeding loops and ad-hoc
logging in one function, the engine is a host loop over *dispatches*: each
dispatch is one fully on-device jit call covering one or more epochs
(migration_period generations per island + ring migration each, see
parallel/islands.py). The runner returns a per-GENERATION (hcv, scv) best
trace per island, so the JSONL logEntry protocol sees every mid-epoch
improvement (ga.cpp:203-228 granularity) while the host reads back exactly
one array per dispatch — no per-epoch scalar fetches (they cost seconds on
tunneled devices; BASELINE.md methodology note).

Timing semantics (Control/Timer parity):
  - the wall-clock bound -t applies per try, reset at the top of each
    trial (beginTry/resetTime, ga.cpp:163-167; Control.cpp:62-68);
  - the generation budget is exact: the final dispatch is clamped to the
    remaining generations instead of overshooting to a multiple of
    migration_period;
  - logEntry times are interpolated linearly across a dispatch's wall
    time (generations inside one dispatch are not individually host-
    timestampable; the interpolation error is bounded by one dispatch).

Observability (--trace, SURVEY section 5): per-phase host timings
(init / dispatch / fetch / checkpoint) bracketed by block_until_ready are
emitted as {"phase": ...} JSONL records — an extension record type; the
reference protocol's three record types are unchanged and remain
byte-compatible.
"""

from __future__ import annotations

import dataclasses
import sys
import time

import jax
import numpy as np

from timetabling_ga_tpu.ops import ga
from timetabling_ga_tpu.parallel import islands
from timetabling_ga_tpu.problem import load_tim_file
from timetabling_ga_tpu.runtime import checkpoint as ckpt
from timetabling_ga_tpu.runtime import jsonl
from timetabling_ga_tpu.runtime.config import RunConfig

INT_MAX = 2 ** 31 - 1

# Compiled-program caches, shared across engine.run calls. A jitted
# island runner costs seconds to tens of seconds to compile at race
# scale; rebuilding it per run (as round 2 did, with a run-local dict)
# made every timed run recompile inside its own wall-clock budget even
# after a warm-up run with identical shapes. Keyed on the mesh's device
# identity plus every static that changes the traced program.
_RUNNER_CACHE: dict = {}
_INIT_CACHE: dict = {}


def _mesh_key(mesh):
    return tuple((d.platform, d.id) for d in mesh.devices.flat)


def _pow2_floor(n: int) -> int:
    """Largest power of two <= n (n >= 1). The engine quantizes every
    static dispatch's epoch count with this, and precompile builds
    exactly the quantized shapes — one shared helper so the
    compiled-shape contract cannot drift."""
    return 1 << (n.bit_length() - 1)


def _shape_sig(problem):
    """Instance-shape signature for the compiled-program caches.

    jax.jit compiles PER INPUT SHAPE, so a cache hit on (mesh, gacfg,
    dispatch shape) alone does NOT mean 'no compile': the same runner
    object retraces for a differently-shaped instance, and treating that
    first call as warm would time the compile into the persisted sec/gen
    and sec/sweep estimates (poisoning every later budget decision for
    that instance — found in round-3 review). The shape signature makes
    warmness per-instance-shape."""
    return (problem.n_events, problem.n_rooms, problem.n_students,
            problem.n_days, problem.slots_per_day)


def cached_runner(mesh, gacfg: ga.GAConfig, n_epochs: int, gens: int,
                  sig):
    """Returns (runner, was_cached). was_cached=False means this
    (program, instance shape) pair is fresh, so its first call will pay
    an XLA compile."""
    k = (_mesh_key(mesh), gacfg, n_epochs, gens, sig)
    r = _RUNNER_CACHE.get(k)
    if r is not None:
        return r, True
    r = islands.make_island_runner(mesh, gacfg, n_epochs=n_epochs,
                                   gens_per_epoch=gens)
    _RUNNER_CACHE[k] = r
    return r, False


def cached_dynamic_runner(mesh, gacfg: ga.GAConfig, max_gens: int, sig):
    """Tail-dispatch runner with a RUNTIME generation count (one compile
    serves every n_gens <= max_gens), used to spend the last slice of a
    wall-clock budget instead of idling through it."""
    k = ("dyn", _mesh_key(mesh), gacfg, max_gens, sig)
    r = _RUNNER_CACHE.get(k)
    if r is not None:
        return r, True
    r = islands.make_island_runner_dynamic(mesh, gacfg, max_gens)
    _RUNNER_CACHE[k] = r
    return r, False


def cached_init(mesh, pop_size: int, gacfg: ga.GAConfig):
    k = (_mesh_key(mesh), pop_size, gacfg)
    f = _INIT_CACHE.get(k)
    if f is None:
        f = jax.jit(lambda pa, key: islands.init_island_population(
            pa, key, mesh, pop_size, gacfg))
        _INIT_CACHE[k] = f
    return f


# Measured seconds-per-generation, persisted across engine.run calls with
# the same (mesh, config, problem shape) so a warm-up run's measurement
# bounds even the FIRST dispatch of a later timed run.
_SPG_CACHE: dict = {}
# Likewise for seconds-per-sweep-pass of the init polish runner.
_SPS_CACHE: dict = {}


def cached_polish_runner(mesh, gacfg: ga.GAConfig, sig):
    """Init-polish runner with a RUNTIME sweep count (one compile serves
    every chunk size); see islands.make_polish_runner."""
    k = ("polish", _mesh_key(mesh), gacfg, sig)
    r = _RUNNER_CACHE.get(k)
    if r is not None:
        return r, True
    r = islands.make_polish_runner(mesh, gacfg)
    _RUNNER_CACHE[k] = r
    return r, False


def build_ga_config(cfg: RunConfig) -> ga.GAConfig:
    """Map run flags to breeding hyper-parameters.

    The reference's LS budget counts candidate evaluations
    (stepCount, Solution.cpp:471-769); one of our LS rounds evaluates
    `ls_candidates` candidates, so rounds = maxSteps / ls_candidates keeps
    the candidate budget comparable."""
    max_steps = cfg.resolved_max_steps()
    ls_rounds = max(1, max_steps // cfg.ls_candidates)
    return ga.GAConfig(
        pop_size=cfg.pop_size,
        p1=cfg.p1, p2=cfg.p2, p3=cfg.p3,
        ls_steps=ls_rounds, ls_candidates=cfg.ls_candidates,
        ls_delta=not cfg.ls_full_eval,
        ls_mode=cfg.ls_mode, ls_sweeps=cfg.ls_sweeps,
        ls_swap_block=cfg.ls_swap_block,
        ls_block_events=cfg.ls_block_events,
        ls_sideways=cfg.ls_sideways,
        ls_converge=cfg.ls_converge, init_sweeps=cfg.init_sweeps,
        rooms_mode=cfg.rooms_mode,
        multi_objective=cfg.nsga2,
    )


_DISTRIBUTED_DONE = False


def maybe_init_distributed(cfg: RunConfig) -> None:
    """Multi-host entry point — the role MPI_Init plays for the
    reference (ga.cpp:373-380). Called before any device use; the island
    mesh then spans every process's devices, with migration riding ICI
    within a slice and DCN across hosts (SURVEY section 5, distributed
    comm backend).

    Launch (one command per host, like mpirun's per-rank launch):
        host0: tt -i x.tim --coordinator host0:1234 \
                  --num-processes 2 --process-id 0
        host1: tt -i x.tim --coordinator host0:1234 \
                  --num-processes 2 --process-id 1
    On TPU pods, `--distributed` alone auto-detects all three values
    from the environment. Idempotent: repeated engine.run calls in one
    process initialize once."""
    global _DISTRIBUTED_DONE
    if _DISTRIBUTED_DONE or not (cfg.distributed or cfg.coordinator):
        return
    kwargs = {}
    if cfg.coordinator is not None:
        kwargs = dict(coordinator_address=cfg.coordinator,
                      num_processes=cfg.num_processes,
                      process_id=cfg.process_id)
    jax.distributed.initialize(**kwargs)
    _DISTRIBUTED_DONE = True


def _fetch(x) -> np.ndarray:
    """Device->host fetch that also works for multi-host global arrays:
    single-process it is a plain np.asarray; multi-process the shards
    are allgathered so every process sees the global value (the
    reference ships full solutions between ranks the same way,
    ga.cpp:318-368)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def _setup(cfg: RunConfig):
    """Shared run setup: load the instance, build mesh + breeding config
    + cache keys. precompile and _run_tries MUST agree on these (the
    compiled-program and sec/gen caches are keyed on them), so both call
    this one helper."""
    problem = load_tim_file(cfg.input)
    if cfg.auto_tune:
        # production defaults are size-tuned (the reference scales its
        # LS budget with problem type the same way, ga.cpp:389-397);
        # explicit user flags and non-default fields are never touched,
        # and a second call is a no-op (tuned values are non-default)
        cfg.apply_tuned_defaults(problem.n_events)
    pa = problem.device_arrays()
    devices = jax.devices()
    n_islands = cfg.islands if cfg.islands is not None else len(devices)
    if n_islands > len(devices):
        print(f"warning: {n_islands} islands requested but only "
              f"{len(devices)} devices; using {len(devices)}",
              file=sys.stderr)
        n_islands = len(devices)
    mesh = islands.make_mesh(n_islands)
    gacfg = build_ga_config(cfg)
    fingerprint = ckpt.config_fingerprint(problem, gacfg, n_islands)
    spg_key = (_mesh_key(mesh), gacfg, fingerprint)
    return problem, pa, mesh, n_islands, gacfg, fingerprint, spg_key


def precompile(cfg: RunConfig) -> None:
    """Compile every program a timed run of `cfg` can dispatch — init,
    the static epoch runner(s), and the dynamic tail runner — into the
    module-level caches, and seed the seconds-per-generation estimate.

    The engine only ever dispatches: cached_init, the static runner at
    power-of-two n_ep x migration_period (both budget-clamping paths
    quantize to that), and the dynamic tail runner — exactly the set
    built here.

    Fixed-wall-clock comparisons call this outside the budget so the
    timed run is measured like the reference binary: compiled ahead of
    time (mpicxx does its compiling before the race too)."""
    if cfg.backend == "cpu":
        jax.config.update("jax_platforms", "cpu")
    maybe_init_distributed(cfg)
    problem, pa, mesh, n_islands, gacfg, fingerprint, spg_key = _setup(cfg)
    sig = _shape_sig(problem)

    key = jax.random.key(0)
    gacfg_init = dataclasses.replace(gacfg, init_sweeps=0)
    state = cached_init(mesh, cfg.pop_size, gacfg_init)(pa, key)
    jax.block_until_ready(state)
    if gacfg.init_sweeps > 0:
        polish, pwarm = cached_polish_runner(mesh, gacfg, sig)
        jax.block_until_ready(polish(pa, key, state, 1))
        if not pwarm:
            t0 = time.monotonic()
            jax.block_until_ready(
                polish(pa, jax.random.key(1), state, 1))
            sps = time.monotonic() - t0
            prev = _SPS_CACHE.get(spg_key)
            _SPS_CACHE[spg_key] = (sps if prev is None
                                   else 0.7 * sps + 0.3 * prev)
    # static dispatches always run gens = migration_period (shorter
    # remainders go through the dynamic runner), at pow2 n_ep; compile
    # exactly those
    gens = cfg.migration_period
    max_ep = (_pow2_floor(max(cfg.epochs_per_dispatch, 1))
              if cfg.generations >= cfg.migration_period else 0)
    n_ep = 1
    while n_ep <= max_ep:
        runner, warm = cached_runner(mesh, gacfg, n_ep, gens, sig)
        st2, _, _ = runner(pa, key, state)
        jax.block_until_ready(st2)
        if not warm:
            # the timing call MUST differ from the compile call: tunneled
            # devices deduplicate byte-identical repeat computations
            # (BASELINE.md methodology note), which once made this
            # measure ~2e-5 s/gen and let a 146 s dispatch through a
            # 60 s budget — so re-run with a different key
            t0 = time.monotonic()
            st2, _, _ = runner(pa, jax.random.key(1), state)
            jax.block_until_ready(st2)
            spg = (time.monotonic() - t0) / (n_ep * gens)
            prev = _SPG_CACHE.get(spg_key)
            _SPG_CACHE[spg_key] = (spg if prev is None
                                   else 0.7 * spg + 0.3 * prev)
        n_ep *= 2
    dyn, _ = cached_dynamic_runner(mesh, gacfg, cfg.migration_period,
                                   sig)
    jax.block_until_ready(dyn(pa, key, state, 1))


def run(cfg: RunConfig, out=None) -> int:
    """Execute the configured run; emit the JSONL protocol on `out`.

    Returns the global best reported evaluation (scv if feasible else
    hcv*1e6+scv), the quantity the reference's runEntry reports.
    """
    if cfg.backend == "cpu":
        jax.config.update("jax_platforms", "cpu")
    if cfg.ls_time_limit != 99999.0:
        # -l is formally retired on this path: the fixed-shape batched LS
        # is bounded by candidate count (-m maxSteps), not wall clock —
        # a deterministic budget where the reference's was temporal
        # (Solution.cpp:499). Warn instead of silently ignoring.
        print("warning: -l (LS time limit) is retired on the TPU path; "
              "the local search is bounded by -m (maxSteps) candidate "
              "evaluations instead", file=sys.stderr)

    maybe_init_distributed(cfg)

    # single-controller reporting: process 0 has the global view (every
    # island's solution records and the runEntry), so other processes
    # stay silent instead of duplicating the protocol — and must not
    # even OPEN -o (on a shared filesystem they would truncate the file
    # process 0 is writing)
    is_main = not (jax.process_count() > 1 and jax.process_index() != 0)
    close_out = False
    if not is_main:
        import io
        out = io.StringIO()
    elif out is None:
        if cfg.output:
            out = open(cfg.output, "w")
            close_out = True
        else:
            out = sys.stdout

    try:
        return _run_tries(cfg, out)
    finally:
        if close_out:
            out.close()


def _phase(out, enabled: bool, name: str, trial: int, seconds: float,
           **extra) -> None:
    if enabled:
        jsonl.phase_record(out, name, trial, seconds, **extra)


def _run_tries(cfg: RunConfig, out) -> int:
    t0 = time.monotonic()
    # Runners come from the module-level compiled-program cache (keyed on
    # mesh + gacfg + dispatch shape), so repeated engine.run calls with
    # the same configuration — e.g. a warm-up run followed by a timed
    # race run — share one compilation. The per-generation time estimate
    # is keyed on the full config fingerprint (instance dims + breeding
    # params + island layout), so a measurement from one problem is never
    # trusted for a differently-shaped one.
    problem, pa, mesh, n_islands, gacfg, fingerprint, spg_key = _setup(cfg)
    sig = _shape_sig(problem)
    # init runs WITHOUT the fused polish (init_sweeps=0): the polish is
    # dispatched in budget-aware chunks right after (see below)
    gacfg_init = dataclasses.replace(gacfg, init_sweeps=0)
    seed = cfg.resolved_seed()
    _phase(out, cfg.trace, "load", 0, time.monotonic() - t0)

    global_best = INT_MAX
    # The reference's try loop is legacy Control behavior (Control.cpp:
    # 188-246) unused by the MPI binary; we honor -n but default it to 1.
    for trial in range(cfg.tries):
        t_try = time.monotonic()   # per-try clock (beginTry, ga.cpp:163)
        key = jax.random.key(seed + trial)
        k_init, key = jax.random.split(key)

        gens_done = 0
        best_seen = None
        state = None
        if cfg.resume and cfg.checkpoint:
            try:
                state, key, gens_done, best_seen, saved_seed = ckpt.load(
                    cfg.checkpoint, fingerprint)
                if saved_seed is not None:
                    if cfg.seed is not None and cfg.seed != saved_seed:
                        raise ValueError(
                            f"checkpoint was written with seed "
                            f"{saved_seed}, but -s {cfg.seed} given — "
                            f"refusing to mix RNG streams")
                    seed = saved_seed   # default seed adopts the saved one
            except FileNotFoundError:
                state = None
        if state is None:
            t = time.monotonic()
            state = cached_init(mesh, cfg.pop_size, gacfg_init)(pa, k_init)
            jax.block_until_ready(state)
            _phase(out, cfg.trace, "init", trial, time.monotonic() - t)
            # Initial-population LS polish (ga.cpp:429-434), CHUNKED so
            # the wall clock is checked between dispatches — one fused
            # 30-pass converge polish at comp scale can otherwise eat a
            # whole budget in a single unboundable dispatch. The runner
            # takes the sweep count at runtime (one compile, any chunk);
            # the loop stops at the pass budget, at the population-wide
            # fixed point (penalty sum stops dropping — convergence
            # inside a chunk implies the next chunk is a no-op), or when
            # the next chunk is predicted not to fit the time budget.
            if best_seen is None:
                best_seen = [INT_MAX] * n_islands
            if gacfg.init_sweeps > 0:
                polish, pwarm = cached_polish_runner(mesh, gacfg, sig)
                sec_per_sweep = _SPS_CACHE.get(spg_key)
                done = 0
                prev_sum = None
                stalls = 0
                while done < gacfg.init_sweeps:
                    remaining_t = (cfg.time_limit
                                   - (time.monotonic() - t_try))
                    chunk = min(4, gacfg.init_sweeps - done)
                    if sec_per_sweep is not None and sec_per_sweep > 0:
                        # 1.25 safety factor: a converge chunk's cost
                        # varies with how many passes actually run, and
                        # an underestimate here is a budget overshoot
                        fit = int(remaining_t / (1.25 * sec_per_sweep))
                        if fit < 1:
                            break
                        chunk = min(chunk, fit)
                    elif remaining_t <= 0:
                        break
                    tp0 = time.monotonic()
                    state = polish(pa, jax.random.fold_in(k_init, done),
                                   state, chunk)
                    pen = _fetch(state.penalty)
                    tp1 = time.monotonic()
                    _phase(out, cfg.trace, "polish", trial, tp1 - tp0,
                           sweeps=chunk)
                    if pwarm:
                        sps = (tp1 - tp0) / chunk
                        sec_per_sweep = (
                            sps if sec_per_sweep is None
                            else 0.7 * sps + 0.3 * sec_per_sweep)
                        _SPS_CACHE[spg_key] = sec_per_sweep
                    pwarm = True
                    done += chunk
                    # polish improvements feed the logEntry stream too:
                    # reaching feasibility during the initial LS must be
                    # visible to time-to-feasible measurement (the
                    # reference logs its init LS bests the same way,
                    # ga.cpp:203-228 fires on any new local best)
                    hcv_a = _fetch(state.hcv).reshape(n_islands, -1)
                    scv_a = _fetch(state.scv).reshape(n_islands, -1)
                    for i in range(n_islands):
                        rep = jsonl.reported_best(hcv_a[i, 0], scv_a[i, 0])
                        if rep < best_seen[i]:
                            best_seen[i] = rep
                            jsonl.log_entry(out, i, 0, rep,
                                            tp1 - t_try)
                    cur_sum = int(pen.astype(np.int64).sum())
                    if prev_sum is not None and cur_sum >= prev_sum:
                        # with sideways acceptance a flat chunk may be a
                        # plateau walk, not the fixed point — allow one
                        # more chunk before concluding convergence
                        stalls += 1
                        if stalls >= 2 or gacfg.ls_sideways == 0.0:
                            break
                    else:
                        stalls = 0
                    prev_sum = cur_sum
        if best_seen is None:
            best_seen = [INT_MAX] * n_islands

        epochs_done = 0
        epochs_at_ckpt = 0
        sec_per_gen = _SPG_CACHE.get(spg_key)
        while gens_done < cfg.generations:
            remaining_t = cfg.time_limit - (time.monotonic() - t_try)
            if remaining_t <= 0:
                break
            remaining = cfg.generations - gens_done
            dyn_gens = None
            gens = cfg.migration_period
            if remaining >= cfg.migration_period:
                n_ep = max(1, min(cfg.epochs_per_dispatch,
                                  remaining // cfg.migration_period))
                # quantize to a power of two: together with the dynamic
                # tail below, the static runner then only ever compiles
                # (pow2 n_ep, migration_period) shapes — the exact set
                # precompile() builds
                n_ep = _pow2_floor(n_ep)
            else:
                # clamped final dispatch: fewer than migration_period
                # generations left — served by the dynamic-gens runner
                # (no fresh static shape, no new compile)
                n_ep, dyn_gens = 1, remaining
            if sec_per_gen is not None and sec_per_gen > 0:
                # -t must HOLD: launch only work predicted to fit the
                # remaining budget (the reference checks its clock before
                # every LS candidate, Solution.cpp:499; our granularity
                # is one dispatch, so bound the dispatch instead). The
                # time-clamped n_ep stays a power of two (at most
                # log2(epochs_per_dispatch) static shapes); when less
                # than one full epoch fits, the TAIL runs through the
                # dynamic-gens runner, whose generation count is a
                # runtime argument — one compile, any tail size — so the
                # budget's last slice still does useful evolution instead
                # of idling (VERDICT round-2 weak 3: 8-9s of a 60s budget
                # went unused).
                g_fit = int(remaining_t / sec_per_gen)
                if g_fit < 1:
                    break
                if dyn_gens is not None:
                    dyn_gens = min(dyn_gens, g_fit)
                else:
                    fit_ep = g_fit // gens
                    if fit_ep < 1:
                        n_ep, dyn_gens = 1, min(g_fit, gens)
                    elif fit_ep < n_ep:
                        n_ep = _pow2_floor(fit_ep)

            key, k_epoch = jax.random.split(key)
            if dyn_gens is not None:
                runner, warm = cached_dynamic_runner(
                    mesh, gacfg, cfg.migration_period, sig)
                td0 = time.monotonic()
                state, trace, _gbest = runner(pa, k_epoch, state, dyn_gens)
                trace = _fetch(trace)[:, :, :dyn_gens]
                gens_run = dyn_gens
            else:
                runner, warm = cached_runner(mesh, gacfg, n_ep, gens,
                                              sig)
                td0 = time.monotonic()
                state, trace, _gbest = runner(pa, k_epoch, state)
                trace = _fetch(trace)          # blocks on the dispatch
                gens_run = n_ep * gens
            td1 = time.monotonic()
            _phase(out, cfg.trace, "dispatch", trial, td1 - td0,
                   epochs=n_ep, gens=gens_run)
            gens_done += gens_run
            epochs_done += n_ep
            if warm and gens_run >= cfg.migration_period:
                # compiling dispatches are excluded: compile time would
                # inflate the estimate, and the poisoned value would both
                # end this run early and persist into later runs. Tiny
                # dynamic tails are excluded too: their wall time is
                # dominated by fixed dispatch/migration/fetch overhead,
                # which would inflate the per-generation estimate
                spg = (td1 - td0) / gens_run
                sec_per_gen = (spg if sec_per_gen is None
                               else 0.7 * spg + 0.3 * sec_per_gen)
                _SPG_CACHE[spg_key] = sec_per_gen

            # per-generation logEntry emission from the device-side trace
            flat = trace.reshape(n_islands, gens_run, 2)
            total = gens_run
            for i in range(n_islands):
                for g in range(total):
                    rep = jsonl.reported_best(flat[i, g, 0], flat[i, g, 1])
                    if rep < best_seen[i]:
                        best_seen[i] = rep
                        tg = (td0 - t_try) + (g + 1) / total * (td1 - td0)
                        jsonl.log_entry(out, i, 0, rep, tg)

            if (cfg.checkpoint
                    and epochs_done - epochs_at_ckpt >= cfg.checkpoint_every):
                t = time.monotonic()
                ckpt.save(cfg.checkpoint, state, key, gens_done,
                          fingerprint, best_seen, seed)
                epochs_at_ckpt = epochs_done
                _phase(out, cfg.trace, "checkpoint", trial,
                       time.monotonic() - t)

        # final per-island solution records (endTry, ga.cpp:169-197)
        t = time.monotonic()
        P = cfg.pop_size
        slots = _fetch(state.slots).reshape(n_islands, P, -1)
        rooms = _fetch(state.rooms).reshape(n_islands, P, -1)
        hcv = _fetch(state.hcv).reshape(n_islands, P)[:, 0]
        scv = _fetch(state.scv).reshape(n_islands, P)[:, 0]
        _phase(out, cfg.trace, "fetch", trial, time.monotonic() - t)
        total_time = time.monotonic() - t_try
        for i in range(n_islands):
            feas = hcv[i] == 0
            rep = jsonl.reported_best(hcv[i], scv[i])
            jsonl.solution_record(
                out, i, 0, total_time, rep, feas,
                timeslots=slots[i, 0].tolist() if feas else None,
                rooms=rooms[i, 0].tolist() if feas else None)

        # cluster-level best (setGlobalCost's Allreduce MIN, ga.cpp:
        # 234-257): first runEntry line
        trial_best = min(jsonl.reported_best(hcv[i], scv[i])
                         for i in range(n_islands))
        feasible = bool((hcv == 0).any())
        jsonl.run_entry(out, trial_best, feasible)
        # final runEntry with procsNum/threadsNum/totalTime appended
        # (ga.cpp:604-607)
        jsonl.run_entry(out, trial_best, feasible,
                        procs_num=n_islands, threads_num=cfg.threads,
                        total_time=total_time)
        global_best = min(global_best, trial_best)

    return global_best
