"""Problem model and `.tim` instance loader.

Capability parity with the reference loader (Problem.cpp:3-96), re-designed
for device residency: instead of ragged C arrays the instance becomes a set
of packed numpy/jnp tensors that are uploaded once and stay in HBM.

`.tim` format (Metaheuristics-Network / ITC-2002):

    E R F S                      header (events, rooms, features, students)
    <R ints>                     room sizes
    <S*E 0/1 ints>               student-event attendance, student-major
    <R*F 0/1 ints>               room features
    <E*F 0/1 ints>               event feature requirements

Derived data (reference Problem.cpp:34-95):
    student_count[e]   = column sums of attendance
    conflict[i, j]     = events i, j share >= 1 student  (eventCorrelations)
    possible[e, r]     = roomSize[r] >= student_count[e] and the room
                         satisfies every feature the event requires

The timeslot grid is parametrized (n_days x slots_per_day) instead of the
reference's hard-wired 45 = 5 x 9 (Solution.cpp:52, 57, 100).
"""

from __future__ import annotations

import dataclasses
import io
from typing import Union

import jax
import jax.numpy as jnp
import numpy as np

DAYS_DEFAULT = 5
SLOTS_PER_DAY_DEFAULT = 9


@dataclasses.dataclass(frozen=True)
class Problem:
    """A timetabling instance, packed as dense arrays.

    All arrays are host numpy; ``device_arrays()`` returns the jnp copies
    used by the kernels. Frozen: an instance never changes after load.
    """

    n_events: int
    n_rooms: int
    n_features: int
    n_students: int
    room_size: np.ndarray      # (R,)    int32
    attends: np.ndarray        # (S, E)  int8   student-event attendance
    room_features: np.ndarray  # (R, F)  int8
    event_features: np.ndarray  # (E, F) int8
    # derived
    student_count: np.ndarray  # (E,)    int32
    conflict: np.ndarray       # (E, E)  bool   shared-student correlation
    possible: np.ndarray       # (E, R)  bool   room suitability
    n_days: int = DAYS_DEFAULT
    slots_per_day: int = SLOTS_PER_DAY_DEFAULT
    # Live-prefix counts for SHAPE-BUCKETED instances (serve/bucket.py):
    # events/rooms at index >= n_live_* are padding — zero attendance,
    # zero features, zero capacity — present only so every instance in a
    # bucket shares one compiled program shape. None = everything live
    # (every instance outside the serve path). The padding invariants
    # (padded events suit no room, padded rooms suit no event) are
    # established by serve.bucket.pad_problem, and the kernels consume
    # them through ProblemArrays.event_mask / room_mask below.
    n_live_events: Union[int, None] = None
    n_live_rooms: Union[int, None] = None
    # Anchored objective (serve/editsolve.py): per-event anchor timeslot
    # and per-event integer weight. An edit job re-solves an edited
    # instance while paying `anchor_w[e]` for every event whose slot
    # differs from `anchor_slots[e]` (the base job's best solution), so
    # the search stays NEAR the published timetable. None = unanchored
    # (every instance outside the edit path); device_arrays() then emits
    # all-zero columns, and a zero weight vector makes the anchor term
    # exactly 0 in integer arithmetic — bit-identical to the unanchored
    # objective. New/padded events carry weight 0 by construction.
    anchor_slots: Union[np.ndarray, None] = None  # (E,) int32
    anchor_w: Union[np.ndarray, None] = None      # (E,) int32

    @property
    def n_slots(self) -> int:
        return self.n_days * self.slots_per_day

    def to_tim(self) -> str:
        """Serialize to canonical `.tim` text (see dump_tim).

        The edit differ and the gateway edit payload both ship problems
        in this form; round-trips load_tim bit-exactly."""
        return dump_tim(self)

    def device_arrays(self):
        """Upload the kernel-facing arrays to the default device once.

        Returns a ``ProblemArrays`` pytree (jnp arrays) that every kernel
        takes as its first argument — the analogue of the reference's
        ``Problem*`` held by each Solution (Solution.h:38), except the data
        is replicated into HBM instead of chased through host pointers.
        """
        live_e = (self.n_events if self.n_live_events is None
                  else self.n_live_events)
        live_r = (self.n_rooms if self.n_live_rooms is None
                  else self.n_live_rooms)
        anchor_slots = (np.zeros(self.n_events, dtype=np.int32)
                        if self.anchor_slots is None else self.anchor_slots)
        anchor_w = (np.zeros(self.n_events, dtype=np.int32)
                    if self.anchor_w is None else self.anchor_w)
        return ProblemArrays(
            attends=jnp.asarray(self.attends, dtype=jnp.float32),
            conflict=jnp.asarray(self.conflict, dtype=jnp.float32),
            possible=jnp.asarray(self.possible, dtype=jnp.bool_),
            student_count=jnp.asarray(self.student_count, dtype=jnp.int32),
            room_size=jnp.asarray(self.room_size, dtype=jnp.int32),
            event_mask=jnp.asarray(
                np.arange(self.n_events) < live_e, dtype=jnp.float32),
            room_mask=jnp.asarray(
                np.arange(self.n_rooms) < live_r, dtype=jnp.bool_),
            anchor_slots=jnp.asarray(anchor_slots, dtype=jnp.int32),
            anchor_w=jnp.asarray(anchor_w, dtype=jnp.int32),
            n_days=self.n_days,
            slots_per_day=self.slots_per_day,
        )


@dataclasses.dataclass(frozen=True)
class ProblemArrays:
    """Device-resident view of a Problem (a pytree of jnp arrays).

    ``attends`` and ``conflict`` are float32 so the fitness contractions
    lower straight onto the MXU; all values are exact small integers so
    float accumulation is bit-exact (counts << 2^24).
    """

    attends: "object"        # (S, E) f32
    conflict: "object"       # (E, E) f32, diagonal = event has >=1 student
    possible: "object"       # (E, R) bool
    student_count: "object"  # (E,)   i32
    room_size: "object"      # (R,)   i32
    # Validity masks for shape-bucketed (padded) instances: 1.0/True for
    # live entries, 0.0/False for padding (serve/bucket.py). All-ones on
    # unpadded instances, where every masked expression reduces to the
    # unmasked one exactly (0/1 float multiplies and int adds are exact).
    # event_mask is float32 because its hottest use is masking the f32
    # one-hot operands of the fitness contractions.
    event_mask: "object"     # (E,)   f32  1.0 live / 0.0 padded
    room_mask: "object"      # (R,)   bool True live / False padded
    # Anchored objective columns (serve/editsolve.py): anchor_w already
    # folds the edit's w_anchor weight with the carried-event mask, so
    # padded and newly-added events hold weight 0 and the masked-Hamming
    # anchor cost needs no extra gating. All-zero (the exact unanchored
    # objective) outside the edit path.
    anchor_slots: "object"   # (E,)   i32  anchor timeslot per event
    anchor_w: "object"       # (E,)   i32  0 = unanchored event
    n_days: int
    slots_per_day: int

    @property
    def n_slots(self) -> int:
        return self.n_days * self.slots_per_day

    @property
    def n_events(self) -> int:
        return self.possible.shape[0]

    @property
    def n_rooms(self) -> int:
        return self.possible.shape[1]


# Register ProblemArrays as a pytree with static day/slot geometry.
def _pa_flatten(pa: ProblemArrays):
    children = (pa.attends, pa.conflict, pa.possible, pa.student_count,
                pa.room_size, pa.event_mask, pa.room_mask,
                pa.anchor_slots, pa.anchor_w)
    aux = (pa.n_days, pa.slots_per_day)
    return children, aux


def _pa_unflatten(aux, children):
    (attends, conflict, possible, student_count, room_size, event_mask,
     room_mask, anchor_slots, anchor_w) = children
    n_days, slots_per_day = aux
    return ProblemArrays(attends, conflict, possible, student_count,
                         room_size, event_mask, room_mask, anchor_slots,
                         anchor_w, n_days, slots_per_day)


jax.tree_util.register_pytree_node(ProblemArrays, _pa_flatten, _pa_unflatten)


def derive(n_events: int, n_rooms: int, n_features: int, n_students: int,
           room_size: np.ndarray, attends: np.ndarray,
           room_features: np.ndarray, event_features: np.ndarray,
           n_days: int = DAYS_DEFAULT,
           slots_per_day: int = SLOTS_PER_DAY_DEFAULT) -> Problem:
    """Build a Problem from raw arrays, computing the derived matrices.

    Vectorized equivalents of the reference's triple loops:
    - conflict:  attends.T @ attends > 0   (Problem.cpp:49-58 O(E^2*S) loop)
    - possible:  size-fits AND features-subset (Problem.cpp:83-95)
    """
    attends = np.asarray(attends, dtype=np.int8)
    room_size = np.asarray(room_size, dtype=np.int32)
    room_features = np.asarray(room_features, dtype=np.int8)
    event_features = np.asarray(event_features, dtype=np.int8)

    expected = {
        "room_size": (room_size.shape, (n_rooms,)),
        "attends": (attends.shape, (n_students, n_events)),
        "room_features": (room_features.shape, (n_rooms, n_features)),
        "event_features": (event_features.shape, (n_events, n_features)),
    }
    for name, (got, want) in expected.items():
        if got != want:
            raise ValueError(f"{name}: expected shape {want}, got {got}")

    student_count = attends.astype(np.int64).sum(axis=0).astype(np.int32)
    # float32 matmul rides BLAS (integer matmuls do not); counts are
    # exact in f32 up to 2^24 co-attendances per pair
    a32 = attends.astype(np.float32)
    conflict = (a32.T @ a32) > 0.5

    size_ok = room_size[None, :] >= student_count[:, None]          # (E, R)
    # event needs feature f and room lacks it -> unsuitable
    missing = (event_features.astype(np.int32)[:, None, :]
               * (1 - room_features.astype(np.int32))[None, :, :]).sum(-1)
    possible = size_ok & (missing == 0)

    return Problem(
        n_events=n_events, n_rooms=n_rooms, n_features=n_features,
        n_students=n_students, room_size=room_size, attends=attends,
        room_features=room_features, event_features=event_features,
        student_count=student_count, conflict=conflict, possible=possible,
        n_days=n_days, slots_per_day=slots_per_day,
    )


def load_tim(source: Union[str, io.TextIOBase],
             n_days: int = DAYS_DEFAULT,
             slots_per_day: int = SLOTS_PER_DAY_DEFAULT) -> Problem:
    """Parse a `.tim` instance from a string or text stream.

    Whitespace-insensitive token stream, like the reference's
    ``ifs >>`` parsing (Problem.cpp:7-74).
    """
    if isinstance(source, str):
        text = source
    else:
        text = source.read()
    tokens = np.array(text.split(), dtype=np.int64)
    pos = 0

    def take(n):
        nonlocal pos
        out = tokens[pos:pos + n]
        if out.size != n:
            raise ValueError(
                f"truncated .tim instance: wanted {n} tokens at {pos}, "
                f"got {out.size}")
        pos += n
        return out

    e, r, f, s = (int(x) for x in take(4))
    room_size = take(r).astype(np.int32)
    attends = take(s * e).reshape(s, e).astype(np.int8)
    room_features = take(r * f).reshape(r, f).astype(np.int8)
    event_features = take(e * f).reshape(e, f).astype(np.int8)
    if pos != tokens.size:
        raise ValueError(
            f".tim instance has {tokens.size - pos} trailing tokens")
    return derive(e, r, f, s, room_size, attends, room_features,
                  event_features, n_days=n_days, slots_per_day=slots_per_day)


def load_tim_file(path: str, **kw) -> Problem:
    with open(path, "r") as fh:
        return load_tim(fh, **kw)


def dump_tim(problem: Problem) -> str:
    """Serialize a Problem back to `.tim` text (inverse of load_tim).

    The reference has no writer (it only parses, Problem.cpp:3-74); this
    exists for fixtures, benchmarks and round-trip tests."""
    lines = [f"{problem.n_events} {problem.n_rooms} "
             f"{problem.n_features} {problem.n_students}"]
    lines += [str(int(x)) for x in problem.room_size]
    lines += [str(int(x)) for x in problem.attends.reshape(-1)]
    lines += [str(int(x)) for x in problem.room_features.reshape(-1)]
    lines += [str(int(x)) for x in problem.event_features.reshape(-1)]
    return "\n".join(lines) + "\n"


def random_instance(key_or_seed, n_events: int, n_rooms: int,
                    n_features: int, n_students: int,
                    attend_prob: float = 0.05,
                    feature_prob: float = 0.3,
                    n_days: int = DAYS_DEFAULT,
                    slots_per_day: int = SLOTS_PER_DAY_DEFAULT) -> Problem:
    """Synthetic instance generator (for tests and benchmarks).

    Room sizes are drawn to make most events placeable, mirroring the
    character of the ITC-2002 set; there is no reference equivalent (the
    reference ships no instances or generators).
    """
    rng = np.random.default_rng(key_or_seed)
    attends = (rng.random((n_students, n_events)) < attend_prob).astype(np.int8)
    event_features = (rng.random((n_events, n_features))
                      < feature_prob).astype(np.int8)
    # Rooms: feature-rich enough that every event has at least one match.
    room_features = (rng.random((n_rooms, n_features)) < 0.6).astype(np.int8)
    # make room 0 satisfy everything so possible[] rows are never empty
    room_features[0, :] = 1
    student_count = attends.sum(axis=0)
    cap = max(int(student_count.max()), 1)
    room_size = rng.integers(max(cap // 2, 1), cap + 1,
                             size=n_rooms).astype(np.int32)
    room_size[0] = cap
    return derive(n_events, n_rooms, n_features, n_students, room_size,
                  attends, room_features, event_features,
                  n_days=n_days, slots_per_day=slots_per_day)


#: Header stats for ITC-2002-style fixtures. The real competition set
#: (20 instances, Metaheuristics Network / IDSIA generator) spans
#: events 350-440, rooms 10-11, features 5-10, students 200-350, always
#: on the fixed 45-slot grid, and every instance is guaranteed to admit
#: a perfect solution (feasible AND scv == 0) because the generator
#: plants one. The reference consumes exactly this format
#: (Problem.cpp:7-31) but ships no instances; these presets characterize
#: the two BASELINE.md anchor instances. Instances cannot be fetched in
#: this environment (zero egress), so the fixtures are *characterized
#: stand-ins*: same header shape, same construction principle (planted
#: perfect solution), not byte-copies of the competition files.
ITC_PRESETS = {
    "comp01": dict(n_events=400, n_rooms=10, n_features=10, n_students=200),
    "comp05": dict(n_events=350, n_rooms=10, n_features=10, n_students=300),
}


def itc_like_instance(key_or_seed, n_events: int = 400, n_rooms: int = 10,
                      n_features: int = 10, n_students: int = 200,
                      n_days: int = DAYS_DEFAULT,
                      slots_per_day: int = SLOTS_PER_DAY_DEFAULT,
                      return_planted: bool = False):
    """ITC-2002-style instance with a PLANTED perfect solution.

    Construction (mirrors the competition generator's guarantee, not its
    code): first build a zero-penalty timetable, then derive the instance
    around it so that timetable stays a witness:

    1. events -> injective (slot, room) pairs, avoiding the last slot of
       every day (so the planted solution's last-slot scv term is 0);
    2. each student gets a slot pattern with, per day, 0 or 2-4 attended
       slots (never exactly 1), no 3 consecutive, never the day's last
       slot — then attends ONE event per chosen slot (so no student
       clash and every soft term is 0 in the planted timetable);
    3. each event requires a random subset of its planted room's
       features, and each room's capacity covers its largest planted
       event — so every planted room is suitable, while suitability
       elsewhere stays scarce like the competition set's (median 2-5
       suitable rooms per event).

    Returns the Problem, or (Problem, planted_slots, planted_rooms) when
    `return_planted` (for the zero-penalty witness test).
    """
    rng = np.random.default_rng(key_or_seed)
    spd, D = slots_per_day, n_days
    T = D * spd
    usable = [t for t in range(T) if t % spd != spd - 1]
    cells = [(t, r) for t in usable for r in range(n_rooms)]
    if n_events > len(cells):
        raise ValueError(
            f"{n_events} events do not fit {len(usable)} usable slots x "
            f"{n_rooms} rooms")
    rng.shuffle(cells)
    planted = cells[:n_events]
    p_slots = np.array([t for t, _ in planted], dtype=np.int32)
    p_rooms = np.array([r for _, r in planted], dtype=np.int32)
    # events available per slot (for student schedule sampling)
    by_slot = {t: np.nonzero(p_slots == t)[0] for t in usable}
    by_slot = {t: ev for t, ev in by_slot.items() if ev.size}

    # valid per-day slot patterns: subsets of the day's slots that
    # actually HOST an event (an empty pattern slot would silently drop
    # to a 1-class day and break the zero-scv witness), size 2-4, no 3
    # consecutive slots
    from itertools import combinations

    def pattern_choices(av):
        out = []
        for k in (2, 3, 4):
            for c in combinations(av, k):
                if not any(c[i + 2] - c[i] == 2
                           for i in range(len(c) - 2)):
                    out.append(c)
        return out

    day_choices = [pattern_choices(
        [j for j in range(spd - 1) if (d * spd + j) in by_slot])
        for d in range(D)]

    attends = np.zeros((n_students, n_events), dtype=np.int8)
    for s in range(n_students):
        active_days = set(rng.permutation(D)[: rng.integers(3, D + 1)]
                          .tolist())
        for d in range(D):
            if d not in active_days or not day_choices[d]:
                continue
            pat = day_choices[d][rng.integers(len(day_choices[d]))]
            for j in pat:
                ev = by_slot[d * spd + j]
                attends[s, ev[rng.integers(ev.size)]] = 1

    # features: rooms get 3..F-2 features; events require a subset of
    # their planted room's features (so the planted room is suitable)
    room_features = np.zeros((n_rooms, n_features), dtype=np.int8)
    for r in range(n_rooms):
        k = rng.integers(3, max(4, n_features - 1))
        room_features[r, rng.permutation(n_features)[:k]] = 1
    event_features = np.zeros((n_events, n_features), dtype=np.int8)
    for e in range(n_events):
        has = np.nonzero(room_features[p_rooms[e]])[0]
        k = rng.integers(1, min(4, has.size) + 1)
        event_features[e, rng.permutation(has)[:k]] = 1

    student_count = attends.astype(np.int64).sum(axis=0).astype(np.int32)
    room_size = np.ones((n_rooms,), dtype=np.int32)
    for e in range(n_events):
        r = p_rooms[e]
        room_size[r] = max(room_size[r], int(student_count[e]))

    p = derive(n_events, n_rooms, n_features, n_students, room_size,
               attends, room_features, event_features,
               n_days=n_days, slots_per_day=slots_per_day)
    if return_planted:
        return p, p_slots, p_rooms
    return p


def room_tight_instance(key_or_seed, n_events: int, n_rooms: int,
                        n_features: int, n_students: int,
                        attend_prob: float = 0.05,
                        feature_prob: float = 0.4,
                        n_days: int = DAYS_DEFAULT,
                        slots_per_day: int = SLOTS_PER_DAY_DEFAULT
                        ) -> Problem:
    """Room-TIGHT synthetic instance: the regime `random_instance` never
    reaches (VERDICT round-1 weakness 8).

    No universal fallback room, capacities hugging the student-count
    distribution, sparse feature coverage — so per-slot `possible[]` rows
    are small and unevenly overlapping, which is exactly where greedy
    matching can lose to the reference's exact per-slot max matching
    (Solution.cpp:836-891). Events with an empty possible[] row are
    repaired minimally (their cheapest room is upgraded), keeping every
    event placeable somewhere but nothing placeable everywhere — the
    character of the ITC-2002 comp instances (each event has >= 1
    suitable room, median 2-5)."""
    rng = np.random.default_rng(key_or_seed)
    attends = (rng.random((n_students, n_events))
               < attend_prob).astype(np.int8)
    event_features = (rng.random((n_events, n_features))
                      < feature_prob).astype(np.int8)
    # sparse room features: ~40% coverage, NO universal room
    room_features = (rng.random((n_rooms, n_features)) < 0.4).astype(np.int8)
    student_count = attends.astype(np.int64).sum(axis=0).astype(np.int32)
    # capacities drawn FROM the event-size distribution: ~half the rooms
    # cannot host the larger half of events
    sizes = np.sort(student_count)
    picks = rng.integers(0, max(n_events, 1), size=n_rooms)
    room_size = np.maximum(sizes[picks], 1).astype(np.int32)

    # minimal repair: every event must have >= 1 suitable room (the
    # reference assumes this too — an event with no possible room makes
    # every solution infeasible)
    for _ in range(n_features + 1):
        p = derive(n_events, n_rooms, n_features, n_students, room_size,
                   attends, room_features, event_features,
                   n_days=n_days, slots_per_day=slots_per_day)
        orphan = np.nonzero(~p.possible.any(axis=1))[0]
        if orphan.size == 0:
            return p
        for e in orphan:
            # upgrade the room needing the fewest changes for this event
            need = event_features[e].astype(bool)
            deficit = ((need & ~room_features.astype(bool)).sum(axis=1)
                       + (room_size < student_count[e]) * 1)
            r = int(np.argmin(deficit))
            room_features[r][need] = 1
            room_size[r] = max(room_size[r], student_count[e])
    return p
