"""Version-tolerant JAX API resolution + the pinned API compatibility table.

Two jobs, one file:

1. **Resolvers** for JAX symbols that have moved between the versions we
   support (0.4.x .. current). The seed was broken for weeks by
   ``from jax import shard_map`` (a 0.6+ export) failing on the installed
   JAX 0.4.37, which killed collection of the entire test suite — every
   JAX symbol with a version-dependent home must be imported through
   here, never directly.

2. **The pinned API surface** (`JAX_COMPAT_TABLE`): the declared set of
   JAX modules/symbols this codebase is allowed to import directly. The
   static analyzer's TT501 rule (timetabling_ga_tpu/analysis) checks
   every ``import jax...`` in the package against this table at lint
   time — the check that would have caught the ``shard_map`` breakage
   before it ever reached a device. Imports guarded by
   ``try/except ImportError`` (the version-tolerance idiom used below)
   are exempt; everything else must be listed here or resolved via this
   module.
"""

from __future__ import annotations

import inspect

try:
    # JAX >= 0.6: public top-level export.
    from jax import shard_map as _shard_map_impl
except ImportError:
    # JAX 0.4.x / 0.5.x: experimental home (removed upstream later).
    from jax.experimental.shard_map import shard_map as _shard_map_impl

_SHARD_MAP_PARAMS = frozenset(
    inspect.signature(_shard_map_impl).parameters)


def shard_map(f, **kwargs):
    """`jax.shard_map` with the replication-check kwarg normalized.

    The checker flag was renamed `check_rep` -> `check_vma` along with
    the move out of jax.experimental; callers use whichever spelling and
    this shim translates to what the installed JAX accepts.
    """
    if "check_vma" in kwargs and "check_vma" not in _SHARD_MAP_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _SHARD_MAP_PARAMS:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map_impl(f, **kwargs)


def coordination_client():
    """The jax.distributed coordination-service client for this
    process, or None when no coordinator is live (single-process) or
    the installed JAX keeps it elsewhere. tt-accord
    (runtime/control_channel.py) builds its KV-store backend on this;
    the client's home is a private module (`jax._src.distributed`) on
    every version we support, so it is resolved HERE behind the
    guarded-import idiom instead of being declared pinned API."""
    try:
        from jax._src import distributed
    except ImportError:
        return None
    return getattr(getattr(distributed, "global_state", None),
                   "client", None)


# The declared JAX API surface (analysis rules TT501 + TT502). Keys are
# module paths; values are the symbol names reachable from that module —
# by `from <module> import <name>` (TT501) OR by attribute access
# `<module>.<name>` (TT502) — with "*" meaning any symbol. A bare
# `import jax.foo` is allowed iff "jax.foo" is a key. `shard_map` is
# deliberately NOT under the "jax" key: its top-level export does not
# exist on every supported version — import it from this module instead.
# The "jax" entry therefore lists every `jax.X` attribute the package
# uses (jit/vmap/devices/...): an attribute outside the table is the
# same API-drift hazard an undeclared import is, just invisible to the
# import scanner — TT502 closes that gap.
JAX_COMPAT_TABLE = {
    "jax": ["lax", "numpy",
            # attribute surface (TT502)
            "jit", "vmap", "devices", "local_devices",
            "block_until_ready", "named_scope",
            "make_array_from_callback", "process_count",
            "process_index", "clear_caches", "device_get",
            "device_put",
            "config", "random", "tree", "tree_util", "sharding",
            "profiler", "distributed", "errors", "experimental"],
    "jax.numpy": ["*"],
    "jax.lax": ["*"],
    "jax.sharding": ["Mesh", "PartitionSpec", "NamedSharding"],
    "jax.random": ["*"],
    "jax.tree": ["*"],
    "jax.config": ["update"],
    "jax.tree_util": ["register_pytree_node"],
    "jax.profiler": ["start_trace", "stop_trace"],
    "jax.distributed": ["initialize"],
    "jax.errors": ["JaxRuntimeError"],
    "jax.experimental": ["multihost_utils"],
    "jax.experimental.multihost_utils": ["*"],
    "jax.experimental.shard_map": ["shard_map"],
}
