"""The per-job snapshot wire format: resume, don't replay.

Every parked serve job already IS an all-numpy host snapshot
(serve/scheduler.py parks through engine.fetch_state — the same tuple
runtime/checkpoint.py serializes for whole runs). This module is the
job-granular analogue of that checkpoint format: a versioned,
fingerprinted serialization of one job's park-fence state that can
cross a process boundary, so a dead replica's hours of search progress
move to a survivor instead of dying with the process.

Wire object (JSON-safe — it rides the /v1 protocol):

    {"v": 1,
     "fingerprint": "j1|b64x8x8x64x5x9|p16|s42",
     "bucket": [64, 8, 8, 64, 5, 9],
     "gens_done": 150, "chunks": 6,            # progress + RNG cursor
     "emitted": 873, "best": 873,              # logEntry floor (the
                                               #   duplicate-free seam)
     "crc": 2839463521, "bytes": 51712,        # integrity of the npz
     "npz": "<base64 of np.savez(PopState fields)>",
     "usage": {"gens": 150, "device_seconds": 1.2, ...}}
                                               # OPTIONAL tt-meter
                                               #   cursor (obs/usage):
                                               #   the resumed job's
                                               #   meter continues

The fingerprint pins everything that must agree for the resumed lane
to be bit-identical to the uninterrupted one: wire version, bucket key
(the padded shapes every lane program is compiled for), per-lane
population size, and the job's seed (lane RNG is fold_in(key(seed),
chunk) — serve/scheduler.py docstring). A snapshot from a different
bucket spec, pop size, or seed REFUSES to load (SnapshotMismatch,
naming both fingerprints), exactly like checkpoint.load's
FingerprintMismatch; damaged bytes (truncated base64, CRC mismatch,
torn npz) raise SnapshotCorrupt naming the failing field — the
CheckpointCorrupt analogue.

Layering: `verify_wire` is STDLIB-ONLY (base64 + zlib) so the fleet
gateway — which never imports jax — can validate and cache snapshots
on its dispatcher thread; `pack_state`/`unpack_state` touch numpy (and
unpack lazily imports ops.ga for PopState), and only ever run on a
replica. Nothing here may import jax at module level.
"""

from __future__ import annotations

import base64
import dataclasses
import io
import os
import zlib

import numpy as np

WIRE_VERSION = 1

# bound on the record prefix a ship unit mirrors (the scheduler keeps
# each active job's emitted records so the snapshot travels with its
# exact stream prefix): a pathological tenant's million-improvement
# stream must not pin the replica's memory — beyond the cap the oldest
# records drop and the unit is marked truncated (resume still works;
# stream identity honestly cannot be claimed)
SHIP_RECORDS_CAP = int(os.environ.get("TT_SNAPSHOT_RECORDS_CAP",
                                      "4096"))

# the PopState fields, in serialization order (kept explicit rather
# than reflected off ga.PopState so the wire format cannot silently
# drift when the runtime type grows a field — a new field is a wire
# VERSION bump, reviewed here)
_FIELDS = ("slots", "rooms", "penalty", "hcv", "scv")

# wire keys every snapshot must carry (verify_wire names the missing
# one — a truncated JSON object fails loudly, not with a KeyError deep
# in the resume path)
_REQUIRED = ("v", "fingerprint", "bucket", "gens_done", "chunks",
             "emitted", "best", "crc", "bytes", "npz")


class SnapshotCorrupt(RuntimeError):
    """The wire snapshot is damaged (truncated base64, CRC mismatch,
    torn npz, missing fields) — the CheckpointCorrupt analogue
    (runtime/checkpoint.py). The message names the failing field."""


class SnapshotMismatch(ValueError):
    """The snapshot is intact but belongs to a different (bucket, pop
    size, seed, wire version) — resuming from it would not reproduce
    the uninterrupted stream. Named fingerprints in the message, like
    checkpoint.FingerprintMismatch."""


def wire_fingerprint(bucket, pop_size: int, seed: int) -> str:
    """The compatibility stamp: wire version + bucket key + per-lane
    population + the job's seed (the whole lane-RNG identity)."""
    dims = "x".join(str(int(d)) for d in bucket)
    return f"j{WIRE_VERSION}|b{dims}|p{int(pop_size)}|s{int(seed)}"


def pack_state(state, *, bucket, pop_size: int, seed: int,
               gens_done: int, chunks: int, emitted: int,
               best: int, usage: dict | None = None) -> dict:
    """Serialize one job's host PopState + progress cursor into the
    wire object. `state` must be the all-numpy park snapshot (never a
    device array — packing runs on replica handler threads). `usage`
    is the job's cumulative tt-meter at this fence (obs/usage.py) —
    an OPTIONAL wire key, not in _REQUIRED, so pre-meter snapshots
    still validate: a resumed job without a cursor simply meters from
    zero on the survivor (honest, never wrong-by-duplication)."""
    buf = io.BytesIO()
    np.savez(buf, **{f: np.asarray(getattr(state, f))
                     for f in _FIELDS})
    raw = buf.getvalue()
    wire = {"v": WIRE_VERSION,
            "fingerprint": wire_fingerprint(bucket, pop_size, seed),
            "bucket": [int(d) for d in bucket],
            "gens_done": int(gens_done), "chunks": int(chunks),
            "emitted": int(emitted), "best": int(best),
            "crc": zlib.crc32(raw) & 0xFFFFFFFF, "bytes": len(raw),
            "npz": base64.b64encode(raw).decode("ascii")}
    if usage:
        from timetabling_ga_tpu.obs import usage as usage_mod
        wire["usage"] = usage_mod.rounded(usage)
    return wire


def verify_wire(wire, expect_fingerprint: str | None = None) -> bytes:
    """Validate a wire snapshot WITHOUT loading it; returns the raw
    npz bytes. Stdlib-only (the gateway's cache-admission check).

    Raises SnapshotCorrupt on structural damage (naming the failing
    field) and SnapshotMismatch when `expect_fingerprint` is given and
    disagrees (naming both fingerprints)."""
    if not isinstance(wire, dict):
        raise SnapshotCorrupt(
            f"snapshot wire is {type(wire).__name__}, not an object")
    for k in _REQUIRED:
        if k not in wire:
            raise SnapshotCorrupt(f"snapshot wire missing field {k!r}")
    if int(wire["v"]) != WIRE_VERSION:
        # version policy (README "Fleet resume"): there is exactly one
        # live wire version per fleet — mixed versions mean a half-
        # upgraded fleet, and a refused resume falls back to replay
        # (progress lost, correctness kept)
        raise SnapshotMismatch(
            f"snapshot wire version {wire['v']!r} != {WIRE_VERSION} "
            f"(fingerprint {str(wire['fingerprint'])!r})")
    if expect_fingerprint is not None \
            and str(wire["fingerprint"]) != expect_fingerprint:
        raise SnapshotMismatch(
            f"snapshot fingerprint mismatch: "
            f"{str(wire['fingerprint'])!r} != {expect_fingerprint!r} "
            f"— different bucket, pop size, seed, or wire version")
    try:
        raw = base64.b64decode(str(wire["npz"]), validate=True)
    except (ValueError, TypeError) as e:
        raise SnapshotCorrupt(
            f"snapshot field 'npz' is not valid base64: {e}") from None
    if len(raw) != int(wire["bytes"]):
        raise SnapshotCorrupt(
            f"snapshot field 'npz' truncated: {len(raw)} bytes != "
            f"declared {int(wire['bytes'])}")
    crc = zlib.crc32(raw) & 0xFFFFFFFF
    if crc != int(wire["crc"]) & 0xFFFFFFFF:
        raise SnapshotCorrupt(
            f"snapshot field 'npz' CRC mismatch: {crc} != declared "
            f"{int(wire['crc'])}")
    return raw


def unpack_state(wire, expect_fingerprint: str | None = None):
    """verify_wire + deserialize: returns (PopState, meta) where meta
    is {'gens_done', 'chunks', 'emitted', 'best'}. A torn npz that
    survived the CRC (impossible short of a bug, but cheap to guard)
    raises SnapshotCorrupt like checkpoint.load's corrupt classes."""
    raw = verify_wire(wire, expect_fingerprint)
    # lazy: PopState lives in ops.ga (which imports jax) and the npz
    # corruption classes in runtime.checkpoint — neither may load in a
    # gateway process, which only ever calls verify_wire
    from timetabling_ga_tpu.ops import ga
    from timetabling_ga_tpu.runtime.checkpoint import CORRUPT_ERRORS
    try:
        with np.load(io.BytesIO(raw), allow_pickle=False) as z:
            state = ga.PopState(
                **{f: np.array(z[f]) for f in _FIELDS})
    except CORRUPT_ERRORS as e:
        raise SnapshotCorrupt(
            f"snapshot npz payload unreadable: {e!r}") from e
    meta = {k: int(wire[k])
            for k in ("gens_done", "chunks", "emitted", "best")}
    return state, meta


@dataclasses.dataclass
class ShipUnit:
    """One job's shippable park-fence unit: the host state plus the
    exact record prefix emitted up to that fence — built by the
    scheduler ON the drive loop (cheap: references + a list copy) and
    replaced wholesale at every park, so a handler thread reading
    `job.ship` sees one consistent (state, records) pair or the other,
    never a mix. The expensive npz pack happens lazily on the HANDLER
    thread serving `?snapshot=1` (fault site `snapshot_ship`): a hung
    export parks one handler thread, never the drive loop or the
    writer."""

    state: object               # host PopState at the fence
    bucket: tuple
    pop_size: int
    seed: int
    gens_done: int
    chunks: int
    emitted: int
    best: int
    records: list               # the job's stream through this fence
    truncated: bool = False     # records list hit its cap — a resumed
    #                             stream cannot claim identity
    usage: dict | None = None   # the job's cumulative tt-meter at
    #                             this fence (obs/usage.py): the wire
    #                             usage cursor a resumed job continues
    #                             from instead of resetting
    wire: dict | None = None    # lazy pack memo (handler threads may
    #                             race it: both compute the same dict)
    records_bytes: int | None = None  # lazy serialized-size memo of
    #                             `records` (same handler-thread
    #                             discipline as `wire`): the gateway
    #                             budgets its snapshot cache on this
    #                             declared size instead of
    #                             re-measuring the prefix per refresh
    served: bool = False        # fetched at least once — preempt
    #                             drain's "shipped" signal

    def pack(self) -> dict:
        if self.wire is None:
            self.wire = pack_state(
                self.state, bucket=self.bucket, pop_size=self.pop_size,
                seed=self.seed, gens_done=self.gens_done,
                chunks=self.chunks, emitted=self.emitted,
                best=self.best, usage=self.usage)
        return self.wire
