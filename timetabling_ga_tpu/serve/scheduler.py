"""The serve scheduler: pack, time-slice, park, resume.

Turns a queue of heterogeneous solve jobs into a sequence of fused mesh
dispatches:

  PACKING   runnable jobs are grouped by bucket_key (serve/bucket.py):
            only same-bucket jobs share compiled programs, so only they
            can ride one dispatch. Up to `lanes` jobs are stacked along
            the island axis (one lane each) into a single
            engine.cached_lane_runner call — the whole mesh advances
            many tenants at once, and the compile-cache key is the
            bucket shape, never the instance.

  SLICING   a dispatch runs at most `quantum` generations per lane (a
            lane with less budget left runs less — per-lane counts are
            runtime arguments, so no new shapes). Between dispatches is
            a control fence: cancellations, deadlines, and newly
            admitted jobs all take effect there, so a late small job
            waits at most one quantum for a lane — the fairness the
            one-run-per-process engine cannot offer.

  PARKING   a job's population is durable as a host snapshot
            (dispatch_core.fetch_state — the same all-numpy tuple the
            PR-3 fault supervisor rolls and checkpoint.save serializes)
            and is re-placed with dispatch_core.reshard_state at its
            next slice. Parked jobs cost zero device memory, so the
            backlog can exceed the lanes by any factor.

  RESIDENCY while a stacked group's lane assignment is UNCHANGED
            between consecutive quanta (same bucket, same jobs in the
            same lane order) the park/resume round trip is skipped:
            the population stays on device (`_resident`, one entry per
            bucket) and only the compressed trace leaf is fetched.
            The group falls back to a full host park — a "flush" — on
            any repack (lane assignment changed), job finish, pending
            deadline, fault, preempt drain, or snapshot-shipping
            request, so the supervisor's rolling host snapshot and the
            tt-resume wire format are always refreshable at park
            fences. While resident, job.snapshot / job.ship freeze at
            the last host fence (`_resident[bkey]["fence"]` records the
            cursors they match); a handler serving ?snapshot=1 gets
            that older-but-consistent unit, sets the flush-request
            flag so the next fence re-syncs, and marks the job
            ship_hot — a continuously-polled job's group parks every
            fence from then on, keeping a gateway's resume cache
            within one quantum of the live cursor. On a fault the group's
            cursors roll BACK to the fence meta, so the requeued jobs
            re-run from exactly the state their snapshots hold — the
            emitted/best floors absorb the re-run's duplicate
            improvement records and the stream stays bit-identical.
            --no-resident restores the per-quantum park/resume cycle
            (the A/B leg bench.py extra.serve_mesh measures).

  FAIRNESS  bucket groups are served round-robin, and within a group
            jobs are ordered by (priority desc, generations-served asc,
            arrival) — so a long job cannot starve a later short one
            even inside its own bucket.

RNG isolation: lane l of dispatch d runs job j's chunk c with keys
fold_in(key(j.seed), c) — a pure function of the job's own identity and
progress. A job's record stream is therefore bit-identical whether it
ran alone or packed with any mix of co-tenants (pinned by
tests/test_serve.py).

Mesh sizing: the scheduler serves every device the replica owns
(`--mesh-devices 0`, the default, sizes the mesh from jax.devices();
N pins the first N — N=1 is the pre-mesh single-device behaviour).
`islands.local_islands` requires `lanes % devices == 0`, so the
configured lane count is padded UP to the next device multiple
(`islands.pad_lanes`); jobs fill the first `cfg.lanes` lanes and the
padding lanes run zero-generation filler whose device-seconds the
tt-meter split books as `overhead_device_seconds`, never billed to a
tenant. Because a lane's RNG streams are pure functions of (seed,
chunk, generation) — independent of lane position and device count —
per-job record streams are bit-identical across mesh sizes (pinned by
tests/test_serve_mesh.py). Single-PROCESS still by design: multi-host
serving has the same agreement problem as the ROADMAP's multi-host
recovery item.
"""

from __future__ import annotations

import collections
import sys
import time

import numpy as np

from timetabling_ga_tpu.obs import metrics as obs_metrics
from timetabling_ga_tpu.obs import quality as obs_quality
from timetabling_ga_tpu.obs import usage as usage_mod
from timetabling_ga_tpu.obs.spans import NULL_TRACER
from timetabling_ga_tpu.ops import ga
from timetabling_ga_tpu.parallel import islands
from timetabling_ga_tpu.runtime import faults, jsonl
from timetabling_ga_tpu.runtime.config import ServeConfig
from timetabling_ga_tpu.serve import bucket as bucket_mod
from timetabling_ga_tpu.serve import snapshot as snapshot_mod
from timetabling_ga_tpu.serve.queue import Job, JobQueue, JobState

INT_MAX = 2 ** 31 - 1


def _stack_states(snaps, pop: int, n_lanes: int, n_events: int
                  ) -> ga.PopState:
    """Concatenate per-job host snapshots (and zero filler for idle
    lanes) into the (n_lanes * pop, E) stacked host state."""
    parts = list(snaps)
    for _ in range(n_lanes - len(parts)):
        parts.append(ga.PopState(
            slots=np.zeros((pop, n_events), np.int32),
            rooms=np.zeros((pop, n_events), np.int32),
            penalty=np.full((pop,), INT_MAX, np.int32),
            hcv=np.full((pop,), INT_MAX, np.int32),
            scv=np.full((pop,), INT_MAX, np.int32)))
    return ga.PopState(*[np.concatenate([getattr(p, f) for p in parts])
                         for f in ga.PopState._fields])


def _slice_state(host: ga.PopState, lane: int, pop: int) -> ga.PopState:
    """One lane's rows of a stacked host state, copied (the job owns
    its snapshot; the stacked buffer is rebuilt every quantum)."""
    lo, hi = lane * pop, (lane + 1) * pop
    return ga.PopState(*[np.array(getattr(host, f)[lo:hi])
                         for f in ga.PopState._fields])


class Scheduler:
    """Drives a JobQueue through the engine's lane programs."""

    def __init__(self, cfg: ServeConfig, queue: JobQueue, out,
                 now=None, tracer=NULL_TRACER, profiler=None,
                 registry=None, usage=None):
        import jax
        self.cfg = cfg
        self.queue = queue
        self.out = out
        self.tracer = tracer
        self._now = now or time.monotonic
        self._dispatches = 0
        self._overflow_warned = False
        # tt-meter (obs/usage.py UsageLedger, wired by the service
        # under cfg.usage): the drive loop folds each job's meter
        # inline at its park fence (fence-consistent — the snapshot
        # wire ships it) and hands the per-tenant settlement to the
        # ledger's own thread; None = metering off
        self._usage = usage
        # the metrics registry this scheduler reports into — THE
        # process registry by default, a private one when several
        # in-process replicas must keep separate /readyz truths
        # (fleet/replicas.py InProcReplica)
        self._metrics = (obs_metrics.REGISTRY if registry is None
                         else registry)
        # on-demand profiler capture (obs/cost.py ProfileCapture, wired
        # by the service): the step loop only ticks its counter
        self._profiler = profiler
        # queue occupancy is only meaningful at read time: a pull gauge
        # sampled when the registry is snapshotted
        self._metrics.gauge_fn("serve.queue_depth",
                               lambda: len(queue.active()))
        # the admission bound as a gauge: /readyz (obs/http.py
        # readiness) flips NOT-READY when queue_depth reaches it, so a
        # fleet router stops sending work to a replica that would only
        # reject it
        self._metrics.gauge("serve.backlog").set(cfg.backlog)
        self.spec = bucket_mod.BucketSpec(
            event_floor=cfg.bucket_events, room_floor=cfg.bucket_rooms,
            feature_floor=cfg.bucket_features,
            student_floor=cfg.bucket_students, ratio=cfg.bucket_ratio)
        # mesh sizing (module docstring): every device the replica
        # owns by default, the first N under --mesh-devices N. The
        # dispatch width is the configured lane count padded UP to a
        # device multiple (islands.local_islands requires
        # `lanes % devices == 0`); jobs only ever fill the first
        # cfg.lanes lanes — padding lanes are zero-generation filler
        self.mesh = islands.make_mesh(cfg.mesh_devices or None)
        self.lanes = islands.pad_lanes(self.mesh, cfg.lanes)
        self._metrics.gauge("serve.mesh_devices").set(
            self.mesh.devices.size)
        self._metrics.gauge("serve.lanes").set(self.lanes)
        # device-resident groups (module docstring RESIDENCY): bucket
        # key -> {"jids": lane-ordered job-id tuple, "state": the
        # group's device PopState, "fence": {job id: (chunks,
        # gens_done) at the last HOST fence — what job.snapshot
        # matches, and what a fault rolls back to}}
        self._resident: dict = {}
        # snapshot-shipping flush request (set from handler threads via
        # request_flush; consumed at the next control fence)
        self._flush_req = False
        self._metrics.gauge_fn("serve.resident_groups",
                               lambda: len(self._resident))
        # bytes currently parked ON DEVICE across resident groups —
        # what a retire would have to flush through the park fences.
        # The autoscaler's residency-aware victim choice reads both
        # gauges off the gateway's scrape (fleet/autoscaler.py
        # choose_victim): prefer cold replicas, tie-break on fewest
        # resident bytes. Pure host arithmetic over leaf .nbytes —
        # never a device sync (tt-analyze TT306/TT603 discipline).
        self._metrics.gauge_fn("serve.resident_bytes",
                               lambda: float(self._resident_bytes()))
        self.gacfg = ga.GAConfig(
            pop_size=cfg.pop_size,
            ls_steps=max(1, cfg.max_steps // cfg.ls_candidates),
            ls_candidates=cfg.ls_candidates)
        self._rr = 0               # round-robin cursor over buckets
        self._jax = jax

    # -- admission ------------------------------------------------------

    def prepare(self, job: Job) -> None:
        """Pad the instance to its bucket and place the problem data.
        Called by the service BEFORE queue.submit: anything that can
        fail about the instance (over-bound buckets, placement errors)
        fails here, while the job is still nobody's — the queue never
        holds a half-admitted job with no bucket."""
        job.padded = bucket_mod.pad_problem(job.problem, self.spec)
        job.bucket = bucket_mod.bucket_key(job.problem, self.spec)
        job.pa_dev = job.padded.device_arrays()

    def prepare_edit(self, job: Job, base_wire) -> None:
        """Warm-start an edit job from its base snapshot (serve/
        editsolve.py; README "Incremental re-solve"). Called by the
        service AFTER prepare (the transplant needs the padded
        instance and bucket) and only when the job carries no resume
        wire of its own — a failed-over edit job's OWN snapshot is
        newer than any re-transplant and takes precedence.

        Success parks the transplanted population in job.resume_wire
        (admit's `_admit_resumed` seam restores it exactly like any
        other warm start). ANY failure — cross-bucket edit, missing or
        undecodable base snapshot, population mismatch — DEMOTES the
        job to a cold solve of the edited instance: one faultEntry
        (site=edit action=demote), the serve.jobs_edit_demoted
        counter, never an error. Admission-time host work only: this
        is the one place the scheduler touches editsolve, and it is
        outside every dispatch loop (tt-analyze TT309)."""
        from timetabling_ga_tpu.serve import editsolve
        self._metrics.counter("serve.jobs_edit").inc()
        try:
            faults.maybe_fail("edit")
            job.resume_wire = editsolve.transplant(
                job.padded, job.edit_map, base_wire,
                bucket=job.bucket, pop_size=self.cfg.pop_size,
                seed=job.seed)
        except (KeyboardInterrupt,):
            raise
        except BaseException as e:
            job.edit_demoted = True
            job.resume_wire = None
            jsonl.fault_entry(self.out, "edit", "demote", e, 0, 0, 0,
                              self.tracer.now(), job=job.id)
            self._metrics.counter("serve.jobs_edit_demoted").inc()

    def admit(self, job: Job) -> None:
        """Record the admission (after queue.submit succeeds). The job
        gets its causal flow id here — every span of its life (admit →
        pack → quantum → park → resume → finalize) carries it, so
        `tt trace --job ID` renders one connected timeline across
        lanes, parks, and co-tenants. A job that ARRIVED with a flow
        (the fleet gateway's X-TT-Flow header, threaded through
        SolveService.submit) keeps it: the replica-side spans then
        continue the gateway's cross-process chain instead of opening
        a local one.

        A job that arrived with a WARM-START snapshot (a failover
        resubmission, a preempted job's re-placement, or a client warm
        start — serve/snapshot.py) is admitted directly as a PARKED
        job: init is skipped, the record stream continues from the
        restored `emitted` floor (duplicate-free by the same floor
        rule every park fence uses), and the only seam is a
        `faultEntry site=fleet action=resume` — which strip_timing
        drops, so the concatenated stream is identical to an
        uninterrupted solve's. A snapshot that fails validation falls
        back to a fresh solve (replay) with a faultEntry, never an
        error: a poisoned snapshot may cost progress, not the job."""
        if not job.flow:
            job.flow = self.tracer.new_flow()
        resumed = (job.resume_wire is not None
                   and self._admit_resumed(job))
        if resumed and not (job.mode == "edit" and job.count_usage):
            self._metrics.counter("serve.jobs_admitted").inc()
            return
        # an edit job's FIRST admission falls through even when its
        # transplant wire resumed it (count_usage distinguishes first
        # admission from a fleet failover resend): the admitted
        # jobEntry with the mode=edit tag and the tenant jobs count
        # must happen exactly once, and the transplant path is the
        # edit job's normal birth, not a recovery seam
        with self.tracer.span("admit", cat="serve", job=job.id,
                              flow=job.flow):
            extra = {}
            if job.tenant != usage_mod.DEFAULT_TENANT:
                # the tenant tag rides the lifecycle record so a log
                # alone maps jobs to tenants; absent for the default
                # tenant, keeping untagged streams byte-identical to
                # pre-meter ones
                extra["tenant"] = job.tenant
            if job.mode != "solve":
                extra["mode"] = job.mode
                if job.edit_of:
                    extra["edit_of"] = job.edit_of
                if job.edit_demoted:
                    extra["demoted"] = True
            self._ship_rec(job, jsonl.job_entry(
                self.out, job.id, "admitted",
                bucket=list(job.bucket),
                generations=job.generations,
                priority=job.priority, **extra))
        self._metrics.counter("serve.jobs_admitted").inc()
        if self._usage is not None and job.count_usage:
            # a FRESH job joins its tenant's jobs count; resumed
            # re-admissions (the early return above) and fleet
            # RESENDS (count_usage=False — a failover REPLAY also
            # lands here, as a fresh admission) do not: the first
            # replica counted them, and the fleet aggregation SUMS
            # tenant ledgers (obs/usage.aggregate)
            self._usage.job(job.id, job.tenant)

    def _ship_rec(self, job: Job, rec: dict) -> None:
        """Mirror one just-emitted record into the job's ship prefix
        (the records a shipped snapshot travels with). Bounded ring
        (the JobTail discipline — a deque, so the pathological
        million-improvement stream costs O(1) per record on the drive
        loop, not an O(cap) list shift): over the cap the OLDEST drop
        and the unit is marked truncated — resume still works,
        identity is honestly disclaimed."""
        rs = job.ship_records
        if not isinstance(rs, collections.deque):
            rs = job.ship_records = collections.deque(
                rs, maxlen=snapshot_mod.SHIP_RECORDS_CAP)
        if len(rs) == rs.maxlen:
            job.ship_truncated = True
        rs.append(rec)

    def _admit_resumed(self, job: Job) -> bool:
        """Warm-start admission from `job.resume_wire`. True on
        success (job is PARKED with restored progress); False falls
        back to a fresh solve. Fault site `resume` fires here — ANY
        failure, including an injected thread death, is absorbed into
        the replay fallback so a bad snapshot can never stall the
        drive loop or touch co-tenant jobs (tests/test_resume.py)."""
        pop = self.cfg.pop_size
        t0 = self._now()
        wire, job.resume_wire = job.resume_wire, None
        try:
            faults.maybe_fail("resume")
            expect = snapshot_mod.wire_fingerprint(job.bucket, pop,
                                                   job.seed)
            state, meta = snapshot_mod.unpack_state(
                wire, expect_fingerprint=expect)
            if tuple(state.slots.shape) != (pop,
                                            job.padded.n_events):
                raise snapshot_mod.SnapshotMismatch(
                    f"snapshot population shape "
                    f"{tuple(state.slots.shape)} != "
                    f"({pop}, {job.padded.n_events}) for bucket "
                    f"{job.bucket}")
        except (KeyboardInterrupt,):
            raise
        except BaseException as e:
            jsonl.fault_entry(self.out, "resume", "replay", e, 0, 0,
                              0, self.tracer.now(), job=job.id)
            self._metrics.counter("serve.jobs_resume_rejected").inc()
            return False
        job.snapshot = state
        job.gens_done = meta["gens_done"]
        job.chunks = meta["chunks"]
        job.emitted = meta["emitted"]
        job.best = meta["best"]
        job.resumed_at = meta["gens_done"]
        job.state = JobState.PARKED
        # tt-meter continuity (README "Usage metering"): the wire's
        # usage cursor seeds the job's meter so a failed-over or
        # preempted job CONTINUES counting instead of resetting — the
        # per-job view and the settle total stay cumulative across
        # incarnations (the tenant LEDGER, by contrast, only ever
        # receives this replica's own deltas)
        cursor = wire.get("usage")
        if isinstance(cursor, dict):
            job.usage = usage_mod.add(None, cursor)
        # the resumed job ships again from admission: a preempt before
        # its first local quantum re-ships the SAME snapshot (empty
        # continuation prefix — the gateway accumulates prefixes)
        job.ship = snapshot_mod.ShipUnit(
            state=state, bucket=job.bucket, pop_size=pop,
            seed=job.seed, gens_done=job.gens_done, chunks=job.chunks,
            emitted=job.emitted, best=job.best, records=[],
            usage=dict(job.usage), wire=dict(wire))
        # the seam: ONE faultEntry (strip_timing drops it — the
        # resumed stream stays in the identity domain) + the
        # `recover` span tt stats turns into the job's `recovered`
        # latency component
        jsonl.fault_entry(
            self.out, "fleet", "resume",
            f"resumed from shipped snapshot at gen "
            f"{meta['gens_done']}", 0, 0, 0, self.tracer.now(),
            job=job.id, gens=meta["gens_done"],
            chunks=meta["chunks"])
        self.tracer.record("recover", t0, self._now() - t0,
                           cat="serve", job=job.id, flow=job.flow,
                           gens=meta["gens_done"])
        self._metrics.counter("serve.jobs_resumed").inc()
        return True

    # -- backpressure ---------------------------------------------------

    def _shed(self) -> None:
        """Registry-driven load shedding at the control fence: while
        `serve.queue_depth` or `writer.queue_depth` sits at/over its
        configured high-water mark (ServeConfig shed_queue_hwm /
        shed_writer_hwm; 0 disables), release the LOWEST-priority
        runnable job (latest arrival among equals — the work the
        ordering would serve last anyway). The scheduler reads its OWN
        registry — the same numbers /metrics scrapes and /readyz
        derives from — so what the dashboard calls overloaded and what
        the scheduler sheds can never disagree. Every shed is a
        jobEntry `shed` record + the serve.jobs_shed counter."""
        q_hwm = self.cfg.shed_queue_hwm
        w_hwm = self.cfg.shed_writer_hwm
        if q_hwm <= 0 and w_hwm <= 0:
            return

        def depth(name):
            v = self._metrics.gauge(name).value
            return 0.0 if v != v else v        # nan (unbound) = no load

        while True:
            over = None
            if q_hwm > 0 and depth("serve.queue_depth") >= q_hwm:
                over = "queue_hwm"
            elif w_hwm > 0 and depth("writer.queue_depth") >= w_hwm:
                over = "writer_hwm"
            if over is None:
                return
            victims = self.queue.ready()
            if not victims:
                return
            job = victims[-1]          # lowest priority, most-served,
            #                            latest arrival — ready()'s
            #                            order reversed
            job.state = JobState.SHED
            job.finished_t = self._now()
            job.error = f"shed ({over})"
            job.snapshot = None
            job.ship = None
            job.ship_records = []
            with self.tracer.span("shed", cat="serve", job=job.id,
                                  flow=job.flow, reason=over):
                jsonl.job_entry(self.out, job.id, "shed", reason=over,
                                priority=job.priority,
                                gens=job.gens_done)
            self._metrics.counter("serve.jobs_shed").inc()
            if over == "writer_hwm":
                # shedding queued jobs cannot drain the WRITER queue
                # (only the worker thread does); one shed per fence
                # bounds the reaction while the backlog of records
                # clears
                return

    # -- one dispatch cycle --------------------------------------------

    def _reap(self) -> None:
        """Deadline pass at the control fence: finalize what ran out of
        wall clock with its best-so-far (a serving deadline is a budget
        cut, not an error — unless the job never got a single slice)."""
        now = self._now()
        for job in self.queue.active():
            if (job.deadline_s is not None
                    and now - job.submitted_t > job.deadline_s):
                if job.snapshot is not None:
                    # a resident job's snapshot is the LAST host
                    # fence's — park its group first so the finalize
                    # reads the generations it actually ran
                    self._flush_job(job, "deadline")
                    self._finalize(job, deadline_hit=True)
                else:
                    job.state = JobState.FAILED
                    job.finished_t = now
                    job.error = "deadline before first slice"
                    jsonl.job_entry(self.out, job.id, "failed",
                                    reason="deadline", gens=0)
                    self._metrics.counter("serve.jobs_failed").inc()

    def _buckets_ready(self) -> list[tuple]:
        seen: list[tuple] = []
        for job in self.queue.ready():
            if job.bucket not in seen:
                seen.append(job.bucket)
        return seen

    def step(self) -> bool:
        """Run one fused dispatch for the next bucket group (round-
        robin). Returns True while any runnable job remains. The top of
        every step is the control fence: deadline reaping and
        backpressure shedding (both registry-visible) happen before the
        next pack."""
        if self._flush_req:
            # a handler thread asked for fresh shippable snapshots
            # (?snapshot=1 on a resident job): park every resident
            # group at THIS fence — the drive loop is the only thread
            # allowed to touch the device (TT605)
            self._flush_req = False
            self.flush_resident("request")
        self._shed()
        self._reap()
        buckets = self._buckets_ready()
        if not buckets:
            if self._resident:
                # nothing runnable but device state lingers (the
                # group's jobs all went terminal between fences):
                # park/free it rather than hold device memory idle
                self.flush_resident("idle")
            return False
        bkey = buckets[self._rr % len(buckets)]
        self._rr += 1

        lanes = self.lanes
        pop = self.cfg.pop_size
        jobs = self.queue.ready(bkey)[:self.cfg.lanes]
        # every span of this dispatch cycle is tagged with the packed
        # jobs' ids AND their flow ids: one span advances many causal
        # chains, and `tt trace --job ID` follows exactly one of them
        jids = [j.id for j in jobs]
        flows = [j.flow for j in jobs]
        with self.tracer.span("pack", cat="serve", bucket=list(bkey),
                              job=jids, flow=flows):
            fresh = [j for j in jobs if j.snapshot is None]
            if fresh:
                self._init_jobs(fresh)
            for job in jobs:
                if job.state != JobState.RUNNING:
                    job.state = JobState.RUNNING

            Ep = jobs[0].padded.n_events
            pa_stack = self._jax.tree.map(
                lambda *ls: self._jax.numpy.stack(ls),
                *[j.pa_dev for j in jobs],
                *([jobs[0].pa_dev] * (lanes - len(jobs))))
            seeds = np.zeros((lanes,), np.int32)
            chunks = np.zeros((lanes,), np.int32)
            gens = np.zeros((lanes,), np.int32)
            for lane, job in enumerate(jobs):
                seeds[lane] = job.seed
                chunks[lane] = job.chunks
                gens[lane] = min(self.cfg.quantum, job.remaining())

        self._dispatches += 1
        self._metrics.counter("serve.dispatches").inc()
        try:
            self._advance(jobs, pa_stack, seeds, chunks, gens, Ep,
                          jids, flows)
            self._metrics.counter("serve.gens").inc(int(gens.sum()))
        except Exception as e:
            # serve-path fault recovery (README "Fleet resume"): the
            # run supervisor's classify/rehydrate logic applied at JOB
            # granularity — only this dispatch's jobs are touched
            self._recover_quantum(jobs, e)
        if self._profiler is not None:
            self._profiler.on_dispatch()
        if (self.cfg.obs and self.cfg.metrics_every > 0
                and self._dispatches % self.cfg.metrics_every == 0):
            jsonl.metrics_entry(self.out, self._metrics.snapshot(),
                                ts=self.tracer.now())
        return bool(self.queue.ready())

    def _advance(self, jobs, pa_stack, seeds, chunks, gens, Ep,
                 jids, flows) -> None:
        """One resume → quantum → park cycle for an already-packed
        group. On a dispatch/fetch fault the possibly-poisoned device
        state is deleted HERE (islands.delete_state — donation may
        already have consumed leaves; both are fine) and the error
        re-raised — every job's park snapshot and host problem data
        are untouched, so _recover_quantum requeues the group from
        exactly where it stood. Fault site `quantum` fires once per
        call, right before the lane dispatch."""
        from timetabling_ga_tpu.runtime import engine
        try:
            self._cycle(jobs, pa_stack, seeds, chunks, gens,
                        Ep, jids, flows, engine)
        except BaseException:
            islands.delete_state(self._inflight)
            raise
        finally:
            self._inflight = None

    # the in-flight device state of the current _cycle, held on self so
    # _advance can delete it when the cycle raises mid-dispatch
    _inflight = None

    def _cycle(self, jobs, pa_stack, seeds, chunks, gens, Ep,
               jids, flows, engine) -> None:
        from timetabling_ga_tpu.runtime import dispatch_core as dcore
        lanes = self.lanes
        pop = self.cfg.pop_size
        bkey = jobs[0].bucket
        jid_t = tuple(jids)
        # tt-meter: the fence instant the wait components are measured
        # against — queue_seconds (admission -> first dispatch) and
        # park_seconds (previous fence -> this dispatch) are computed
        # here but APPLIED only at the successful park below, so a
        # faulted dispatch charges nothing twice (the lost wall lands
        # in the next successful fence's park component)
        t_fence0 = self._now()
        entry = self._resident.get(bkey)
        if entry is not None and (entry["jids"] != jid_t
                                  or not self.cfg.resident
                                  or self._flush_req):
            # lane assignment changed (or a flush is pending): park
            # the old group to host FIRST, so this pack resumes every
            # member — kept or swapped out — from a fresh snapshot
            self._flush_bucket(bkey, "repack")
            entry = None
        resident = entry is not None
        with self.tracer.span("resume", cat="serve", job=jids,
                              flow=flows, resident=resident):
            if resident:
                # the group's population never left the device: the
                # previous quantum's output is this dispatch's input
                # (donation consumes it below, as always)
                state = self._inflight = entry["state"]
                self._metrics.counter("serve.resident_hits").inc()
            else:
                # parked host snapshots -> one stacked device placement
                host0 = _stack_states([j.snapshot for j in jobs], pop,
                                      lanes, Ep)
                state = self._inflight = dcore.reshard_state(host0,
                                                             self.mesh)
                self._metrics.counter("serve.resume_bytes").inc(
                    dcore.state_nbytes(host0))
                # the host fence this device state matches: a fault in
                # any LATER resident quantum rolls the group's cursors
                # back here (the snapshots never advanced past it)
                entry = {"jids": jid_t, "state": None,
                         "fence": {j.id: (j.chunks, j.gens_done)
                                   for j in jobs}}
        with self.tracer.span("quantum", cat="device", job=jids,
                              flow=flows, gens=int(gens.sum())):
            faults.maybe_fail("quantum")
            runner, _ = engine.cached_lane_runner(
                self.mesh, self.gacfg, self.cfg.quantum, lanes,
                donate=True, trace_mode=self.cfg.trace_mode,
                quality=self.cfg.quality)
            tq0 = self._now()
            state, trace = runner(pa_stack, seeds, chunks, state, gens)
            self._inflight = state
            trace = dcore.fetch_leaf(trace)  # (lanes, quantum, 2)|packed
            tq_wall = self._now() - tq0
            # device wall under dispatch, for the serve_mesh bench
            # leg's host-gap metric (wall - quantum_seconds = time the
            # device sat idle between quanta)
            self._metrics.counter("serve.quantum_seconds").inc(tq_wall)
            # live roofline for the serve path, same gauges and same
            # formula as the engine's (obs/cost.py owns it): the lane
            # program's compile-time counts over this quantum's wall.
            # Skipped when THIS call paid the bucket's compile — that
            # wall time is compile+execute and would crater the gauges
            # (compile.seconds carries it under its own name)
            if not getattr(runner, "last_compiled", False):
                from timetabling_ga_tpu.obs import cost as obs_cost
                obs_cost.set_live_roofline(
                    getattr(runner, "last_cost", None), tq_wall)
        # park to host unless the group can stay device-resident: a
        # finishing job needs its final snapshot, a pending flush
        # request needs fresh shippable units, --no-resident always
        # parks, a ship_hot job (someone polls its ?snapshot=1 —
        # freshness beats residency for it) parks every fence, and a
        # job that has never shipped parks ONCE first — the fleet's
        # rolling-snapshot invariant is that every active job has a
        # shippable unit soon after its first quantum, so residency
        # starts at the second consecutive quantum of an unchanged
        # pack. The jid-tuple check at the NEXT resume catches
        # repacks; everything else (fault, deadline, preempt) flushes
        # through its own fence hook.
        stay = (self.cfg.resident and not self._flush_req
                and all(job.ship is not None and not job.ship_hot
                        for job in jobs)
                and not any(int(gens[lane]) >= job.remaining()
                            for lane, job in enumerate(jobs)))
        with self.tracer.span("park", cat="serve", job=jids,
                              flow=flows, resident=stay):
            if stay:
                entry["state"] = state
                self._resident[bkey] = entry
                host = None
            else:
                # fetch BEFORE dropping the entry: if this fetch
                # faults mid-resident-run, _recover_quantum still
                # finds the fence meta to roll the cursors back to
                host = dcore.fetch_state(state)
                self._resident.pop(bkey, None)
                self._metrics.counter("serve.park_bytes").inc(
                    dcore.state_nbytes(host))
            # the telemetry decode shared with the engine
            # (dispatch_core.decode_telemetry): quality split, effective
            # trace-mode packing and overflow surfacing all match the
            # engine's retire path record-for-record
            events, _, qrows, self._overflow_warned = \
                dcore.decode_telemetry(
                    trace, self.cfg.quality, self.cfg.trace_mode,
                    metrics=self._metrics,
                    overflow_counter="serve.trace_delta_overflow",
                    overflow_warned=self._overflow_warned,
                    warn_label="serve ")
            q_dec = None
            if qrows is not None:
                # decode only the lanes that carried real jobs: filler
                # lanes hold INT_MAX padding whose "diversity" means
                # nothing. Per-job qualityEntry records go out under
                # --obs; the cross-lane aggregate feeds the same
                # quality.* registry families the engine uses.
                q_dec = obs_quality.decode_rows(qrows[:len(jobs)])
                q_agg = obs_quality.aggregate(q_dec)
                for name, v in q_agg["counters"].items():
                    self._metrics.counter(name).inc(v)
                for name, v in q_agg["gauges"].items():
                    self._metrics.gauge(name).set(v)
            now = self._now()
            deltas, meter_payload = self._meter_quantum(
                jobs, gens, tq_wall, runner, t_fence0)
            for lane, job in enumerate(jobs):
                if host is not None:
                    job.snapshot = _slice_state(host, lane, pop)
                job.chunks += 1
                job.gens_done += int(gens[lane])
                if deltas is not None:
                    # fold THIS lane's share into the job's cumulative
                    # meter (a NEW dict — GET /v1/usage handlers read
                    # one fence's meter or the next, never a torn mix)
                    job.usage = usage_mod.add(job.usage, deltas[lane])
                    if job.first_work_t is None:
                        job.first_work_t = t_fence0
                    job.last_fence_t = now
                for _g, h, s in events[lane]:
                    rep = jsonl.reported_best(h, s)
                    if rep < job.best:
                        job.best = rep
                    if rep < job.emitted:
                        job.emitted = rep
                        self._ship_rec(job, jsonl.log_entry(
                            self.out, 0, 0, rep,
                            now - job.submitted_t, job=job.id))
                if q_dec is not None and self.cfg.obs:
                    jsonl.quality_entry(
                        self.out, obs_quality.lane_payload(q_dec, lane),
                        ts=self.tracer.now(), job=job.id,
                        gens=int(gens[lane]))
                job.state = JobState.PARKED
                if job.remaining() == 0:
                    self._finalize(job)
                elif host is not None:
                    # the park fence IS the ship fence (README "Fleet
                    # resume"): replace the job's shippable unit
                    # wholesale — state + the exact record prefix
                    # through this fence, one consistent pair for any
                    # handler thread serving ?snapshot=1. A resident
                    # job keeps its LAST host fence's unit (older but
                    # consistent — request_flush re-syncs it)
                    job.ship = snapshot_mod.ShipUnit(
                        state=job.snapshot, bucket=job.bucket,
                        pop_size=pop, seed=job.seed,
                        gens_done=job.gens_done, chunks=job.chunks,
                        emitted=job.emitted, best=job.best,
                        records=list(job.ship_records),
                        truncated=job.ship_truncated,
                        usage=dict(job.usage))
            if meter_payload is not None:
                # per-tenant settlement rides the ledger's own thread
                # (an O(1) bounded append — the fault-site `usage`
                # isolation contract); the usageEntry it emits carries
                # the EXACT per-lane shares, summing to the dispatch
                # totals (the conservation invariant)
                self._usage.dispatch(meter_payload)

    def _meter_quantum(self, jobs, gens, tq_wall, runner, t_fence0):
        """tt-meter attribution for one retired quantum (README "Usage
        metering"): split the dispatch's measured device wall (minus
        any compile the same call paid — attributed separately as
        compile amortization), the lane program's compile-time FLOP
        count, and the executed generations across the co-tenant lanes
        proportionally to the generations each lane actually ran —
        `usage_mod.split`, whose shares sum to the totals EXACTLY (the
        pinned conservation invariant). Per-job wait components
        (queue_seconds once at first work, park_seconds since the last
        fence) ride the same delta. Returns (per-lane deltas, ledger
        payload), or (None, None) with metering off. Pure host dict
        arithmetic on the drive loop; everything slower (tenant folds,
        registry bumps, usageEntry emission) happens on the ledger's
        own thread."""
        if self._usage is None:
            return None, None
        gens_l = [int(gens[lane]) for lane in range(len(jobs))]
        compiled = bool(getattr(runner, "last_compiled", False))
        compile_s = (float(getattr(runner, "last_compile_s", 0.0))
                     if compiled else 0.0)
        exec_s = max(0.0, float(tq_wall) - compile_s)
        cost = getattr(runner, "last_cost", None) or {}
        flops = float(cost.get("flops", 0.0))
        # idle-lane device-seconds are OVERHEAD, not tenant work: a
        # dispatch reserves the whole padded lane width (mesh sizing,
        # module docstring) whether or not every lane carries a job —
        # the idle fraction lands in the payload's
        # `overhead_device_seconds`, and only the live-lane share is
        # split across tenants (the conservation invariant checks
        # lane shares against the ATTRIBUTED total)
        idle = self.lanes - len(jobs)
        overhead_raw = exec_s * idle / float(self.lanes) if idle else 0.0
        exec_s -= overhead_raw
        overhead_s, _ = usage_mod.split(overhead_raw, [1])
        # dyadic-grid splits (obs/usage.split): the recorded totals
        # are the QUANTIZED ones, so lane shares sum to them exactly —
        # seconds on the ~ns default grid, FLOPs on the integer grid
        exec_s, dev_shares = usage_mod.split(exec_s, gens_l)
        flops, flop_shares = usage_mod.split(flops, gens_l, quantum=1.0)
        compile_s, comp_shares = usage_mod.split(compile_s, gens_l)
        deltas = []
        lanes_out = []
        for lane, job in enumerate(jobs):
            queued = (max(0.0, t_fence0 - job.submitted_t)
                      if job.first_work_t is None else 0.0)
            parked = (max(0.0, t_fence0 - job.last_fence_t)
                      if job.last_fence_t is not None else 0.0)
            delta = {"gens": gens_l[lane], "dispatches": 1,
                     "device_seconds": dev_shares[lane],
                     "compile_seconds": comp_shares[lane],
                     "flops": flop_shares[lane],
                     "queue_seconds": queued,
                     "park_seconds": parked}
            deltas.append(delta)
            # UNROUNDED shares on the wire: the usageEntry's per-lane
            # values must sum bit-exactly to its totals (bench
            # extra.usage and tests/test_usage.py assert it on the
            # emitted record, not on an internal float)
            lanes_out.append({"job": job.id, "tenant": job.tenant,
                              **delta})
        payload = {"dispatch": self._dispatches,
                   "bucket": list(jobs[0].bucket),
                   "gens": sum(gens_l),
                   "device_seconds": exec_s,
                   "overhead_device_seconds": overhead_s,
                   "compile_seconds": compile_s,
                   "flops": flops,
                   "lanes": lanes_out}
        return deltas, payload

    def _recover_quantum(self, jobs, exc) -> None:
        """Serve-path fault recovery: the engine supervisor's
        classify/rehydrate logic at JOB granularity (ROADMAP item 1's
        named payoff). The poisoned device state is already deleted
        (_advance); here the compiled lane programs bound to the mesh
        are purged (they may reference dead buffers — the supervisor's
        rule), and each job of the faulted dispatch is REQUEUED from
        its park snapshot: chunks/gens_done match the snapshot (never
        advanced on a parked run; rolled back to the fence meta on a
        resident one — below), so the re-run repeats the identical
        chunk(s) and the record stream stays bit-identical to an
        uninjected run's (the per-job emitted floor absorbs any
        records the faulted dispatch — or a rolled-back resident
        quantum — got out before dying). A non-transient error — or a job over its
        --max-job-recoveries budget — fails THAT JOB alone with a
        terminal jobEntry; co-tenants, other buckets, the writer, and
        the service itself run on untouched."""
        from timetabling_ga_tpu.runtime import dispatch_core as dcore
        from timetabling_ga_tpu.runtime import retry
        dcore.purge_programs(self.mesh)
        # a RESIDENT group's cursors ran ahead of its host snapshots;
        # roll them back to the fence meta so the requeued jobs re-run
        # from exactly the state their snapshots hold. The re-run's
        # quanta repeat deterministically (RNG is pure in (seed, chunk,
        # gen)) and the emitted/best floors absorb the duplicate
        # improvement records, so the stream stays bit-identical. The
        # re-run device time IS re-metered — the device really runs it
        # twice, and tt-meter bills consumption, not progress.
        entry = self._resident.pop(jobs[0].bucket, None)
        if entry is not None:
            islands.delete_state(entry["state"])
            for job in jobs:
                if (job.state not in JobState.TERMINAL
                        and job.id in entry["fence"]):
                    job.chunks, job.gens_done = entry["fence"][job.id]
        transient = retry.is_transient(exc)
        now = self.tracer.now()
        for job in jobs:
            if job.state in JobState.TERMINAL:
                # a fault late in the park loop (e.g. a dying writer)
                # can interrupt the dispatch AFTER some lanes already
                # finalized — a settled job must never be resurrected
                continue
            job.recoveries += 1
            if transient and job.recoveries \
                    <= self.cfg.max_job_recoveries:
                job.state = JobState.PARKED
                jsonl.fault_entry(self.out, "quantum", "requeue", exc,
                                  0, job.recoveries, 0, now,
                                  job=job.id, gens=job.gens_done)
                self._metrics.counter("serve.job_recoveries").inc()
            else:
                jsonl.fault_entry(self.out, "quantum", "abort", exc,
                                  0, job.recoveries, 0, now,
                                  job=job.id, gens=job.gens_done)
                jsonl.job_entry(self.out, job.id, "failed",
                                reason="quantum fault: "
                                       + str(exc)[:120],
                                gens=job.gens_done)
                job.state = JobState.FAILED
                job.error = f"quantum fault: {str(exc)[:200]}"
                job.finished_t = self._now()
                job.snapshot = None
                job.ship = None
                job.ship_records = []
                self._metrics.counter("serve.jobs_failed").inc()

    # -- residency flush fences ----------------------------------------

    def _flush_bucket(self, bkey, reason: str) -> None:
        """Park ONE device-resident group to host: fetch its stacked
        state, refresh every live member's snapshot + shippable unit
        (the park fence IS the ship fence), free the device buffers
        and drop the entry. THE park fence for resident jobs — every
        other fallback path (repack, deadline, preempt, shipping
        request, idle teardown) funnels through here.

        Fault-safe: if the fetch dies, the group rolls back to its
        fence meta (cursors re-match the stale snapshots) before the
        error propagates — a failed flush costs resident progress,
        never consistency."""
        from timetabling_ga_tpu.runtime import dispatch_core as dcore
        entry = self._resident.pop(bkey, None)
        if entry is None:
            return
        pop = self.cfg.pop_size
        live = [(lane, self.queue.get(jid))
                for lane, jid in enumerate(entry["jids"])
                if jid in self.queue]
        live = [(lane, job) for lane, job in live
                if job.state not in JobState.TERMINAL]
        if not live:
            # every member went terminal (cancel/shed) since the last
            # quantum: nothing to park, just free the device buffers
            islands.delete_state(entry["state"])
            return
        try:
            with self.tracer.span("flush", cat="serve",
                                  bucket=list(bkey), reason=reason,
                                  job=[job.id for _, job in live]):
                host = dcore.fetch_state(entry["state"])
        except BaseException:
            islands.delete_state(entry["state"])
            for _, job in live:
                if job.id in entry["fence"]:
                    job.chunks, job.gens_done = entry["fence"][job.id]
            raise
        islands.delete_state(entry["state"])
        self._metrics.counter("serve.park_bytes").inc(
            dcore.state_nbytes(host))
        for lane, job in live:
            job.snapshot = _slice_state(host, lane, pop)
            job.ship = snapshot_mod.ShipUnit(
                state=job.snapshot, bucket=job.bucket,
                pop_size=pop, seed=job.seed,
                gens_done=job.gens_done, chunks=job.chunks,
                emitted=job.emitted, best=job.best,
                records=list(job.ship_records),
                truncated=job.ship_truncated,
                usage=dict(job.usage))
        self._metrics.counter("serve.resident_flushes").inc()

    def _flush_job(self, job: Job, reason: str) -> None:
        """Park the resident group CONTAINING `job`, if any. Absorbs a
        flush fault (the job is rolled back and proceeds from its last
        host fence — consistent, just less progressed)."""
        entry = self._resident.get(job.bucket)
        if entry is None or job.id not in entry["jids"]:
            return
        try:
            self._flush_bucket(job.bucket, reason)
        except Exception as e:
            jsonl.fault_entry(self.out, "flush", "rollback", e, 0, 0,
                              0, self.tracer.now(), job=job.id)

    def flush_resident(self, reason: str) -> int:
        """Park EVERY device-resident group to host now. Drive-loop
        threads only (it touches the device) — handler threads use
        request_flush instead. The fleet Replica calls this at its
        preempt fence so every shipped snapshot reflects real
        progress. A group whose flush faults rolls back to its last
        host fence and is skipped (its jobs stay consistent). Returns
        the number of groups parked."""
        n = 0
        for bkey in list(self._resident):
            try:
                self._flush_bucket(bkey, reason)
                n += 1
            except Exception as e:
                jsonl.fault_entry(self.out, "flush", "rollback", e,
                                  0, 0, 0, self.tracer.now())
        return n

    def _resident_bytes(self) -> int:
        """Total device bytes across resident groups (the
        serve.resident_bytes gauge) — leaf `.nbytes` sums, no device
        sync. Tolerates a group mid-eviction (dict snapshot)."""
        from timetabling_ga_tpu.runtime import dispatch_core as dcore
        return sum(dcore.state_nbytes(g.get("state"))
                   for g in list(self._resident.values()))

    def request_flush(self) -> None:
        """Ask the drive loop to park every resident group at its next
        control fence. Safe from any thread (handlers serving
        ?snapshot=1 on a resident job call this — they must never
        touch the device themselves, the TT605 discipline); until the
        fence runs, shipped units stay the last host fence's
        older-but-consistent pair."""
        self._flush_req = True

    def drive(self) -> None:
        """Run dispatches until no runnable job remains."""
        while self.step():
            pass

    # -- job endpoints --------------------------------------------------

    def _init_jobs(self, jobs: list[Job]) -> None:
        """First slices, BATCHED: all freshly scheduled jobs of the
        group initialize in ONE lane-stacked dispatch (the same lane
        width as the runner, so each bucket compiles exactly one init
        program). Each lane draws from key(its job's seed) alone, so
        batched init preserves the co-tenant-independence contract.
        Idle lanes replicate the first job's data and are discarded."""
        from timetabling_ga_tpu.runtime import dispatch_core as dcore
        from timetabling_ga_tpu.runtime import engine
        lanes = self.lanes
        with self.tracer.span("init", cat="device",
                              job=[j.id for j in jobs],
                              flow=[j.flow for j in jobs]):
            init = engine.cached_lane_init(self.mesh, self.cfg.pop_size,
                                           self.gacfg, n_lanes=lanes)
            pa_stack = self._jax.tree.map(
                lambda *ls: self._jax.numpy.stack(ls),
                *[j.pa_dev for j in jobs],
                *([jobs[0].pa_dev] * (lanes - len(jobs))))
            seeds = np.zeros((lanes,), np.int32)
            for lane, job in enumerate(jobs):
                seeds[lane] = job.seed
            host = dcore.fetch_state(init(pa_stack, seeds))
        for lane, job in enumerate(jobs):
            job.snapshot = _slice_state(host, lane, self.cfg.pop_size)
            self._ship_rec(job, jsonl.job_entry(
                self.out, job.id, "started", bucket=list(job.bucket)))

    def _finalize(self, job: Job, deadline_hit: bool = False) -> None:
        """Emit the job's endTry records from its snapshot (row 0 is
        the lane's lex-best individual) and mark it DONE. The span
        closes the job's flow; the job_seconds observation carries the
        job id as its exemplar, so a p99 spike on the scrape dashboard
        joins straight back to this jobEntry lifecycle."""
        with self.tracer.span("finalize", cat="serve", job=job.id,
                              flow=job.flow):
            self._finalize_records(job, deadline_hit)

    def _finalize_records(self, job: Job, deadline_hit: bool) -> None:
        snap = job.snapshot
        hcv = int(snap.hcv[0])
        scv = int(snap.scv[0])
        rep = jsonl.reported_best(hcv, scv)
        if rep < job.best:
            job.best = rep
        feasible = hcv == 0
        total_time = self._now() - job.submitted_t
        slots, rooms = bucket_mod.extract_solution(
            snap.slots[0], snap.rooms[0], job.padded)
        jsonl.solution_record(
            self.out, 0, 0, total_time, job.best, feasible,
            timeslots=slots.tolist() if feasible else None,
            rooms=rooms.tolist() if feasible else None, job=job.id)
        jsonl.run_entry(self.out, job.best, feasible, job=job.id)
        jsonl.run_entry(self.out, job.best, feasible, procs_num=1,
                        threads_num=1, total_time=total_time,
                        job=job.id)
        done_extra = {}
        edit_dist = None
        if job.mode == "edit":
            # distance vs the base job's published timetable, from the
            # event map — NOT anchor_w (a w_anchor=0 edit still reports
            # its true distance; the bench A/B's cold leg needs it)
            from timetabling_ga_tpu.serve import editsolve
            edit_dist = editsolve.edit_distance(
                snap.slots[0],
                getattr(job.padded, "anchor_slots", None),
                job.edit_map)
            done_extra["mode"] = job.mode
            if edit_dist is not None:
                done_extra["edit_distance"] = edit_dist
            if job.edit_demoted:
                done_extra["demoted"] = True
        jsonl.job_entry(self.out, job.id, "done", gens=job.gens_done,
                        best=job.best, feasible=feasible,
                        deadline_hit=deadline_hit, **done_extra)
        job.state = JobState.DONE
        job.finished_t = self._now()
        self._metrics.counter("serve.jobs_done").inc()
        self._metrics.histogram("serve.job_seconds").observe(
            total_time, exemplar={"job": job.id})
        job.result = {"best": job.best, "feasible": feasible,
                      "hcv": hcv, "scv": scv, "gens": job.gens_done,
                      "deadline_hit": deadline_hit,
                      "resumed_at": job.resumed_at,
                      "timeslots": slots.tolist(),
                      "rooms": rooms.tolist()}
        if job.mode != "solve":
            job.result["mode"] = job.mode
            job.result["edit_distance"] = edit_dist
            job.result["edit_demoted"] = job.edit_demoted
            if job.edit_of:
                job.result["edit_of"] = job.edit_of
        if self._usage is not None:
            # the settled meter travels with the result (the /v1 job
            # view a billing consumer reads) and lands on the record
            # stream as the job's authoritative `event: "total"`
            # usageEntry — cumulative across incarnations for a
            # resumed job (the wire cursor seeded it)
            job.result["tenant"] = job.tenant
            job.result["usage"] = usage_mod.rounded(job.usage)
            self._usage.final(job.id, job.tenant, job.usage,
                              mode=job.mode)
        job.snapshot = None        # parked memory released
        # the FINAL park-fence ship unit stays (host bytes, no device
        # refs): a done job may become an edit BASE (tt-edit), and its
        # final wire is what turns that edit into a warm transplant —
        # the replica's TAIL_JOBS forget is the retention bound
        job.ship_records = []      # live tail serves its records
