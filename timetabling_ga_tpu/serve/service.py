"""The service frontend: Python API + line-JSON protocol.

Python API:

    from timetabling_ga_tpu.runtime.config import ServeConfig
    from timetabling_ga_tpu.serve.service import SolveService

    svc = SolveService(ServeConfig(backend="cpu"), out=stream)
    jid = svc.submit(problem, generations=100, priority=5)
    svc.drive()                       # run until every job settles
    svc.result(jid)                   # {"best": ..., "feasible": ...}
    svc.close()

Line-JSON protocol (`tt serve` / `python -m timetabling_ga_tpu serve`):
one request object per input line, one record object per output line —
the engine's JSONL protocol with each record tagged `"job"`, plus the
`jobEntry` lifecycle records (jsonl.job_entry):

    {"submit": {"id": "j1", "instance": "comp01.tim", "priority": 5,
                "seed": 42, "generations": 200, "deadline": 30.0,
                "tenant": "acme"}}
    {"submit": {"id": "j2", "tim": "4 2 2 5\\n..."}}   inline instance
    {"submit": {"id": "j3", "edit": {"base": {"tim": "..."},
                "ops": [...], "snapshot": {...}, "w_anchor": 1}}}
                                       incremental re-solve (tt-edit:
                                       serve/editsolve.py — warm-start
                                       from the base snapshot under
                                       the anchored objective)
    {"cancel": "j1"}
    {"stats": true}                    live metricsEntry snapshot
    {"stats": "prometheus"}            snapshot + Prometheus text
    {"drain": true}                    run everything admitted so far

Requests are processed in order; `drain` (and end-of-input) hands the
queue to the scheduler. A malformed request or a rejected submission
emits a jobEntry (event "rejected") and the stream continues — one bad
tenant must not take down the service.

Observability (README "Observability"): `--obs` emits spanEntry spans
(admit / pack / quantum / park / resume) and periodic metricsEntry
snapshots; the `stats` request answers with a metricsEntry on the
record stream at any time (obs or not), and `{"stats": "prometheus"}`
embeds the registry's Prometheus text exposition in the record so a
sidecar can relay it to a scrape endpoint. `--trace-mode deltas|stats`
compresses the lane runner's telemetry leaf on device exactly like the
engine's (parallel/islands.py), with an identical record stream.
"""

from __future__ import annotations

import json
import sys

from timetabling_ga_tpu.obs import metrics as obs_metrics
from timetabling_ga_tpu.obs import usage as obs_usage
from timetabling_ga_tpu.obs.spans import SpanTracer
from timetabling_ga_tpu.problem import load_tim, load_tim_file
from timetabling_ga_tpu.runtime import jsonl
from timetabling_ga_tpu.runtime.config import ServeConfig, parse_serve_args
from timetabling_ga_tpu.serve.queue import AdmissionError, Job, JobQueue
from timetabling_ga_tpu.serve.scheduler import Scheduler


class SolveService:
    """Owns the queue, the scheduler, and the job-tagged record stream.

    All records ride a jsonl.AsyncWriter, so solve dispatches never
    stall on host I/O — the same telemetry discipline as the engine's
    run loop, shared across every tenant of the stream."""

    def __init__(self, cfg: ServeConfig, out=None, now=None,
                 registry=None):
        import jax
        if cfg.backend == "cpu":
            jax.config.update("jax_platforms", "cpu")
        self.cfg = cfg
        # which metrics registry this service reports into: THE process
        # registry by default; a private MetricsRegistry when several
        # in-process replicas coexist (fleet/replicas.py InProcReplica)
        # so each replica's /metrics and /readyz tell only its own
        # truth. The cost observatory stays process-global either way
        # (compile caches genuinely are shared in-process).
        self._registry = (obs_metrics.REGISTRY if registry is None
                          else registry)
        # deterministic fault injection, mirroring engine.run: install
        # the configured plan (or $TT_FAULTS) so the serve-relevant
        # sites (writer, obs_listen, scrape) fire under `tt serve` too.
        # Only when a spec is present — a service must not clobber a
        # plan a test installed programmatically before constructing it.
        from timetabling_ga_tpu.runtime import faults as faults_mod
        spec = faults_mod.active_spec(cfg.faults)
        if spec:
            faults_mod.install(spec)
        self._close_out = False
        if out is None:
            if cfg.output:
                out = open(cfg.output, "w")
                self._close_out = True
            else:
                out = sys.stdout
        self._raw_out = out
        # tt-flight, mirroring engine.run's wiring: the history
        # sampler on its own daemon thread, the incident recorder
        # teeing the record stream (writer-thread ingestion; fault
        # sites `history`/`flight_dump`; the stream is bit-identical
        # with both on or off). The recorder reports into THIS
        # service's registry, so N in-process replicas keep separate
        # incident truths like they keep separate /readyz truths.
        from timetabling_ga_tpu.obs import flight as obs_flight
        self.history, self.flight, sink = obs_flight.wire(
            cfg, out, registry=self._registry, process="serve")
        self.writer = jsonl.AsyncWriter(sink)
        # obs wiring, mirroring engine.run's: spans ride the writer,
        # the registry's writer gauges re-bind to this service's writer
        self.tracer = SpanTracer(self.writer, enabled=cfg.obs)
        if self.flight is not None:
            self.flight.bind_tracer(self.tracer)
            self.flight.start()
        self._registry.gauge_fn("writer.queue_depth",
                                self.writer.qsize)
        self._registry.gauge_fn(
            "writer.records", lambda: self.writer.records_written)
        # cost observatory (obs/cost.py), mirroring engine.run's
        # wiring: costEntry emission binds to this service's writer
        # under --obs; the memory poller and the on-demand profiler
        # capture run on their own daemon threads, OFF the serve path
        from timetabling_ga_tpu.obs import cost as obs_cost
        obs_cost.OBSERVATORY.bind(self.writer if cfg.obs else None,
                                  now=self.tracer.now)
        self.mem_poller = None
        if (cfg.obs or cfg.obs_listen) and cfg.mem_poll_every > 0:
            self.mem_poller = obs_cost.MemPoller(
                obs_cost.jax_memory_stats_fn(),
                cfg.mem_poll_every).start()
        self.profile_capture = None
        if cfg.profile_for > 0 or cfg.obs_listen:
            self.profile_capture = obs_cost.ProfileCapture(
                lambda d: jax.profiler.start_trace(d),
                jax.profiler.stop_trace,
                default_dir=cfg.profile_dir)
            # tt-prof, mirroring engine.run's wiring: finished
            # captures attribute themselves on the capture worker
            # into THIS service's registry (and its writer under
            # --obs — profEntry is a TIMING record)
            from timetabling_ga_tpu.obs import prof as obs_prof
            self.profile_capture.on_complete = obs_prof.capture_hook(
                self.writer if cfg.obs else None,
                registry=self._registry, now=self.tracer.now)
            if cfg.profile_for > 0:
                self.profile_capture.trigger(cfg.profile_for)
        # tt-meter (obs/usage.py, README "Usage metering"): the usage
        # ledger's own daemon thread folds per-tenant capacity
        # attribution off the drive loop; usageEntry records ride the
        # writer under --obs (they are TIMING records — the stream is
        # identical with metering on or off). --no-usage drops the
        # whole meter (the bench A/B's other leg).
        self.usage = None
        if cfg.usage:
            self.usage = obs_usage.UsageLedger(
                registry=self._registry,
                out=(self.writer if cfg.obs else None),
                now=self.tracer.now)
        self.queue = JobQueue(cfg.backlog, now=now)
        self.scheduler = Scheduler(cfg, self.queue, self.writer,
                                   now=now, tracer=self.tracer,
                                   profiler=self.profile_capture,
                                   registry=self._registry,
                                   usage=self.usage)
        self._auto_id = 0
        self.obs_server = None
        if cfg.obs_listen:
            # the pull front (obs/http.py): Prometheus scrapes /metrics
            # (OpenMetrics + job exemplars) and probes /healthz //readyz
            # straight off this process — no sidecar tailing the record
            # stream. The listener writes NO records; the JSONL stream
            # is identical with it on or off (tests + bench pin it).
            try:
                from timetabling_ga_tpu.obs import http as obs_http
                self.obs_server = obs_http.ObsServer(
                    cfg.obs_listen, registry=self._registry,
                    probes={"process": lambda: True,
                            "writer": self.writer.alive},
                    profile=self.profile_capture,
                    history=self.history).start()
            except BaseException:
                # a failed construction (e.g. the port is taken) never
                # reaches close(): the observatory threads started
                # above — and the global emitter bound to THIS writer —
                # must not outlive the service that never existed. The
                # pull gauges bound above (and by the Scheduler) get
                # the same freeze treatment close() gives them: the
                # process-global registry must not keep closures over
                # the dead writer and queue alive.
                if self.profile_capture is not None:
                    self.profile_capture.close()
                if self.mem_poller is not None:
                    self.mem_poller.close()
                if self.usage is not None:
                    self.usage.close()
                if self.flight is not None:
                    self.flight.close()
                if self.history is not None:
                    self.history.close()
                obs_cost.OBSERVATORY.unbind()
                self.writer.close(raise_error=False)
                self._registry.freeze(
                    "writer.records", self.writer.records_written)
                self._registry.freeze("writer.queue_depth", 0.0)
                self._registry.freeze("serve.queue_depth", 0.0)
                raise

    # -- API -------------------------------------------------------------

    @property
    def registry(self):
        """The metrics registry this service reports into (the fleet
        replica front serves /metrics //readyz from it)."""
        return self._registry

    def submit(self, problem, job_id=None, priority: int = 0,
               seed=None, generations=None, deadline_s=None,
               flow: int = 0, snapshot=None, tenant=None,
               count_job: bool = True, edit=None) -> str:
        """Admit one job; returns its id. Raises AdmissionError when
        the backlog is full or the id is taken (admission control).
        `flow` (optional) is an inherited causal flow id — the fleet
        gateway's X-TT-Flow, so a routed job's replica-side spans
        continue the gateway's chain; 0 lets the scheduler allocate a
        local one at admit. `snapshot` (optional) is a warm-start wire
        snapshot (serve/snapshot.py): the scheduler admits the job as
        already PARKED at the snapshot's progress — init skipped, the
        record stream continuing duplicate-free from the restored
        `emitted` floor — and `generations` stays the job's TOTAL
        budget (the remaining budget is total minus the snapshot's
        gens_done). A snapshot that fails validation demotes to a
        fresh solve with a faultEntry, never an error. `tenant`
        (optional) tags the job for tt-meter capacity attribution
        (obs/usage.py — sanitized to a bounded metric-safe label;
        None/empty = the shared default tenant). `count_job=False`
        marks a fleet RESEND (the gateway's X-TT-Resubmit): the job
        is metered as usual but NOT re-counted in its tenant's `jobs`
        ledger — its first admission, possibly on a now-dead replica
        whose cached ledger the gateway still sums, already did.

        `edit` (optional; serve/editsolve.py, README "Incremental
        re-solve") is an edit spec {"base": ..., "ops"|"edited": ...,
        "w_anchor": W, "snapshot": <base wire>, "base_id": ...}: the
        service derives the EDITED instance from it (`problem` may be
        None), attaches the anchored objective (the base snapshot's
        best timetable at weight W on carried events — deterministic,
        so a failed-over edit job re-derives the SAME objective), and
        warm-starts from a population transplanted out of the base
        snapshot when the edit stays in the base's shape bucket. A
        cross-bucket edit or missing/bad base snapshot DEMOTES to a
        cold solve of the edited instance (counted, never an error); a
        malformed spec is a rejection like any other bad submit. An
        edit job that ALSO carries `snapshot` (its own resume wire — a
        fleet failover) resumes from that instead of re-transplanting:
        its own wire is strictly newer."""
        if job_id is None:
            self._auto_id += 1
            job_id = f"job-{self._auto_id}"
        mode = "solve"
        edit_map = None
        edit_of = None
        base_wire = None
        if edit is not None:
            from timetabling_ga_tpu.serve import editsolve
            _base, edited, edit_map, _ops = editsolve.resolve_edit(
                edit, n_days=getattr(self.cfg, "n_days", None),
                slots_per_day=getattr(self.cfg, "slots_per_day",
                                      None))
            base_wire = edit.get("snapshot")
            w_anchor = int(edit.get("w_anchor",
                                    editsolve.DEFAULT_ANCHOR_W))
            problem = editsolve.attach_anchor(
                edited, edit_map,
                editsolve.anchor_from_wire(base_wire), w_anchor)
            mode = "edit"
            edit_of = edit.get("base_id") or (
                edit["base"] if isinstance(edit["base"], str)
                else None)
        job = Job(id=str(job_id), problem=problem,
                  priority=int(priority),
                  seed=int(self.cfg.seed if seed is None else seed),
                  generations=int(self.cfg.generations
                                  if generations is None
                                  else generations),
                  deadline_s=deadline_s, flow=int(flow or 0),
                  resume_wire=snapshot,
                  tenant=obs_usage.tenant_label(tenant),
                  count_usage=bool(count_job),
                  mode=mode, edit_of=edit_of, edit_map=edit_map)
        # prepare (pad + place) BEFORE the queue takes the job: a
        # failing instance is rejected here with the queue untouched —
        # no half-admitted job can reach the scheduler
        self.scheduler.prepare(job)
        self.queue.submit(job)
        if mode == "edit" and job.resume_wire is None:
            # transplant the base population (or demote to cold) —
            # after the queue takes the job so its faultEntry joins
            # the job's stream, before admit so the wire warm-starts
            self.scheduler.prepare_edit(job, base_wire)
        self.scheduler.admit(job)
        return job.id

    def cancel(self, job_id: str) -> bool:
        ok = self.queue.cancel(job_id)
        if ok:
            jsonl.job_entry(self.writer, job_id, "cancelled")
        return ok

    def drive(self) -> None:
        """Run dispatches until every admitted job settles."""
        self.scheduler.drive()

    def step(self) -> bool:
        """One dispatch cycle (for callers interleaving submissions)."""
        return self.scheduler.step()

    def result(self, job_id: str):
        return self.queue.get(job_id).result

    def state(self, job_id: str) -> str:
        return self.queue.get(job_id).state

    def stats(self) -> dict:
        """Live metrics-registry snapshot (the metricsEntry payload)."""
        return self._registry.snapshot()

    def prometheus(self) -> str:
        """Prometheus text exposition of the registry (format 0.0.4)."""
        return self._registry.to_prometheus()

    def emit_stats(self, prometheus: bool = False) -> None:
        """Answer a `stats` request: one metricsEntry on the record
        stream, optionally carrying the Prometheus text exposition so a
        sidecar can relay it to a scrape endpoint."""
        snap = self.stats()
        if prometheus:
            snap["prometheus"] = self.prometheus()
        jsonl.metrics_entry(self.writer, snap, ts=self.tracer.now())

    def close(self) -> None:
        if self.obs_server is not None:
            self.obs_server.close()
        if self.profile_capture is not None:
            self.profile_capture.close()
        if self.mem_poller is not None:
            self.mem_poller.close()
        if self.usage is not None:
            # BEFORE the writer closes: the ledger drains its pending
            # settlements (their usageEntry lines enqueue into the
            # writer), then the writer's own close drains those to the
            # stream; a hung ledger is abandoned, never waited out
            self.usage.close()
        try:
            self.writer.close()
        finally:
            # flight teardown AFTER the writer drains (the tee's last
            # records land in the rings; a pending trigger's final
            # dump happens in flight.close) — and, like the unbind
            # below, even when close() re-raises a latched writer
            # error: drop the process-global registry's closures over
            # this service's writer and queue (and the observatory's
            # costEntry emitter, which holds the same writer)
            if self.flight is not None:
                self.flight.close()
            if self.history is not None:
                self.history.close()
            from timetabling_ga_tpu.obs import cost as obs_cost
            obs_cost.OBSERVATORY.unbind()
            self._registry.freeze(
                "writer.records", self.writer.records_written)
            self._registry.freeze("writer.queue_depth", 0.0)
            self._registry.freeze("serve.queue_depth", 0.0)
            if self._close_out:
                self._raw_out.close()


def _load_submit_problem(req: dict):
    if "edit" in req:
        return None          # the edit spec derives the instance
    if "tim" in req:
        return load_tim(req["tim"])
    return load_tim_file(req["instance"])


def serve_stream(cfg: ServeConfig, in_stream, out_stream=None,
                 now=None) -> SolveService:
    """Run the line-JSON protocol over `in_stream` to completion.

    Returns the (closed) service so programmatic callers can inspect
    results. Errors in individual requests are reported on the record
    stream and skipped."""
    svc = SolveService(cfg, out=out_stream, now=now)
    try:
        for line in in_stream:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except ValueError as e:
                jsonl.job_entry(svc.writer, "?", "rejected",
                                reason=f"bad request: {e}")
                continue
            if "submit" in req:
                sub = req["submit"]
                try:
                    svc.submit(_load_submit_problem(sub),
                               job_id=sub.get("id"),
                               priority=sub.get("priority", 0),
                               seed=sub.get("seed"),
                               generations=sub.get("generations"),
                               deadline_s=sub.get("deadline"),
                               snapshot=sub.get("snapshot"),
                               tenant=sub.get("tenant"),
                               edit=sub.get("edit"))
                except Exception as e:
                    # one bad tenant must not take down the service:
                    # ANY submit-side failure (parse error, admission
                    # control, over-bound bucket, placement OOM) is a
                    # rejection record, and the stream continues —
                    # submit() leaves no partial state (prepare runs
                    # before the queue takes the job)
                    jsonl.job_entry(svc.writer, str(sub.get("id", "?")),
                                    "rejected", reason=str(e)[:200])
            elif "cancel" in req:
                svc.cancel(str(req["cancel"]))
            elif "stats" in req:
                svc.emit_stats(prometheus=req["stats"] == "prometheus")
            elif "drain" in req:
                svc.drive()
            else:
                jsonl.job_entry(svc.writer, "?", "rejected",
                                reason=f"unknown request "
                                       f"{sorted(req)[:3]}")
        svc.drive()
    finally:
        svc.close()
    return svc


def main_serve(argv) -> int:
    """`tt serve` entry point (cli.py dispatches here)."""
    cfg = parse_serve_args(argv)
    if cfg.http:
        # the fleet replica mode (README "Fleet"): the same service,
        # driven by a command inbox behind an HTTP front speaking the
        # gateway's /v1 protocol instead of line-JSON on stdio
        from timetabling_ga_tpu.fleet.replicas import serve_http
        return serve_http(cfg)
    if cfg.input:
        with open(cfg.input, "r") as fh:
            serve_stream(cfg, fh)
    else:
        serve_stream(cfg, sys.stdin)
    return 0
