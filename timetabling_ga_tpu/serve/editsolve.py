"""tt-edit: incremental re-solve — edit specs, population transplant,
and the anchored objective's host side.

Traffic shape (ROADMAP item 5a): a timetabling service at scale sees
many SMALL EDITS against few cold solves — one event added, one
attendance list changed — yet a cold solve re-derives everything the
base job already learned. This module turns an edit into a warm
restart that INHERITS the base job's search state instead of
recomputing it (the increasing-population-restart idea from the CMA-ES
literature, applied to the tt-resume wire snapshot):

  edit spec     {"edit": {"base": <job_id> | {"tim"|"problem": ...},
                          "ops": [...] | "edited": {"tim"|"problem":
                          ...}, "w_anchor": W, "snapshot": <wire>}}
                ops grammar (applied in order, events indexed in the
                CURRENT problem at each step):
                  {"op": "add_event", "students": [s...],
                   "features": [f...]}            append one event
                  {"op": "remove_event", "event": e}
                  {"op": "set_attendance", "event": e, "student": s,
                   "value": 0|1}
                  {"op": "set_event_features", "event": e,
                   "features": [f...]}            replace requirement row
                  {"op": "set_room_size", "room": r, "size": n}
                  {"op": "set_room_features", "room": r,
                   "features": [f...]}            replace feature row
                Alternatively "edited" ships the full edited instance
                and `diff_problems` recovers the event mapping
                positionally (equal-count prefix matches 1:1, extra
                trailing events are adds, missing ones removes).

  warm vs cold  the edit is WARM-COMPATIBLE iff the edited instance
                pads into the SAME shape bucket as the base snapshot
                (serve/bucket.bucket_key == wire["bucket"]): every
                compiled island program then fits the transplanted
                population unchanged. A cross-bucket edit, a missing/
                undecodable base snapshot, or a population-size
                mismatch DEMOTES the job to a cold solve (counted —
                serve.jobs_edit_demoted — never an error).

  transplant    carried events keep their slot/room genes from the
                base job's park-fence snapshot; new events enter
                parked at seeded-random slots (room 0 — the greedy
                matcher re-rooms on first touch); removed events drop.
                The population is re-evaluated under the EDITED
                problem (the base snapshot's penalties are stale by
                construction), lex-sorted, and packed into a fresh
                wire carrying the EDIT job's own fingerprint with
                cursors reset (gens_done=0, chunks=0 — the edit job's
                lane RNG starts from ITS seed) — then admitted PARKED
                through the scheduler's `_admit_resumed` seam.

  anchor        the base job's published timetable (the snapshot's
                lex-best row) becomes `Problem.anchor_slots`, with
                `anchor_w[e] = w_anchor` on carried events and 0 on
                new ones, so the kernels charge w_anchor per carried
                event moved away from its published slot
                (ops/fitness.anchor_cost — threaded through every
                delta-acceptance site). w_anchor == 0 keeps the
                anchor columns numerically inert (integer weight 0),
                so those streams stay byte-identical to unanchored
                solves.

Layering: everything here is host-side numpy + stdlib except
`transplant`'s one batched re-evaluation (fitness.batch_penalty), and
it runs at ADMISSION time only — never inside a dispatch loop or a
traced function (tt-analyze TT309 bans `editsolve.*` there).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from timetabling_ga_tpu.problem import Problem, derive, load_tim
from timetabling_ga_tpu.serve import bucket as bucket_mod
from timetabling_ga_tpu.serve import snapshot as snapshot_mod

#: default anchor weight when the edit spec omits `w_anchor`: one soft
#: point per moved carried event — enough to prefer the published slot
#: among otherwise-equal candidates, never enough to trade a hard
#: constraint for stability (any hcv dominates through the
#: INFEASIBLE_OFFSET encoding).
DEFAULT_ANCHOR_W = 1

_OPS = ("add_event", "remove_event", "set_attendance",
        "set_event_features", "set_room_size", "set_room_features")


class EditError(ValueError):
    """The edit spec is malformed or inapplicable to its base problem
    (bad op name, out-of-range index, missing base). Raised at
    admission — an edit job with a bad spec is REJECTED, not demoted
    (demotion is for valid edits that merely cannot warm-start)."""


class EditDemoted(RuntimeError):
    """A valid edit cannot warm-start (cross-bucket shape, missing or
    undecodable base snapshot, population mismatch). The scheduler
    catches this, counts serve.jobs_edit_demoted, and runs the job as
    a plain cold solve of the edited instance."""


def parse_edit_spec(edit) -> dict:
    """Validate the edit object's structure (not its applicability —
    that needs the base problem). Returns the dict unchanged."""
    if not isinstance(edit, dict):
        raise EditError(f"edit spec is {type(edit).__name__}, "
                        f"not an object")
    if "base" not in edit:
        raise EditError("edit spec needs a 'base' (job id or inline "
                        "problem object)")
    has_ops = "ops" in edit
    has_edited = "edited" in edit
    if has_ops == has_edited:
        raise EditError("edit spec needs exactly one of 'ops' or "
                        "'edited'")
    if has_ops:
        ops = edit["ops"]
        if not isinstance(ops, (list, tuple)):
            raise EditError("edit 'ops' must be a list")
        for i, op in enumerate(ops):
            if not isinstance(op, dict) or op.get("op") not in _OPS:
                raise EditError(
                    f"edit op {i} is not one of {_OPS}: {op!r}")
    w = edit.get("w_anchor", DEFAULT_ANCHOR_W)
    try:
        if int(w) < 0:
            raise ValueError
    except (TypeError, ValueError):
        raise EditError(f"edit w_anchor must be a non-negative "
                        f"integer, got {w!r}") from None
    return edit


def load_base_problem(base, n_days=None, slots_per_day=None) -> Problem:
    """The edit's base problem from its inline payload form — the same
    {"tim": ...} / {"problem": ...} shapes every submit payload uses
    (the gateway rewrites a job-id base into this form before
    forwarding, so the replica never resolves ids)."""
    if not isinstance(base, dict):
        raise EditError(
            f"edit base must be resolved to an inline problem object "
            f"before it reaches the solver, got {type(base).__name__} "
            f"(unresolved job-id bases are a gateway-only form)")
    kw = {}
    days = base.get("n_days", n_days)
    spd = base.get("slots_per_day", slots_per_day)
    if days is not None:
        kw["n_days"] = int(days)
    if spd is not None:
        kw["slots_per_day"] = int(spd)
    if "problem" in base:
        # lazy: the JSON problem codec lives with the fleet wire code
        from timetabling_ga_tpu.fleet.replicas import problem_from_json
        return problem_from_json(base["problem"])
    if "tim" in base:
        return load_tim(str(base["tim"]), **kw)
    raise EditError("edit base object needs a 'tim' text or a "
                    "'problem' object")


def _check_index(name: str, idx, bound: int) -> int:
    try:
        i = int(idx)
    except (TypeError, ValueError):
        raise EditError(f"edit op {name} index {idx!r} is not an "
                        f"int") from None
    if not 0 <= i < bound:
        raise EditError(f"edit op {name} index {i} out of range "
                        f"[0, {bound})")
    return i


def _feature_row(features, n_features: int) -> np.ndarray:
    row = np.zeros((n_features,), np.int8)
    for f in features or ():
        row[_check_index("feature", f, n_features)] = 1
    return row


def apply_ops(base: Problem, ops) -> tuple[Problem, np.ndarray]:
    """Apply an op list to `base`; returns (edited, event_map) where
    event_map[e_edited] = the base event index it carries, or -1 for a
    newly added event. All stdlib/numpy — the differ side of the
    edit-spec grammar (module docstring)."""
    attends = np.array(base.attends, dtype=np.int8)        # (S, E)
    event_features = np.array(base.event_features, np.int8)
    room_features = np.array(base.room_features, np.int8)
    room_size = np.array(base.room_size, np.int32)
    event_map = list(range(base.n_events))
    S, F = base.n_students, base.n_features

    for op in ops:
        kind = op.get("op")
        E = attends.shape[1]
        if kind == "add_event":
            col = np.zeros((S, 1), np.int8)
            for s in op.get("students") or ():
                col[_check_index("student", s, S), 0] = 1
            attends = np.concatenate([attends, col], axis=1)
            event_features = np.concatenate(
                [event_features,
                 _feature_row(op.get("features"), F)[None, :]], axis=0)
            event_map.append(-1)
        elif kind == "remove_event":
            e = _check_index("event", op.get("event"), E)
            attends = np.delete(attends, e, axis=1)
            event_features = np.delete(event_features, e, axis=0)
            del event_map[e]
        elif kind == "set_attendance":
            e = _check_index("event", op.get("event"), E)
            s = _check_index("student", op.get("student"), S)
            attends[s, e] = 1 if op.get("value") else 0
        elif kind == "set_event_features":
            e = _check_index("event", op.get("event"), E)
            event_features[e] = _feature_row(op.get("features"), F)
        elif kind == "set_room_size":
            r = _check_index("room", op.get("room"), base.n_rooms)
            size = int(op.get("size", 0))
            if size < 0:
                raise EditError(f"edit op set_room_size: negative "
                                f"size {size}")
            room_size[r] = size
        elif kind == "set_room_features":
            r = _check_index("room", op.get("room"), base.n_rooms)
            room_features[r] = _feature_row(op.get("features"), F)
        else:
            raise EditError(f"unknown edit op {kind!r}")

    if attends.shape[1] == 0:
        raise EditError("edit removes every event")
    edited = derive(attends.shape[1], base.n_rooms, F, S, room_size,
                    attends, room_features, event_features,
                    n_days=base.n_days,
                    slots_per_day=base.slots_per_day)
    return edited, np.asarray(event_map, np.int32)


def diff_problems(base: Problem, edited: Problem
                  ) -> tuple[list, np.ndarray]:
    """Positional differ for full-instance edits (`tt submit EDITED.tim
    --edit-of BASE`): events are matched BY POSITION — the common
    prefix min(E_base, E_edited) carries 1:1, trailing extra edited
    events are adds, trailing missing base events are removes. Simple
    and predictable: a client that reorders events gets a (valid but
    cold-ish) high-distance mapping, not a guess. Returns (ops,
    event_map) where ops is a summary op list in the apply_ops grammar
    and event_map matches apply_ops' convention."""
    if (base.n_students, base.n_features, base.n_rooms) != (
            edited.n_students, edited.n_features, edited.n_rooms):
        raise EditError(
            f"diff needs matching (students, features, rooms) axes: "
            f"base ({base.n_students}, {base.n_features}, "
            f"{base.n_rooms}) != edited ({edited.n_students}, "
            f"{edited.n_features}, {edited.n_rooms})")
    if (base.n_days, base.slots_per_day) != (edited.n_days,
                                             edited.slots_per_day):
        raise EditError("diff needs matching slot grids")
    Eb, Ee = base.n_events, edited.n_events
    common = min(Eb, Ee)
    ops: list = []
    for e in range(common):
        changed = np.flatnonzero(base.attends[:, e]
                                 != edited.attends[:, e])
        for s in changed:
            ops.append({"op": "set_attendance", "event": e,
                        "student": int(s),
                        "value": int(edited.attends[s, e])})
        if np.any(base.event_features[e] != edited.event_features[e]):
            ops.append({"op": "set_event_features", "event": e,
                        "features": np.flatnonzero(
                            edited.event_features[e]).tolist()})
    for r in range(base.n_rooms):
        if int(base.room_size[r]) != int(edited.room_size[r]):
            ops.append({"op": "set_room_size", "room": r,
                        "size": int(edited.room_size[r])})
        if np.any(base.room_features[r] != edited.room_features[r]):
            ops.append({"op": "set_room_features", "room": r,
                        "features": np.flatnonzero(
                            edited.room_features[r]).tolist()})
    for e in range(common, Ee):                    # trailing adds
        ops.append({"op": "add_event",
                    "students": np.flatnonzero(
                        edited.attends[:, e]).tolist(),
                    "features": np.flatnonzero(
                        edited.event_features[e]).tolist()})
    for e in range(Eb - 1, common - 1, -1):        # trailing removes
        ops.append({"op": "remove_event", "event": e})
    event_map = np.concatenate(
        [np.arange(common, dtype=np.int32),
         np.full((Ee - common,), -1, np.int32)])
    return ops, event_map


def resolve_edit(edit, n_days=None, slots_per_day=None):
    """Edit spec -> (base, edited, event_map, ops). Validates the spec,
    loads the base, and applies/diffs — everything about the edit that
    does not need the snapshot or the scheduler."""
    parse_edit_spec(edit)
    base = load_base_problem(edit["base"], n_days=n_days,
                             slots_per_day=slots_per_day)
    if "ops" in edit:
        ops = list(edit["ops"])
        edited, event_map = apply_ops(base, ops)
    else:
        edited_p = load_base_problem(edit["edited"],
                                     n_days=base.n_days,
                                     slots_per_day=base.slots_per_day)
        ops, event_map = diff_problems(base, edited_p)
        edited = edited_p
    return base, edited, event_map, ops


def anchor_from_wire(wire) -> np.ndarray | None:
    """The base job's published timetable: the snapshot population's
    lex-best row of slots ((E_padded,) int32), or None when the wire
    is missing/undecodable. Host-only (numpy lexsort)."""
    if wire is None:
        return None
    try:
        state, _meta = snapshot_mod.unpack_state(wire)
    except Exception:
        return None
    best = int(np.lexsort((np.asarray(state.scv),
                           np.asarray(state.penalty)))[0])
    return np.asarray(state.slots[best], np.int32)


def attach_anchor(edited: Problem, event_map: np.ndarray,
                  base_anchor: np.ndarray | None,
                  w_anchor: int) -> Problem:
    """Attach the anchored-objective columns to the edited problem:
    anchor_slots[e] = the base best solution's slot for carried events
    (event_map[e] >= 0), weight w_anchor there and 0 on new events.
    With no decodable base solution the problem is returned unanchored
    (the cold/demoted legs still solve the plain objective)."""
    if base_anchor is None or w_anchor is None:
        return edited
    E = edited.n_events
    anchor_slots = np.zeros((E,), np.int32)
    anchor_w = np.zeros((E,), np.int32)
    carried = event_map >= 0
    # base live events occupy the padded prefix, so live base indices
    # index base_anchor directly
    anchor_slots[carried] = base_anchor[event_map[carried]]
    anchor_w[carried] = int(w_anchor)
    return dataclasses.replace(edited, anchor_slots=anchor_slots,
                               anchor_w=anchor_w)


def classify(edited_padded_key: tuple, wire) -> bool:
    """Warm-compatible iff the edited instance's bucket equals the
    base snapshot's (module docstring). False = cold."""
    if wire is None:
        return False
    return [int(d) for d in edited_padded_key] == [
        int(d) for d in wire.get("bucket", ())]


def transplant(edited_padded: Problem, event_map: np.ndarray, wire,
               *, bucket, pop_size: int, seed: int) -> dict:
    """Build the edit job's warm-start wire: carried events keep their
    base slot/room genes, new events enter at seeded-random slots,
    removed events drop; the population is re-evaluated under the
    edited problem, lex-sorted, and packed with the EDIT job's own
    fingerprint and RESET cursors (gens_done=0, chunks=0 — its lane
    RNG starts from its own seed; emitted/best at the fresh-job floor
    so the record stream starts clean). Raises EditDemoted on any
    warm-start obstacle; the caller runs the job cold."""
    if wire is None:
        raise EditDemoted("no base snapshot to transplant from")
    if not classify(bucket, wire):
        raise EditDemoted(
            f"cross-bucket edit: edited bucket {list(bucket)} != base "
            f"snapshot bucket {list(wire.get('bucket', ()))}")
    try:
        base_state, _meta = snapshot_mod.unpack_state(wire)
    except Exception as e:
        raise EditDemoted(f"base snapshot undecodable: {e}") from e
    b_slots = np.asarray(base_state.slots)
    b_rooms = np.asarray(base_state.rooms)
    if b_slots.shape[0] != pop_size:
        raise EditDemoted(
            f"base snapshot population {b_slots.shape[0]} != "
            f"configured pop_size {pop_size}")

    Ep = edited_padded.n_events
    live = (edited_padded.n_live_events
            if edited_padded.n_live_events is not None else Ep)
    T = edited_padded.n_slots
    if np.any(event_map[:live] >= b_slots.shape[1]):
        raise EditDemoted("event map exceeds base genotype width")
    rng = np.random.default_rng(seed)
    slots = np.zeros((pop_size, Ep), np.int32)
    rooms = np.zeros((pop_size, Ep), np.int32)
    carried = np.flatnonzero(event_map[:live] >= 0)
    fresh = np.flatnonzero(event_map[:live] < 0)
    slots[:, carried] = b_slots[:, event_map[carried]]
    rooms[:, carried] = b_rooms[:, event_map[carried]]
    if fresh.size:
        slots[:, fresh] = rng.integers(
            0, T, size=(pop_size, fresh.size), dtype=np.int32)
        # room 0 is a placeholder: the first local-search touch
        # re-rooms greedily, and an unsuitable room is just hcv the
        # search immediately repairs

    # the base penalties are STALE under the edited problem (changed
    # attendance/suitability, dropped events): one batched
    # re-evaluation under the edited padded instance — admission-time
    # device work, the one jax call in this module (never inside a
    # dispatch loop: TT309)
    from timetabling_ga_tpu.ops import fitness, ga
    pa = edited_padded.device_arrays()
    pen_d, hcv_d, scv_d = fitness.batch_penalty(pa, slots, rooms)
    pen = np.asarray(pen_d)
    hcv = np.asarray(hcv_d)
    scv = np.asarray(scv_d)
    order = np.asarray(fitness.lex_order(pen_d, scv_d))
    state = ga.PopState(slots=slots[order], rooms=rooms[order],
                        penalty=pen[order], hcv=hcv[order],
                        scv=scv[order])
    fresh_floor = 2**31 - 1
    return snapshot_mod.pack_state(
        state, bucket=bucket, pop_size=pop_size, seed=seed,
        gens_done=0, chunks=0, emitted=fresh_floor, best=fresh_floor)


def edit_distance(final_slots, anchor_slots, event_map) -> int | None:
    """Events MOVED vs the anchor: carried live events whose final
    slot differs from the base solution's. Computed from the event map
    (not anchor_w — the w_anchor=0 bench leg must still report its
    true distance). None when the job never had a decodable anchor."""
    if anchor_slots is None or event_map is None:
        return None
    final_slots = np.asarray(final_slots)
    live = min(final_slots.shape[-1], len(event_map))
    carried = np.asarray(event_map[:live]) >= 0
    return int(np.sum((final_slots[..., :live][..., carried]
                       != np.asarray(anchor_slots)[:live][carried])))
