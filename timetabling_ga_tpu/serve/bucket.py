"""Shape bucketing: pad instances to shared compile shapes, neutrally.

jax.jit compiles PER INPUT SHAPE, so a service that solved each
instance at its native (E, R, F, S) would pay a multi-second XLA
compile for every new instance shape — the compile cache would be as
fragmented as the traffic. Padding every instance up to geometric
bucket boundaries makes the compile-cache key the BUCKET shape: any
two instances in a bucket share every compiled island program, and a
warm bucket serves a cold instance with zero compiles
(tests/test_serve.py pins "exactly one trace per program per bucket").

Neutrality contract (the part that makes this safe to serve):

  - padded EVENTS attend no students, require no features, and carry
    `ProblemArrays.event_mask == 0`: the mask-aware kernels exclude
    them from occupancy, clash/correlation counts, the unsuitable-room
    count, and the greedy matcher's occupancy bookkeeping — they are
    genotype freeloaders whose slot/room values cannot affect any
    penalty term;
  - padded ROOMS have zero capacity, zero features, and
    `room_mask == False`: `possible[:, padded]` is forced False and
    every room argmin carries the `_W_DEAD` key penalty, so no live
    event ever chooses one;
  - `possible[padded_event, :]` is forced uniformly False, so the
    unsuitable-room DELTA of relocating a padded event is identically
    zero on every path.

Together: for any genotype that places live events exactly as an
unpadded genotype does, (penalty, hcv, scv) are bit-exact equal, and
`assign_rooms` assigns live events the same rooms (padded rooms
append at the tail, so live capacity ranks shift uniformly and every
argmin comparison among live rooms is preserved).
tests/test_serve.py pins both properties on the ITC fixtures.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from timetabling_ga_tpu.problem import Problem, derive


@dataclasses.dataclass(frozen=True)
class BucketSpec:
    """Geometric bucket boundaries: dim -> smallest floor*ratio^k >= n.

    Floors keep tiny instances from over-fragmenting the small buckets;
    ratio 2 bounds padding waste below 2x per dimension (the classic
    geometric-bucketing bound). The slot grid (n_days, slots_per_day)
    is never padded — it is part of the bucket key instead: timeslot
    semantics (last-slot-of-day scv, day windows) are not maskable."""

    event_floor: int = 32
    room_floor: int = 4
    feature_floor: int = 4
    student_floor: int = 32
    ratio: float = 2.0


DEFAULT_SPEC = BucketSpec()


def _round_up(n: int, floor: int, ratio: float) -> int:
    if n <= 0:
        return floor
    size = floor
    while size < n:
        size = int(np.ceil(size * ratio))
    return size


def bucket_dims(problem: Problem, spec: BucketSpec = DEFAULT_SPEC
                ) -> tuple[int, int, int, int]:
    """(E', R', F', S') bucket boundaries for `problem`."""
    return (_round_up(problem.n_events, spec.event_floor, spec.ratio),
            _round_up(problem.n_rooms, spec.room_floor, spec.ratio),
            _round_up(problem.n_features, spec.feature_floor, spec.ratio),
            _round_up(problem.n_students, spec.student_floor, spec.ratio))


def bucket_key_from_counts(n_events: int, n_rooms: int, n_features: int,
                           n_students: int, n_days: int,
                           slots_per_day: int,
                           spec: BucketSpec = DEFAULT_SPEC) -> tuple:
    """bucket_key from raw instance counts — no Problem required.

    The fleet gateway (fleet/router.py) routes on the bucket key at
    admission, from nothing but the `.tim` header's four counts: the
    full parse (conflict matrices, suitability) happens once, on the
    replica that actually solves the job, never on the routing path."""
    return (_round_up(n_events, spec.event_floor, spec.ratio),
            _round_up(n_rooms, spec.room_floor, spec.ratio),
            _round_up(n_features, spec.feature_floor, spec.ratio),
            _round_up(n_students, spec.student_floor, spec.ratio),
            int(n_days), int(slots_per_day))


def bucket_key(problem: Problem, spec: BucketSpec = DEFAULT_SPEC
               ) -> tuple:
    """The compile-compatibility key: bucket dims + the slot grid.

    Two jobs with equal bucket_key (and equal breeding config) execute
    the SAME compiled island programs — the scheduler packs them into
    one dispatch and the engine's program caches serve both."""
    return bucket_key_from_counts(
        problem.n_events, problem.n_rooms, problem.n_features,
        problem.n_students, problem.n_days, problem.slots_per_day, spec)


def pad_problem(problem: Problem, spec: BucketSpec = DEFAULT_SPEC
                ) -> Problem:
    """Pad `problem` up to its bucket boundaries with masked padding.

    Returns a new Problem whose raw arrays are zero-padded to
    `bucket_dims`, whose `possible` matrix enforces the neutrality
    contract (module docstring), and whose `n_live_events` /
    `n_live_rooms` drive the ProblemArrays validity masks. Idempotent
    on an already-bucket-shaped instance (same dims in = same dims
    out), and a no-op-shaped instance still gets the mask fields set."""
    E, R, F, S = (problem.n_events, problem.n_rooms, problem.n_features,
                  problem.n_students)
    Ep, Rp, Fp, Sp = bucket_dims(problem, spec)
    # The room-key packing bound (ops/rooms.py: `assert E < 4096 and
    # R < _W_UNSUIT`) applies to the PADDED dims — geometric rounding
    # can push an instance the single-run engine solves fine (e.g.
    # E = 2500) up to a bucket that would assert at trace time. Reject
    # it here, at admission, with an actionable error instead.
    if Ep >= 4096 or Rp >= 4096:
        raise ValueError(
            f"instance too large for serve bucketing: padded dims "
            f"events={Ep} rooms={Rp} exceed the room-key packing "
            f"bound 4096 (instance events={E} rooms={R}; use the "
            f"single-run engine, or a finer BucketSpec ratio)")

    room_size = np.zeros((Rp,), np.int32)
    room_size[:R] = problem.room_size
    attends = np.zeros((Sp, Ep), np.int8)
    attends[:S, :E] = problem.attends
    room_features = np.zeros((Rp, Fp), np.int8)
    room_features[:R, :F] = problem.room_features
    event_features = np.zeros((Ep, Fp), np.int8)
    event_features[:E, :F] = problem.event_features

    padded = derive(Ep, Rp, Fp, Sp, room_size, attends, room_features,
                    event_features, n_days=problem.n_days,
                    slots_per_day=problem.slots_per_day)
    # derive() leaves zero-padding mostly neutral (conflict rows/cols and
    # student counts of padded events are zero by construction), but the
    # suitability matrix needs the explicit contract: a zero-requirement
    # live event would otherwise find a zero-capacity padded room
    # "possible", and padded events would look placeable everywhere.
    possible = np.array(padded.possible)
    possible[E:, :] = False       # padded events suit NO room
    possible[:, R:] = False       # padded rooms suit NO event
    # anchored-objective columns (serve/editsolve.py) ride along zero-
    # padded: padded events carry anchor weight 0, so the anchor cost of
    # a padded genotype equals the unpadded instance's bit-exactly (the
    # same neutrality contract as every other term)
    anchor_slots = anchor_w = None
    if problem.anchor_slots is not None:
        anchor_slots = np.zeros((Ep,), np.int32)
        anchor_slots[:E] = problem.anchor_slots
    if problem.anchor_w is not None:
        anchor_w = np.zeros((Ep,), np.int32)
        anchor_w[:E] = problem.anchor_w
    return dataclasses.replace(padded, possible=possible,
                               n_live_events=E, n_live_rooms=R,
                               anchor_slots=anchor_slots,
                               anchor_w=anchor_w)


def embed_population(slots: np.ndarray, rooms: np.ndarray,
                     padded: Problem) -> tuple[np.ndarray, np.ndarray]:
    """Extend (P, E) live genotypes to the padded (P, E') shape.

    Padded events are parked at slot 0 / room 0 — any valid indices
    work, since the masks make them fitness- and matching-invisible."""
    P, E = slots.shape
    Ep = padded.n_events
    s = np.zeros((P, Ep), np.int32)
    r = np.zeros((P, Ep), np.int32)
    s[:, :E] = slots
    r[:, :E] = rooms
    return s, r


def extract_solution(slots, rooms, padded: Problem):
    """Slice a padded genotype back to the live events."""
    E = (padded.n_live_events if padded.n_live_events is not None
         else padded.n_events)
    return np.asarray(slots)[..., :E], np.asarray(rooms)[..., :E]
