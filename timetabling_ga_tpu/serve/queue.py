"""Job admission and lifecycle for the solver service.

The backlog is BOUNDED (admission control): a service that accepts
unbounded work converts overload into unbounded latency for everyone;
rejecting at submit time converts it into immediate, actionable
backpressure — the same principle as jsonl.AsyncWriter's bounded
queue. Priorities order admission into the scheduler's lanes
(higher first, FIFO within a priority); a job's seed, generation
budget and wall-clock deadline travel with it, so one tenant's
parameters can never leak into another's stream.

Lifecycle:

    PENDING --admit--> RUNNING --quantum--> PARKED --resume--> RUNNING
       |                  |                    |
       |                  +------- budget/deadline ------> DONE
       +--cancel--> CANCELLED      (failure) ------------> FAILED

PARKED is the between-quanta state: the job's population lives as a
host snapshot (the PR-3 checkpoint tuple), not on the device, so a
parked job costs zero HBM and any number of jobs can share the lanes.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Optional

from timetabling_ga_tpu.problem import Problem


class JobState:
    """String states (JSON-friendly; no enum dependency in records)."""
    PENDING = "pending"
    RUNNING = "running"
    PARKED = "parked"
    DONE = "done"
    FAILED = "failed"
    CANCELLED = "cancelled"
    SHED = "shed"         # dropped by backpressure (scheduler shed):
    #                       the lowest-priority runnable work is
    #                       released when a registry depth crosses its
    #                       configured high-water mark — immediate,
    #                       visible load shedding instead of unbounded
    #                       latency for everyone
    PREEMPTED = "preempted"  # released by a preempt drain (POST
    #                       /v1/drain?mode=preempt, or SIGTERM on a
    #                       spot worker with --preempt-on-term): the
    #                       replica stops advancing the job and SHIPS
    #                       its park snapshot instead — terminal for
    #                       THIS replica, but the fleet gateway reads
    #                       it as "resume me elsewhere", never as done
    #                       (fleet/gateway.py _poll_replicas)

    ACTIVE = (PENDING, RUNNING, PARKED)
    TERMINAL = (DONE, FAILED, CANCELLED, SHED)


class AdmissionError(RuntimeError):
    """Backlog full — the job was NOT admitted (admission control)."""


@dataclasses.dataclass
class Job:
    """One solve request plus its runtime bookkeeping."""

    id: str
    problem: Problem                  # the parsed, UNPADDED instance
    priority: int = 0                 # higher = served first
    seed: int = 0
    generations: int = 200            # total generation budget
    deadline_s: Optional[float] = None  # wall-clock bound from submit
    tenant: str = "default"           # who submitted it (tt-meter,
    #                                   obs/usage.py): every share of
    #                                   fleet capacity this job
    #                                   consumes is attributed to this
    #                                   tag — the usage.tenant.<t>.*
    #                                   metrics namespace, usageEntry
    #                                   records, and GET /v1/usage
    count_usage: bool = True          # False on a fleet RESEND (the
    #                                   gateway's X-TT-Resubmit): the
    #                                   job is metered but not
    #                                   re-counted in its tenant's
    #                                   `jobs` ledger — the first
    #                                   admission already billed it
    # -- runtime (owned by the scheduler) --------------------------------
    state: str = JobState.PENDING
    seq: int = 0                      # admission order (FIFO tie-break)
    padded: Optional[Problem] = None  # bucket-padded instance
    bucket: Optional[tuple] = None    # serve.bucket.bucket_key result
    pa_dev: object = None             # padded ProblemArrays (device)
    gens_done: int = 0
    chunks: int = 0                   # dispatched quanta (RNG stream idx)
    snapshot: object = None           # host PopState between quanta
    best: int = 2 ** 31 - 1           # reported-form best seen
    emitted: int = 2 ** 31 - 1        # logEntry floor (no duplicates)
    submitted_t: float = 0.0
    finished_t: Optional[float] = None
    result: Optional[dict] = None
    error: Optional[str] = None
    flow: int = 0                     # causal flow id (obs/spans.py
    #                                   new_flow): every span of this
    #                                   job's life shares it, so
    #                                   `tt trace --job ID` renders one
    #                                   connected end-to-end timeline
    # -- resume, don't replay (serve/snapshot.py; README "Fleet
    # resume") -----------------------------------------------------------
    resume_wire: object = None        # warm-start wire snapshot the
    #                                   submit carried (a failover
    #                                   resubmission, a preempted
    #                                   job's re-placement, or a
    #                                   client warm start): admitted
    #                                   as a PARKED job, init skipped
    resumed_at: int = 0               # gens_done restored at resume
    #                                   admission (0 = fresh solve)
    recoveries: int = 0               # quantum-fault requeues so far
    #                                   (scheduler._recover_quantum);
    #                                   over --max-job-recoveries the
    #                                   job fails ALONE, co-tenants
    #                                   untouched
    ship: object = None               # latest park-fence ShipUnit
    #                                   (host state + record prefix),
    #                                   replaced wholesale at every
    #                                   park — what ?snapshot=1 serves
    ship_records: list = dataclasses.field(default_factory=list)
    #                                   running mirror of THIS job's
    #                                   emitted records (the prefix a
    #                                   shipped snapshot carries so a
    #                                   resumed stream is whole)
    ship_truncated: bool = False      # the mirror hit its cap: a
    #                                   resumed stream can no longer
    #                                   claim identity (surfaced on
    #                                   the wire, never silent)
    ship_hot: bool = False            # someone polls ?snapshot=1 on
    #                                   this job (a gateway keeping a
    #                                   resume cache warm): its group
    #                                   parks at EVERY fence so each
    #                                   refresh ships current
    #                                   progress — device residency
    #                                   yields to snapshot freshness
    #                                   (serve/scheduler.py RESIDENCY)
    # -- tt-meter (obs/usage.py; README "Usage metering") ----------------
    usage: dict = dataclasses.field(default_factory=dict)
    #                                   cumulative per-job meter,
    #                                   REPLACED wholesale by the drive
    #                                   loop at every park fence (plain
    #                                   dict arithmetic — handler
    #                                   threads serving GET /v1/usage
    #                                   read one fence's meter or the
    #                                   next, never a torn mix). Ships
    #                                   with the snapshot wire as the
    #                                   usage cursor, so a resumed
    #                                   job's meter CONTINUES on the
    #                                   survivor instead of resetting
    first_work_t: Optional[float] = None  # first dispatch fence: the
    #                                   queue_seconds component's end
    last_fence_t: Optional[float] = None  # latest park fence: the next
    #                                   cycle's park_seconds baseline
    # -- tt-edit (serve/editsolve.py; README "Incremental re-solve") -----
    mode: str = "solve"               # "solve" | "edit": an edit job
    #                                   solves an EDITED instance
    #                                   warm-started from its base
    #                                   job's snapshot under the
    #                                   anchored objective; the tag
    #                                   rides jobEntry/usageEntry and
    #                                   the result so tt stats can
    #                                   split edit latency out
    edit_of: Optional[str] = None     # base job id (or None for an
    #                                   inline base instance)
    edit_map: object = None           # (E_edited,) int32 event map:
    #                                   edited event -> base event
    #                                   index, -1 for added events —
    #                                   what edit_distance reports
    #                                   against at finalize
    edit_demoted: bool = False        # the warm start failed (cross-
    #                                   bucket edit, missing/bad base
    #                                   snapshot): the job ran as a
    #                                   cold solve of the edited
    #                                   instance (counted, never an
    #                                   error)

    def runnable(self) -> bool:
        return self.state in (JobState.PENDING, JobState.RUNNING,
                              JobState.PARKED)

    def remaining(self) -> int:
        return max(0, self.generations - self.gens_done)


class JobQueue:
    """Bounded, priority-ordered job table.

    Holds every job the service knows about (terminal jobs stay
    queryable until `forget`); `backlog` bounds only the ACTIVE set.
    Single-threaded by design — the scheduler drives it between
    dispatches, the service mutates it between requests; there is no
    concurrent producer the way there is for AsyncWriter."""

    def __init__(self, backlog: int = 64, now=None):
        import time
        self._backlog = backlog
        self._jobs: dict[str, Job] = {}
        self._seq = itertools.count()
        self._now = now or time.monotonic

    def __len__(self) -> int:
        return len(self._jobs)

    def __contains__(self, job_id: str) -> bool:
        return job_id in self._jobs

    def active(self) -> list[Job]:
        return [j for j in self._jobs.values() if j.runnable()]

    def submit(self, job: Job) -> str:
        if job.id in self._jobs:
            raise AdmissionError(f"duplicate job id {job.id!r}")
        if len(self.active()) >= self._backlog:
            raise AdmissionError(
                f"backlog full ({self._backlog} active jobs) — "
                f"job {job.id!r} rejected")
        job.seq = next(self._seq)
        job.submitted_t = self._now()
        job.state = JobState.PENDING
        self._jobs[job.id] = job
        return job.id

    def get(self, job_id: str) -> Job:
        return self._jobs[job_id]

    def cancel(self, job_id: str) -> bool:
        """Cancel a job: immediate for PENDING/PARKED, honored at the
        next control fence for RUNNING (the scheduler checks state
        between dispatches — a quantum is never interrupted mid-kernel,
        exactly like every other engine control decision)."""
        job = self._jobs.get(job_id)
        if job is None or job.state in JobState.TERMINAL:
            return False
        job.state = JobState.CANCELLED
        job.finished_t = self._now()
        job.snapshot = None
        job.ship = None
        return True

    def ready(self, bucket: Optional[tuple] = None) -> list[Job]:
        """Runnable jobs (optionally of one bucket), scheduling order:
        higher priority first, then least-served, then admission order —
        the least-served term is what lets a small late job overtake a
        long early one inside a full bucket (fairness)."""
        jobs = [j for j in self.active()
                if bucket is None or j.bucket == bucket]
        return sorted(jobs, key=lambda j: (-j.priority, j.gens_done,
                                           j.seq))

    def forget(self, job_id: str) -> None:
        self._jobs.pop(job_id, None)
