"""tt-serve — the multi-tenant batched solver service (ISSUE 4).

The engine solves one `.tim` instance per invocation, inheriting the
reference's one-problem-one-process shape (`mpirun ... -i comp01.tim`).
This subsystem turns the same compiled island machinery into a SERVER:
many concurrent solve jobs, admitted through a bounded queue, batched
onto shared accelerator hardware, time-sliced so late arrivals don't
starve, and streamed back as job-tagged JSONL records.

Four layers (each its own module):

  bucket.py     shape bucketing: pad a parsed Problem's arrays up to
                geometric bucket boundaries with validity masks, so
                every job in a bucket hits the SAME compiled island
                programs — compile-cache keys become bucket shapes,
                not instance shapes. Padding is provably neutral:
                padded events carry zero attendance/features and
                padded rooms zero capacity, and the mask-aware kernels
                (ops/fitness.py, ops/rooms.py, ops/delta.py,
                ops/sweep.py) keep (penalty, hcv, scv) and the greedy
                matching bit-exact vs the unpadded instance.
  queue.py      job admission and lifecycle: bounded backlog,
                priorities, per-job seed/budget/deadline, cancellation.
  scheduler.py  packs compatible queued jobs into one mesh dispatch
                (jobs stacked along the island axis — one lane each),
                time-slices long jobs into generation quanta at the
                engine's control-fence boundaries, and parks/resumes
                jobs through the PR-3 host-snapshot machinery
                (engine.fetch_state / engine.reshard_state).
  service.py    the frontend: a Python API (SolveService) and a
                line-JSON protocol (`tt serve`, cli.py), streaming each
                job's records tagged with a `job` id through the
                existing jsonl.AsyncWriter.

EvoX (arXiv:2301.12457) motivates the shape: evolutionary workloads as
batched tensor programs behind a scheduling layer; the wafer-scale
island work (arXiv:2405.03605) multiplexes island populations far
beyond one problem's needs the same way.
"""

from timetabling_ga_tpu.serve.bucket import (  # noqa: F401
    BucketSpec, bucket_dims, bucket_key, pad_problem)
from timetabling_ga_tpu.serve.queue import (  # noqa: F401
    AdmissionError, Job, JobQueue, JobState)
