"""Late-Acceptance Hill Climbing chains — the scv-endgame walker.

Motivation (BASELINE.md round 5, asymmetric race): the post-feasibility
scv endgame is where the reference's sequential first-improvement walk
(Solution.cpp:619-768) is more sample-efficient per candidate than our
best-improvement sweeps — at a 32x CPU budget it out-polishes them on
comp01s/comp05s. Best-improvement + stall kicks plateau because every
accepted move must improve (or drift sideways); deep scv basins need
CONTROLLED uphill acceptance. Late-Acceptance Hill Climbing (Burke &
Bykov, "The late acceptance Hill-Climbing heuristic", EJOR 2017 —
introduced ON timetabling benchmarks) is exactly that mechanism, and it
is TPU-shaped: P independent walkers vmapped, each taking one cheap
delta-evaluated random move per `lax.fori_loop` step, with no
data-dependent shapes.

The rule, per walker: keep a ring buffer `hist` of the last-seen costs
at each phase of a length-Lh cycle. A candidate is accepted iff it is
no worse than the CURRENT cost or no worse than the cost Lh steps ago:

    v = step mod Lh
    accept = cand <= hist[v]  OR  cand <= cur        (lexicographic)
    move if accept; hist[v] = cur'; step += 1

Early in the run hist holds high costs, so the walker crosses wide
plateaus and shallow hills; as improvements feed back into hist the
acceptance tightens toward pure hill-climbing — an annealing schedule
with ONE parameter (Lh) and no temperature tuning.

Costs are compared in the reported evaluation's total order
(hcv*1e6 + scv, ga.cpp:191) expressed overflow-safely as the
lexicographic pair (penalty, scv) — see fitness.lex_order. Once a
walker is feasible it can never be accepted into infeasibility: an
infeasible candidate's penalty (1e6 + hcv) lex-dominates every
feasible history entry, so the rule rejects it without a gate.

Candidates are the reference's own move distribution: `sample_move`
(Move1/2/3 at p1:p2:p3, Solution.cpp:441-469) delta-evaluated by
`_delta_one` — the bit-exactness-tested kernel the sweeps share.

Best-so-far tracking: LAHC walkers wander uphill by design, so each
walker carries its best-seen (slots, rooms, hcv, scv); the final answer
is the best snapshot, not the walker's current position.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from timetabling_ga_tpu.obs import prof as obs_prof
from timetabling_ga_tpu.ops import fitness
from timetabling_ga_tpu.ops.delta import (
    LSState, _apply_move, _delta_one, init_state)
from timetabling_ga_tpu.ops.moves import sample_move
from timetabling_ga_tpu.ops.rooms import capacity_rank


class LahcState(NamedTuple):
    """Per-walker LAHC state. Every field has leading axis P, so one
    sharding spec covers the whole tree (the per-walker `step` counters
    are identical by construction; per-walker storage keeps the pytree
    uniformly island-shardable)."""

    ls: LSState            # current walker positions + maintained tensors
    hist_pen: jnp.ndarray  # (P, Lh) int32 ring buffer of penalties
    hist_scv: jnp.ndarray  # (P, Lh) int32 ring buffer of scv tie-breaks
    step: jnp.ndarray      # (P,) int32 chain position (mod Lh indexing)
    best_slots: jnp.ndarray  # (P, E) int32 best-so-far snapshot
    best_rooms: jnp.ndarray  # (P, E) int32
    best_pen: jnp.ndarray    # (P,) int32
    best_hcv: jnp.ndarray    # (P,) int32
    best_scv: jnp.ndarray    # (P,) int32


def _lex_le(p_a, s_a, p_b, s_b):
    """(p_a, s_a) <= (p_b, s_b) in the reported-metric order."""
    return (p_a < p_b) | ((p_a == p_b) & (s_a <= s_b))


def _lex_lt(p_a, s_a, p_b, s_b):
    return (p_a < p_b) | ((p_a == p_b) & (s_a < s_b))


@obs_prof.scope("tt.lahc")
def init_lahc(pa, slots, rooms_arr, hist_len: int) -> LahcState:
    """Start P walkers at the given genotypes; history primed with each
    walker's initial cost (the standard LAHC initialization: hist[k] :=
    f(s0) for all k)."""
    ls = init_state(pa, slots, rooms_arr)
    P = slots.shape[0]
    ones = jnp.ones((P, hist_len), jnp.int32)
    return LahcState(
        ls=ls,
        hist_pen=ones * ls.pen[:, None],
        hist_scv=ones * ls.scv[:, None],
        step=jnp.zeros((P,), jnp.int32),
        best_slots=slots, best_rooms=rooms_arr,
        best_pen=ls.pen, best_hcv=ls.hcv, best_scv=ls.scv)


@obs_prof.scope("tt.lahc")
def lahc_steps(pa, key, state: LahcState, n_steps,
               p1: float = 1.0, p2: float = 1.0, p3: float = 0.0,
               k_cands: int = 1):
    """Advance every walker `n_steps` LAHC steps (`n_steps` is a RUNTIME
    scalar — one compile serves every chunk size; the engine sizes
    chunks to its wall-clock budget like every other dispatch).

    `k_cands` > 1 evaluates a block of K independent random candidates
    per walker per step IN PARALLEL and applies the late-acceptance rule
    to the lex-best of the block ("steepest-of-K LAHC"). At endgame
    population sizes the chain is dispatch-latency-bound, so the K
    extra delta evaluations ride along nearly free (vmap width, not
    scan depth) — K× the candidate throughput per wall-second. A
    uniform random single candidate is a very sparse sample of the
    Move1/2/3 neighborhood; the measured single-candidate chain lost
    to the sweep endgame ~25x on candidates/sec (BASELINE.md round 5),
    and best-of-K closes exactly that gap while keeping the acceptance
    semantics (when the block's best is uphill — a local optimum — the
    rule still takes the controlled uphill step)."""
    cap_rank = capacity_rank(pa)
    P, Lh = state.hist_pen.shape

    def one_step(i, st: LahcState) -> LahcState:
        keys = jax.random.split(jax.random.fold_in(key, i), P)

        def per_walker(k, s, r, att, occ, pen, hcv, scv, hp, hs, step):
            # anchor residual of the walker's maintained pen (exact:
            # init_lahc's pen rides batch_penalty, which includes the
            # anchor term; 0 on unanchored instances) — candidates carry
            # it plus their own anchor delta so the chain accepts on the
            # same anchored objective selection uses
            anc = pen - fitness.base_penalty(hcv, scv)

            def one_cand(kc):
                evs, new_slots, active = sample_move(pa, kc, s, p1, p2,
                                                     p3)
                d_hcv, d_scv, new_rooms = _delta_one(
                    pa, s, r, att, occ, evs, new_slots, active,
                    cap_rank)
                d_anc = fitness.anchor_delta(pa, s, evs, new_slots)
                return d_hcv, d_scv, d_anc, evs, new_slots, new_rooms

            if k_cands > 1:
                dh, ds, da, evs_k, ns_k, nr_k = jax.vmap(one_cand)(
                    jax.random.split(k, k_cands))
                ch = hcv + dh
                cs = scv + ds
                cp = fitness.base_penalty(ch, cs) + anc + da
                # lex-argmin over the block (exact integer arithmetic)
                b = jnp.lexsort((cs, cp))[0]
                evs, new_slots, new_rooms = evs_k[b], ns_k[b], nr_k[b]
                c_hcv, c_scv, c_pen = ch[b], cs[b], cp[b]
            else:
                d_hcv, d_scv, d_anc, evs, new_slots, new_rooms = one_cand(k)
                c_hcv = hcv + d_hcv
                c_scv = scv + d_scv
                c_pen = (fitness.base_penalty(c_hcv, c_scv)
                         + anc + d_anc)
            v = step % Lh
            accept = (_lex_le(c_pen, c_scv, hp[v], hs[v])
                      | _lex_le(c_pen, c_scv, pen, scv))
            s2, r2, att2, occ2 = _apply_move(
                pa, (s, r, att, occ), evs, new_slots, new_rooms)
            s = jnp.where(accept, s2, s)
            r = jnp.where(accept, r2, r)
            att = jnp.where(accept, att2, att)
            occ = jnp.where(accept, occ2, occ)
            pen = jnp.where(accept, c_pen, pen)
            hcv = jnp.where(accept, c_hcv, hcv)
            scv = jnp.where(accept, c_scv, scv)
            # history takes the POST-decision current cost (Burke-Bykov
            # update order: acceptance first, then hist[v] := f(current))
            hp = hp.at[v].set(pen)
            hs = hs.at[v].set(scv)
            return s, r, att, occ, pen, hcv, scv, hp, hs, step + 1

        (s, r, att, occ, pen, hcv, scv, hp, hs, step) = jax.vmap(
            per_walker)(keys, st.ls.slots, st.ls.rooms, st.ls.att,
                        st.ls.occ, st.ls.pen, st.ls.hcv, st.ls.scv,
                        st.hist_pen, st.hist_scv, st.step)

        improved = _lex_lt(pen, scv, st.best_pen, st.best_scv)   # (P,)
        return LahcState(
            ls=LSState(slots=s, rooms=r, att=att, occ=occ,
                       pen=pen, hcv=hcv, scv=scv),
            hist_pen=hp, hist_scv=hs, step=step,
            best_slots=jnp.where(improved[:, None], s, st.best_slots),
            best_rooms=jnp.where(improved[:, None], r, st.best_rooms),
            best_pen=jnp.where(improved, pen, st.best_pen),
            best_hcv=jnp.where(improved, hcv, st.best_hcv),
            best_scv=jnp.where(improved, scv, st.best_scv))

    return lax.fori_loop(0, n_steps, one_step, state)


@functools.partial(jax.jit, static_argnames=("hist_len",))
def jit_init_lahc(pa, slots, rooms_arr, hist_len: int):
    return init_lahc(pa, slots, rooms_arr, hist_len)


@functools.partial(jax.jit,
                   static_argnames=("p1", "p2", "p3", "k_cands"))
def jit_lahc_steps(pa, key, state: LahcState, n_steps,
                   p1: float = 1.0, p2: float = 1.0, p3: float = 0.0,
                   k_cands: int = 1):
    return lahc_steps(pa, key, state, n_steps, p1, p2, p3, k_cands)
