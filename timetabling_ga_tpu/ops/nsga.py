"""NSGA-II style multi-objective selection on (hcv, scv).

The reference is single-objective (penalty scalarization,
Solution.cpp:162-170), but the benchmark protocol (BASELINE.json config 5;
SURVEY section 7.7) calls for a multi-objective HCV/SCV variant: treat
hard and soft violations as two minimization objectives, rank by
non-dominated fronts (NSGA-II, Deb et al. 2002 — public algorithm,
re-derived here in batched tensor form), and break ties within a front by
crowding distance.

Everything is fixed-shape for XLA:
  - the domination matrix is one (N, N) tensor expression;
  - front peeling is a bounded `fori_loop` over at most `max_fronts`
    rounds (any residue gets the worst rank — harmless for selection);
  - crowding distances come from two argsorts (one per objective), with
    +inf at each front's boundary individuals.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# Python float, not jnp.float32: a module-level device constant would
# initialize the JAX backend at import time (see rooms._BIG note)
INF = float("inf")


def domination_matrix(hcv: jnp.ndarray, scv: jnp.ndarray) -> jnp.ndarray:
    """dom[i, j] = True iff i dominates j on (hcv, scv), both minimized:
    i is no worse in both and strictly better in at least one."""
    h_le = hcv[:, None] <= hcv[None, :]
    s_le = scv[:, None] <= scv[None, :]
    h_lt = hcv[:, None] < hcv[None, :]
    s_lt = scv[:, None] < scv[None, :]
    return h_le & s_le & (h_lt | s_lt)


def nondominated_ranks(hcv: jnp.ndarray, scv: jnp.ndarray) -> jnp.ndarray:
    """Front index per individual (0 = Pareto front). Complete peeling
    under `lax.while_loop` — a converging integer-objective population
    can have hundreds of fronts, so no fixed bound is imposed (the loop
    runs at most N rounds by construction)."""
    N = hcv.shape[0]
    UNASSIGNED = jnp.int32(N + 1)
    dom = domination_matrix(hcv, scv)
    n_dominators = jnp.sum(dom, axis=0).astype(jnp.int32)     # (N,)
    ranks0 = jnp.full((N,), UNASSIGNED, jnp.int32)

    def cond(carry):
        ranks, _, _ = carry
        return jnp.any(ranks == UNASSIGNED)

    def body(carry):
        ranks, n_dom, f = carry
        front = (n_dom == 0) & (ranks == UNASSIGNED)
        ranks = jnp.where(front, f, ranks)
        # remove the front's domination contributions
        removed = jnp.sum(dom & front[:, None], axis=0).astype(jnp.int32)
        n_dom = jnp.where(front, -1, n_dom - removed)
        return ranks, n_dom, f + 1

    ranks, _, _ = lax.while_loop(
        cond, body, (ranks0, n_dominators, jnp.int32(0)))
    return ranks


def crowding_distance(hcv: jnp.ndarray, scv: jnp.ndarray,
                      ranks: jnp.ndarray) -> jnp.ndarray:
    """Per-individual crowding distance within its front (larger =
    lonelier = preferred). Boundary individuals of each front get +inf."""
    N = hcv.shape[0]
    dist = jnp.zeros((N,), jnp.float32)
    for obj_i in (hcv, scv):
        # sort within front: exact lexicographic (rank, objective) via
        # lexsort (stable, two int32 keys) — no int64 needed, and no
        # composite key to overflow or truncate
        order = jnp.lexsort((obj_i, ranks))            # (N,)
        obj = obj_i.astype(jnp.float32)
        obj_s = obj[order]
        rank_s = ranks[order]
        lo = jnp.concatenate([jnp.array([-jnp.inf]), obj_s[:-1]])
        hi = jnp.concatenate([obj_s[1:], jnp.array([jnp.inf])])
        same_lo = jnp.concatenate(
            [jnp.array([False]), rank_s[1:] == rank_s[:-1]])
        same_hi = jnp.concatenate(
            [rank_s[:-1] == rank_s[1:], jnp.array([False])])
        # range normalization per front is overkill; global range works
        # for ranking purposes and keeps everything fixed-shape
        rng = jnp.maximum(jnp.max(obj) - jnp.min(obj), 1.0)
        gap = jnp.where(same_lo & same_hi, (hi - lo) / rng, INF)
        dist = dist.at[order].add(gap)
    return dist


def nsga_survivor_indices(hcv: jnp.ndarray, scv: jnp.ndarray,
                          n_survivors: int) -> jnp.ndarray:
    """Indices of the NSGA-II survivors (rank asc, crowding desc) —
    the multi-objective replacement for mu+lambda penalty truncation."""
    ranks = nondominated_ranks(hcv, scv)
    crowd = crowding_distance(hcv, scv, ranks)
    # exact lexicographic (rank asc, crowd desc): lexsort is stable, so
    # no composite key and no float-precision collapse (the rank step
    # survives any magnitude, unlike rank + 1/(1+crowd))
    return jnp.lexsort((-crowd, ranks))[:n_survivors]


def crowded_tournament(key, ranks: jnp.ndarray, crowd: jnp.ndarray,
                       k: int) -> jnp.ndarray:
    """k-way tournament under the crowded comparison operator
    (rank asc, crowding desc) — the NSGA-II parent selector."""
    N = ranks.shape[0]
    draws = jax.random.randint(key, (k,), 0, N)
    # crowded-comparison winner: exact lexicographic (rank asc, crowd
    # desc) over the k draws, same ordering as nsga_survivor_indices
    best = jnp.lexsort((-crowd[draws], ranks[draws]))[0]
    return draws[best]
