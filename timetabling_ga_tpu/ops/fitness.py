"""Batched fitness evaluation: hard/soft constraint violations as one
jit+vmap tensor program.

This is the TPU-native redesign of the reference's scalar evaluation loops
(Solution::computeHcv Solution.cpp:141-160, Solution::computeScv 86-139,
Solution::computeFeasibility 63-84, Solution::computePenalty 162-170).
Where the reference walks O(E^2) event pairs per solution, the kernels here
express the same counts as dense contractions over one-hot occupancy
tensors so XLA tiles them onto the MXU and a whole population is evaluated
in one launch:

  room/slot clash pairs : occupancy counts n[t, r] via (T,E)x(E,R) matmul,
                          then sum n(n-1)/2
  correlated-slot pairs : einsum('te,ef,tf->', X, C, X) with the diagonal
                          removed, X = slot one-hot (T, E), C = conflict
  unsuitable rooms      : one gather per event
  soft constraints      : per-(student, slot) attendance A = attends @ X^T,
                          then window products for runs-of-3, per-day sums
                          for single-class days, masked sums for last-slot

All operands are 0/1-valued float32, so counts are exact (<< 2^24).
Every public function evaluates ONE individual `(E,)`; `batch_*` wrappers
vmap over a population axis.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# tt-prof phase scopes (obs/prof.py): @obs_prof.scope("tt.<phase>")
# wraps tracing in jax.named_scope — metadata-only, so records, RNG
# streams and compile-cache keys are bit-identical with scopes on or
# off (tests/test_prof.py asserts this). The profiler's attribution
# joins device ops back to these names.
from timetabling_ga_tpu.obs import prof as obs_prof

# Penalty encoding (reference Solution.cpp:167 and ga.cpp:191):
INFEASIBLE_OFFSET = 1_000_000


def slot_onehot(slots: jnp.ndarray, n_slots: int) -> jnp.ndarray:
    """(E,) int32 -> (T, E) float32 one-hot of event timeslots."""
    return (slots[None, :] == jnp.arange(n_slots, dtype=slots.dtype)[:, None]
            ).astype(jnp.float32)


def room_onehot(rooms: jnp.ndarray, n_rooms: int) -> jnp.ndarray:
    """(E,) int32 -> (R, E) float32 one-hot of event rooms."""
    return (rooms[None, :] == jnp.arange(n_rooms, dtype=rooms.dtype)[:, None]
            ).astype(jnp.float32)


@obs_prof.scope("tt.fitness")
def compute_hcv(pa, slots: jnp.ndarray, rooms: jnp.ndarray) -> jnp.ndarray:
    """Hard-constraint violations of one individual (int32 scalar).

    Exact count parity with Solution::computeHcv (Solution.cpp:141-160).
    """
    T = pa.n_slots
    R = pa.n_rooms
    # Padded (masked-out) events occupy nothing and count nowhere: their
    # one-hot columns are zeroed, so they vanish from the occupancy and
    # correlation contractions exactly (their conflict rows/columns are
    # already zero by construction — serve/bucket.py). On unpadded
    # instances event_mask is all-ones and the multiply is exact.
    X = slot_onehot(slots, T) * pa.event_mask[None, :]   # (T, E)
    Y = room_onehot(rooms, R)                      # (R, E)

    # (a) events sharing (slot, room): occupancy n[t, r], pairs = C(n, 2)
    occ = X @ Y.T                                   # (T, R) counts, MXU
    pair_clash = jnp.sum(occ * (occ - 1.0)) * 0.5

    # (b) correlated events sharing a slot: sum_t x_t^T C x_t counts each
    # unordered pair twice and each event once on the diagonal (an event is
    # in exactly one slot and C[e,e]=1 iff the event has students).
    cx = pa.conflict @ X.T                          # (E, T), MXU
    full = jnp.sum(X.T * cx)
    diag = jnp.sum(jnp.diagonal(pa.conflict))
    corr_pairs = (full - diag) * 0.5

    # (c) event in unsuitable room — padded events suit no room by
    # construction, so the mask keeps them out of the count
    unsuitable = jnp.sum(
        (~pa.possible[jnp.arange(slots.shape[0]), rooms])
        * pa.event_mask.astype(jnp.int32))

    return (pair_clash + corr_pairs).astype(jnp.int32) + unsuitable.astype(
        jnp.int32)


@obs_prof.scope("tt.fitness")
def attendance_matrix(pa, slots: jnp.ndarray) -> jnp.ndarray:
    """Per-(student, slot) attended-event counts A (S, T) float32.

    A = attends @ X^T — the big MXU contraction shared by all soft
    constraints; kept public so the local search can rank-1-update it.
    """
    X = slot_onehot(slots, pa.n_slots)              # (T, E)
    return pa.attends @ X.T                         # (S, T)


@obs_prof.scope("tt.fitness")
def scv_from_attendance(pa, slots: jnp.ndarray,
                        att: jnp.ndarray) -> jnp.ndarray:
    """Soft-constraint violations given the attendance count matrix.

    Semantics of Solution::computeScv (Solution.cpp:86-139); attendance is
    binarized (B = A > 0) exactly as the reference's per-slot early-exit
    event scan does (Solution.cpp:105-114).
    """
    spd = pa.slots_per_day
    D = pa.n_days

    # (a) class in last slot of day: studentNumber[e] per offending event
    last = jnp.sum(jnp.where(slots % spd == spd - 1, pa.student_count, 0))

    B = (att > 0).reshape(att.shape[0], D, spd)     # (S, D, spd) bool

    # (b) each attended slot that is the >=3rd consecutive within a day
    consec = jnp.sum((B[:, :, 2:] & B[:, :, 1:-1] & B[:, :, :-2]
                      ).astype(jnp.int32))

    # (c) exactly one attended slot in a day
    single = jnp.sum((B.sum(axis=2) == 1).astype(jnp.int32))

    return last.astype(jnp.int32) + consec + single


def compute_scv(pa, slots: jnp.ndarray) -> jnp.ndarray:
    """Soft-constraint violations of one individual (int32 scalar)."""
    return scv_from_attendance(pa, slots, attendance_matrix(pa, slots))


def compute_feasible(pa, slots, rooms) -> jnp.ndarray:
    """feasible <=> hcv == 0 (Solution.cpp:63-84 checks the same three
    conditions with early exit)."""
    return compute_hcv(pa, slots, rooms) == 0


def base_penalty(hcv, scv):
    """The un-anchored penalty encoding (Solution.cpp:162-170): scv if
    feasible else 1_000_000 + hcv. Shared by compute_penalty and every
    delta-path acceptance site, which recovers a state's anchor residual
    as `pen - base_penalty(hcv, scv)` (exact: all integer arithmetic)."""
    return jnp.where(hcv == 0, scv, INFEASIBLE_OFFSET + hcv)


@obs_prof.scope("tt.fitness")
def anchor_cost(pa, slots) -> jnp.ndarray:
    """Anchored-objective term of one individual (int32 scalar):
    `sum_e anchor_w[e] * [slots[e] != anchor_slots[e]]` — a weighted
    Hamming distance to the base solution (serve/editsolve.py). The
    mask discipline rides the weights: padded and newly-added events
    carry anchor_w == 0, so no event_mask gating is needed, and an
    all-zero weight column makes this exactly 0 (w_anchor == 0 is
    bit-identical to the unanchored objective)."""
    return jnp.sum(pa.anchor_w
                   * (slots != pa.anchor_slots).astype(jnp.int32))


@obs_prof.scope("tt.fitness")
def anchor_delta(pa, slots, evs, new_slots) -> jnp.ndarray:
    """Anchor-cost change of a sparse move: events `evs` (M,) moving from
    `slots[evs]` to `new_slots` (M,). Inactive move lanes (padding in the
    fixed-width move encoding, ops/delta.py) pass new == old and cancel
    exactly; events with anchor_w == 0 contribute 0 either way."""
    w = pa.anchor_w[evs]
    old = slots[evs]
    anc = pa.anchor_slots[evs]
    return jnp.sum(w * ((new_slots != anc).astype(jnp.int32)
                        - (old != anc).astype(jnp.int32)))


def compute_penalty(pa, slots, rooms):
    """Internal selection penalty (Solution.cpp:162-170) plus the
    anchored-objective term: base_penalty(hcv, scv) + anchor_cost.

    Returns (penalty, hcv, scv) — callers almost always want the parts
    too. hcv/scv stay pure constraint counts (the anchor term never
    leaks into reported evaluations); only the selection/acceptance
    penalty is anchored.
    """
    hcv = compute_hcv(pa, slots, rooms)
    scv = compute_scv(pa, slots)
    penalty = base_penalty(hcv, scv) + anchor_cost(pa, slots)
    return penalty, hcv, scv


def reported_evaluation(hcv, scv) -> int:
    """The evaluation the JSONL log reports for infeasible solutions:
    hcv * 1e6 + scv (ga.cpp:191, 218, 247). Host-side only: forced to
    Python ints so it cannot wrap int32 (hcv >= 2148 would overflow)."""
    return int(hcv) * INFEASIBLE_OFFSET + int(scv)


def lex_order(penalty, scv):
    """Sort indices by (penalty, scv) lexicographically — the total
    order of the REPORTED evaluation (hcv*1e6+scv, ga.cpp:191) expressed
    without its int32-overflowing composite: the internal penalty
    majorizes exactly as in the reported form (any hcv difference
    dominates; feasible penalty IS scv), and scv breaks penalty ties.

    The tie-break matters whenever hcv is pinned at an infeasibility
    floor: under plain penalty ordering the population drifts on scv —
    invisible internally, but the reported metric counts every point of
    it (round-4 race: `medium` never goes feasible for either side, so
    best-at-budget is decided entirely by scv at equal hcv)."""
    return jnp.lexsort((scv, penalty))


# ---------------------------------------------------------------------------
# Batched (population) forms
#
# The production batching is `jax.vmap` of the per-individual kernel: XLA
# lowers it to batched dot_generals with P as the batch dimension and
# fuses the one-hot construction into the matmul operands without
# materializing them in HBM. Measured on v5e (P=4096, E=400, S=350,
# inside a lax.scan so dispatch latency is amortized): ~2.7 ms/batch,
# ~1.5M full evaluations/s/chip.
#
# Rejected alternative (measured 6x SLOWER, kept as a lesson): flattening
# the population into the matmul N dimension — stacking slot one-hots
# into (P*T, E) and computing (P*T,E)@(E,E) and (S,E)@(E,P*T) — forces
# the 147-295MB one-hot intermediates through HBM, and a scatter-add
# histogram for room clashes costs 4x the entire vmapped program. bf16
# and int8 MXU variants of the vmapped path were also measured: no gain
# (the kernel is layout/bandwidth-bound, not matmul-rate-bound, at comp
# scale).


@jax.jit
def batch_penalty(pa, slots, rooms):
    """Evaluate a whole population: slots/rooms (P, E) -> (penalty, hcv,
    scv), each (P,) int32."""
    return jax.vmap(lambda s, r: compute_penalty(pa, s, r))(slots, rooms)


# Alias kept so cross-check tests can name the reference batching
# explicitly even if batch_penalty is later swapped for a fused kernel.
batch_penalty_vmapped = batch_penalty


def batch_hcv(pa, slots, rooms):
    return jax.vmap(lambda s, r: compute_hcv(pa, s, r))(slots, rooms)


def batch_scv(pa, slots):
    return jax.vmap(lambda s: compute_scv(pa, s))(slots)


def batch_feasible(pa, slots, rooms):
    return jax.vmap(lambda s, r: compute_feasible(pa, s, r))(slots, rooms)
