"""Systematic batched sweep local search — the fixed-shape analogue of the
reference's exhaustive first-improvement sweeps.

The reference's `Solution::localSearch` (Solution.cpp:471-769) walks events
in shuffled order and, for each, tries ALL 45 target slots (Move1,
Solution.cpp:508-534) and all swap partners (Move2, 535-561), accepting the
first improving candidate and resetting its pass counter — effectively
running to a local optimum. The round-1 K-random-candidate search
(ops/local_search.py) samples a far sparser neighborhood; this module
closes that power gap with fixed shapes:

  one PASS = `lax.scan` over event positions (shuffled per individual per
  pass, Solution.cpp:476-484). At each position, for every individual in
  the population simultaneously:
    - Move1: delta-evaluate relocating the event to ALL T slots at once
      (each target also re-rooms the event greedily in its new slot);
    - Move2: delta-evaluate swapping with a block of `swap_block` partner
      events (the next B events in the permutation, so successive passes
      rotate coverage across all partners);
    - accept the BEST strictly improving candidate (best-improvement per
      event vs the reference's first-improvement — a documented
      divergence that only strengthens the per-event step).

Delta costs are neighborhood-local: the Move1 sweep computes all T slot
deltas in O(S*T + E + T*R) per event by expressing the scv change of
adding/removing one attendance as a function of the 4-slot window around
the target (a run-of-3 can only be created through the inserted slot, and
single-day counts shift by one) instead of re-scoring whole days per
candidate. The two phases (hcv repair, then scv polish that never breaks
feasibility) need no explicit gate: acceptance compares the scalar penalty
`scv if feasible else 1e6+hcv` (Solution.cpp:162-170), under whose
ordering any hcv reduction dominates while infeasible and any
feasibility-breaking move is unacceptable once feasible.

Move3 (3-cycles) is off by default in the reference (p3=0, Control.cpp:
115-125) and is served by the random-candidate search (ops/local_search.py
/ ops/delta.py); the sweep covers Move1+Move2, the moves the reference
actually sweeps.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from timetabling_ga_tpu.ops import fitness
from timetabling_ga_tpu.ops.delta import (
    LSState, _apply_move, _day_scv, _delta_one, init_state)
from timetabling_ga_tpu.ops.rooms import _W_COST, _W_UNSUIT, capacity_rank


def _move1_sweep(pa, slots, rooms_arr, att, occ, e, cap_rank):
    """Delta-evaluate Move1(e, t) for EVERY target slot t of one
    individual. Returns (d_hcv (T,), d_scv (T,), new_rooms (T,)).

    The t == current-slot column is a re-rooming candidate (delta 0 when
    the greedy choice is the current room). Semantics per candidate match
    ops/delta.py's `_delta_one` for a single-event relocation exactly.
    """
    T = pa.n_slots
    spd = pa.slots_per_day
    D = pa.n_days
    S = pa.attends.shape[0]
    s_old = slots[e]
    r_old = rooms_arr[e]

    # ---- room-pair clashes + greedy re-rooming for every target slot
    occ32 = occ.astype(jnp.int32).at[s_old, r_old].add(-1)
    remove_d = -(occ.astype(jnp.int32)[s_old, r_old] - 1)
    suit = pa.possible[e]                                  # (R,)
    # marginal-hcv-cost key — MUST stay in lockstep with rooms._room_key
    unsuit = (~suit).astype(jnp.int32)[None, :]
    key = ((occ32 + unsuit) * _W_COST
           + unsuit * _W_UNSUIT
           + cap_rank[None, :])                            # (T, R)
    new_rooms = jnp.argmin(key, axis=1).astype(jnp.int32)  # (T,)
    add_d = occ32[jnp.arange(T), new_rooms]
    pair_d = remove_d + add_d

    unsuit_d = ((~pa.possible[e, new_rooms]).astype(jnp.int32)
                - (~pa.possible[e, r_old]).astype(jnp.int32))

    # ---- correlated-pair delta: conflicting events per slot (one
    # segment-sum over E), minus the current slot's count
    conf = pa.conflict[e].at[e].set(0.0)                   # (E,)
    per_slot = jnp.zeros((T,), jnp.float32).at[slots].add(conf)
    corr_d = (per_slot - per_slot[s_old]).astype(jnp.int32)

    d_hcv = pair_d + unsuit_d + corr_d

    # ---- scv: last-slot-of-day term
    sc = pa.student_count[e]
    t_idx = jnp.arange(T)
    last_d = (jnp.where(t_idx % spd == spd - 1, sc, 0)
              - jnp.where(s_old % spd == spd - 1, sc, 0))

    # ---- scv: day terms. Removing e from s_old re-scores one day
    # window; adding e at target t is neighborhood-local on the
    # binarized post-removal attendance b1:
    #   consec: a new run-of-3 through an empty slot j needs two
    #           attended neighbors on one side or both sides;
    #   single: day count 0 -> 1 creates a single, 1 -> 2 removes one.
    col = pa.attends[:, e].astype(jnp.int32)               # (S,)
    att1 = att.astype(jnp.int32).at[:, s_old].add(-col)

    d0 = s_old // spd
    before = lax.dynamic_slice(att.astype(jnp.int32),
                               (0, d0 * spd), (S, spd))
    after = lax.dynamic_slice(att1, (0, d0 * spd), (S, spd))
    rm_d = _day_scv(after > 0) - _day_scv(before > 0)

    b1 = (att1 > 0).reshape(S, D, spd)                     # (S, D, spd)
    z = jnp.zeros((S, D, 1), jnp.bool_)
    bp = jnp.concatenate([z, z, b1, z, z], axis=2)         # pad 2 each side
    # neighbors at distance 1/2 left/right of each in-day position
    l1, l2 = bp[:, :, 1:-3], bp[:, :, :-4]
    r1, r2 = bp[:, :, 3:-1], bp[:, :, 4:]
    free = ~b1
    # COUNT of new runs-of-3 through slot j (0..3), so each pair term
    # must be cast before summing (bool + bool is OR, not count)
    dconsec = free * ((l2 & l1).astype(jnp.int32)
                      + (l1 & r1).astype(jnp.int32)
                      + (r1 & r2).astype(jnp.int32))
    cnt = b1.sum(axis=2, dtype=jnp.int32)                  # (S, D)
    dsingle = free * ((cnt == 0).astype(jnp.int32)
                      - (cnt == 1).astype(jnp.int32))[:, :, None]
    add_per_target = jnp.einsum(
        "s,sdj->dj", col.astype(jnp.float32),
        (dconsec + dsingle).astype(jnp.float32)).reshape(T)

    d_scv = last_d + rm_d + add_per_target.astype(jnp.int32)
    return d_hcv, d_scv, new_rooms


def _distinct_pad(e1, e2, E: int):
    """An event index distinct from e1 and e2 (needs E >= 3)."""
    pad = (e1 + 1) % E
    return jnp.where(pad == e2, (e1 + 2) % E, pad)


def sweep_pass(pa, key, state: LSState, swap_block: int = 8,
               block_events: int = 1, sideways: float = 0.0):
    """One full sweep pass over all events (shuffled per individual).

    `block_events` = events examined per scan step. With 1 (default)
    this is the serial sweep: each event's accepted move is visible to
    the next event's deltas — maximum acceptance density per pass. With
    B > 1, B events' full candidate sets are delta-evaluated TOGETHER
    and only the single best improving move among them is applied, so
    the sequential scan depth drops from E to ceil(E/B): ~B x less
    wall-clock per pass (the per-step cost is latency- not flop-bound
    at comp scale) for at most 1/B the accepted moves per pass — a
    throughput/density trade the caller tunes. All delta semantics are
    shared with the B=1 path.

    Returns (state, improved) where `improved` is a scalar bool: did ANY
    individual accept ANY move this pass. A False means the entire
    population is at a Move1+Move2-block local optimum, the same
    fixed-point condition that ends the reference's localSearch (a full
    improving-free pass over all events, Solution.cpp:497-618 counter
    semantics)."""
    cap_rank = capacity_rank(pa)
    P, E = state.slots.shape
    T = pa.n_slots
    assert E >= 3, "padded 3-relocation form needs E >= 3"
    # partner offsets must stay within the permutation; clamp for tiny E
    swap_block = min(max(swap_block, 0), E - 1)
    B = min(max(block_events, 1), E)
    n_steps = (E + B - 1) // B

    k_perm, k_tie, k_side = jax.random.split(key, 3)
    perm_keys = jax.random.split(k_perm, P)
    perms = jax.vmap(
        lambda k: jax.random.permutation(k, E).astype(jnp.int32))(perm_keys)

    def step(st, pos):
        # block of B event positions (wraps at the tail when B ∤ E;
        # duplicate candidates are harmless — only one move is applied)
        idx = (pos * B + jnp.arange(B)) % E                # (B,)
        e_blk = perms[:, idx]                              # (P, B)

        def per_e(e_i, s, r, att, occ):
            # Move1: all T targets
            dh1, ds1, rooms1 = _move1_sweep(pa, s, r, att, occ, e_i,
                                            cap_rank)
            # pad events: distinct from e (and each other) so the padded
            # 3-relocation form's correlation terms stay exact
            p1 = _distinct_pad(e_i, e_i, E)
            p2 = _distinct_pad(e_i, p1, E)
            evs1 = jnp.broadcast_to(jnp.stack([e_i, p1, p2]), (T, 3))
            ns1 = jnp.stack([jnp.arange(T, dtype=jnp.int32),
                             jnp.broadcast_to(s[p1], (T,)),
                             jnp.broadcast_to(s[p2], (T,))], axis=1)
            nr1 = jnp.stack([rooms1,
                             jnp.broadcast_to(r[p1], (T,)),
                             jnp.broadcast_to(r[p2], (T,))], axis=1)
            return dh1, ds1, evs1, ns1, nr1

        def per_ind(es, s, r, att, occ):
            # (B, T), (B, T, 3), ... -> flatten candidates across block
            dh1, ds1, evs1, ns1, nr1 = jax.vmap(
                lambda e_i: per_e(e_i, s, r, att, occ))(es)
            return (dh1.reshape(-1), ds1.reshape(-1),
                    evs1.reshape(-1, 3), ns1.reshape(-1, 3),
                    nr1.reshape(-1, 3))

        # Move1 sweep for every individual
        dh1, ds1, evs1, ns1, nr1 = jax.vmap(per_ind)(
            e_blk, st.slots, st.rooms, st.att, st.occ)

        cand_dh, cand_ds = dh1, ds1                        # (P, B*T)
        cand_evs, cand_ns, cand_nr = evs1, ns1, nr1        # (P, B*T, 3)

        if swap_block > 0:
            # Move2 partners per block event j: the next swap_block
            # positions after its own (rotates coverage across passes,
            # as in the B=1 form)
            offs = (pos * B + jnp.arange(B)[:, None] + 1
                    + jnp.arange(swap_block)[None, :]) % E  # (B, SB)
            partners = perms[:, offs]                       # (P, B, SB)

            def swap_one(e_i, q, s, r, att, occ):
                pad = _distinct_pad(e_i, q, E)
                evs = jnp.stack([e_i, q, pad])
                ns = jnp.stack([s[q], s[e_i], s[pad]])
                active = jnp.array([True, True, False])
                dh, ds, nr = _delta_one(pa, s, r, att, occ, evs, ns,
                                        active, cap_rank)
                return dh, ds, evs, ns, nr

            def swaps_per_ind(es, qss, s, r, att, occ):
                dh, ds, evs, ns, nr = jax.vmap(jax.vmap(
                    lambda e_i, q: swap_one(e_i, q, s, r, att, occ)))(
                        jnp.broadcast_to(es[:, None], qss.shape), qss)
                return (dh.reshape(-1), ds.reshape(-1),
                        evs.reshape(-1, 3), ns.reshape(-1, 3),
                        nr.reshape(-1, 3))

            dh2, ds2, evs2, ns2, nr2 = jax.vmap(swaps_per_ind)(
                e_blk, partners, st.slots, st.rooms, st.att, st.occ)
            cand_dh = jnp.concatenate([cand_dh, dh2], axis=1)
            cand_ds = jnp.concatenate([cand_ds, ds2], axis=1)
            cand_evs = jnp.concatenate([cand_evs, evs2], axis=1)
            cand_ns = jnp.concatenate([cand_ns, ns2], axis=1)
            cand_nr = jnp.concatenate([cand_nr, nr2], axis=1)

        new_hcv = st.hcv[:, None] + cand_dh                # (P, C)
        new_scv = st.scv[:, None] + cand_ds
        new_pen = jnp.where(new_hcv == 0, new_scv,
                            fitness.INFEASIBLE_OFFSET + new_hcv)
        ar = jnp.arange(P)
        if sideways > 0.0:
            # PLATEAU WALK: the reference's phase-1 acceptance is
            # event-LOCAL (eventAffectedHcv, Solution.cpp:519-527), so
            # it takes globally-neutral moves and drifts across hcv
            # plateaus; strict global-improvement acceptance gets stuck
            # there (measured: hcv stalls at ~3 pure correlation
            # clashes on comp05s). Equivalent capability here: among the
            # candidates achieving the row-minimum penalty, pick one at
            # RANDOM (the min and the tie test stay in exact integer
            # arithmetic — float noise added to the penalty itself would
            # merge adjacent integers at the 1e6 infeasible offset,
            # float32 ulp there is 0.0625), and accept an equal-penalty
            # best with probability `sideways` per individual per step.
            noise = jax.random.uniform(
                jax.random.fold_in(k_tie, pos), new_pen.shape)
            row_min = new_pen.min(axis=1, keepdims=True)
            best = jnp.argmax(
                jnp.where(new_pen == row_min, noise, -1.0), axis=1)
            best_pen = new_pen[ar, best]
            allow = jax.random.bernoulli(
                jax.random.fold_in(k_side, pos), sideways, (P,))
            strict = best_pen < st.pen
            better = strict | (allow & (best_pen == st.pen))
        else:
            best = jnp.argmin(new_pen, axis=1)             # (P,)
            best_pen = new_pen[ar, best]
            better = strict = best_pen < st.pen

        def apply_or_keep(b, s, r, att, occ, e3, ns3, nr3):
            s2, r2, att2, occ2 = _apply_move(pa, (s, r, att, occ),
                                             e3, ns3, nr3)
            return (jnp.where(b, s2, s), jnp.where(b, r2, r),
                    jnp.where(b, att2, att), jnp.where(b, occ2, occ))

        s2, r2, att2, occ2 = jax.vmap(apply_or_keep)(
            better, st.slots, st.rooms, st.att, st.occ,
            cand_evs[ar, best], cand_ns[ar, best], cand_nr[ar, best])

        st = LSState(
            slots=s2, rooms=r2, att=att2, occ=occ2,
            pen=jnp.where(better, best_pen, st.pen),
            hcv=jnp.where(better, new_hcv[ar, best], st.hcv),
            scv=jnp.where(better, new_scv[ar, best], st.scv))
        # `improved` counts only STRICT improvements: sideways accepts
        # must not keep the convergence loop alive forever
        return st, strict.any()

    state, accepted = lax.scan(step, state, jnp.arange(n_steps))
    return state, accepted.any()


def sweep_local_search(pa, key, slots, rooms_arr, n_sweeps: int,
                       swap_block: int = 8, converge: bool = False,
                       block_events: int = 1, sideways: float = 0.0):
    """Run up to `n_sweeps` full sweep passes over a (P, E) population.

    Candidate budget per pass per individual: E * (T + swap_block)
    delta evaluations — the full Move1 neighborhood plus a rotating
    Move2 block, vs the reference's identical per-pass Move1 coverage
    (Solution.cpp:508-534) and full Move2 coverage (535-561).

    converge=True runs passes under a bounded `lax.while_loop` that
    exits early once a whole pass accepts no move anywhere in the
    population — the reference's run-to-local-optimum stopping rule
    (its pass counter resets on every improvement and the search ends
    after one improving-free pass, Solution.cpp:524, 653), with
    `n_sweeps` as the hard pass bound standing in for maxSteps.
    """
    state = init_state(pa, slots, rooms_arr)

    # Both modes draw pass i's shuffle key as fold_in(key, i), so a
    # converge=True run and a fixed-pass run with the same key follow
    # IDENTICAL trajectories for their shared prefix of passes — the
    # converged result is then provably <= any fixed-budget result.
    if converge:
        def cond(carry):
            _, i, improved = carry
            return (i < n_sweeps) & improved

        def body(carry):
            st, i, _ = carry
            st, improved = sweep_pass(pa, jax.random.fold_in(key, i), st,
                                      swap_block, block_events, sideways)
            return st, i + 1, improved

        state, _, _ = lax.while_loop(
            cond, body, (state, jnp.int32(0), jnp.bool_(True)))
    else:
        def one(st, i):
            st, _ = sweep_pass(pa, jax.random.fold_in(key, i), st,
                               swap_block, block_events, sideways)
            return st, None

        state, _ = lax.scan(one, state, jnp.arange(n_sweeps))
    return state.slots, state.rooms


@functools.partial(jax.jit,
                   static_argnames=("n_sweeps", "swap_block", "converge",
                                    "block_events", "sideways"))
def jit_sweep_local_search(pa, key, slots, rooms_arr, n_sweeps: int,
                           swap_block: int = 8, converge: bool = False,
                           block_events: int = 1, sideways: float = 0.0):
    return sweep_local_search(pa, key, slots, rooms_arr, n_sweeps,
                              swap_block, converge, block_events, sideways)
