"""Systematic batched sweep local search — the fixed-shape analogue of the
reference's exhaustive first-improvement sweeps.

The reference's `Solution::localSearch` (Solution.cpp:471-769) walks events
in shuffled order and, for each, tries ALL 45 target slots (Move1,
Solution.cpp:508-534) and all swap partners (Move2, 535-561), accepting the
first improving candidate and resetting its pass counter — effectively
running to a local optimum. The round-1 K-random-candidate search
(ops/local_search.py) samples a far sparser neighborhood; this module
closes that power gap with fixed shapes:

  one PASS = `lax.scan` over event positions (shuffled per individual per
  pass, Solution.cpp:476-484). At each position, for every individual in
  the population simultaneously:
    - Move1: delta-evaluate relocating the event to ALL T slots at once
      (each target also re-rooms the event greedily in its new slot);
    - Move2: delta-evaluate swapping with a block of `swap_block` partner
      events (the next B events in the permutation, so successive passes
      rotate coverage across all partners);
    - accept the BEST strictly improving candidate (best-improvement per
      event vs the reference's first-improvement — a documented
      divergence that only strengthens the per-event step).

Delta costs are neighborhood-local: the Move1 sweep computes all T slot
deltas in O(S*T + E + T*R) per event by expressing the scv change of
adding/removing one attendance as a function of the 4-slot window around
the target (a run-of-3 can only be created through the inserted slot, and
single-day counts shift by one) instead of re-scoring whole days per
candidate. The two phases (hcv repair, then scv polish that never breaks
feasibility) need no explicit gate: acceptance compares the scalar penalty
`scv if feasible else 1e6+hcv` (Solution.cpp:162-170), under whose
ordering any hcv reduction dominates while infeasible and any
feasibility-breaking move is unacceptable once feasible.

Move3 (3-cycles) is off by default in the reference (p3=0, Control.cpp:
115-125); with p3 > 0 the sweep adds 3-cycle candidates over adjacent
Move2-partner pairs in both orientations (Solution.cpp:562-615), so the
full Move1/2/3 surface is swept.

Violation-guided pivot selection (`hot_k`): the reference's sweep skips
events not implicated in any violation (phase 1 skips eventHcv(e)==0,
Solution.cpp:501-505; phase 2 skips eventScv(e)==0, 628-633), so near
feasibility its effective pass is over a handful of hot events. `hot_k`
reproduces that in fixed shapes: score every event's violation
involvement (`event_heat`), sweep only the top-K as pivots. Partners
still span all events.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from timetabling_ga_tpu.obs import prof as obs_prof
from timetabling_ga_tpu.ops import fitness
from timetabling_ga_tpu.ops.delta import (
    LSState, _apply_move, _day_scv, _delta_one, init_state)
from timetabling_ga_tpu.ops.rooms import (
    _W_COST, _W_UNSUIT, _dead_rooms, capacity_rank)


def _neighbor_masks(b):
    """Distance-1/2 left/right neighbor masks of an (S, D, spd) boolean
    attendance tensor (pad 2 empty slots on each side of every day).
    Shared by the Move1 sweep's add-delta and event_heat's run-of-3
    membership so the windowing semantics cannot diverge."""
    S, D, _ = b.shape
    z = jnp.zeros((S, D, 1), jnp.bool_)
    bp = jnp.concatenate([z, z, b, z, z], axis=2)
    return bp[:, :, :-4], bp[:, :, 1:-3], bp[:, :, 3:-1], bp[:, :, 4:]


@obs_prof.scope("tt.sweep")
def _move1_sweep(pa, slots, rooms_arr, att, occ, e, cap_rank):
    """Delta-evaluate Move1(e, t) for EVERY target slot t of one
    individual. Returns (d_hcv (T,), d_scv (T,), new_rooms (T,)).

    The t == current-slot column is a re-rooming candidate (delta 0 when
    the greedy choice is the current room). Semantics per candidate match
    ops/delta.py's `_delta_one` for a single-event relocation exactly.
    """
    T = pa.n_slots
    spd = pa.slots_per_day
    D = pa.n_days
    S = pa.attends.shape[0]
    s_old = slots[e]
    r_old = rooms_arr[e]

    # ---- room-pair clashes + greedy re-rooming for every target slot.
    # `live` is 0 for padded (masked-out) events: they never occupied a
    # cell, so the self-removal is weighted out, and the final deltas
    # are forced to exactly 0 below (a padded event's relocation cannot
    # change any penalty term).
    live = pa.event_mask[e].astype(jnp.int32)
    occ32 = occ.astype(jnp.int32).at[s_old, r_old].add(-live)
    remove_d = -(occ.astype(jnp.int32)[s_old, r_old] - 1)
    suit = pa.possible[e]                                  # (R,)
    # marginal-hcv-cost key — MUST stay in lockstep with rooms._room_key
    unsuit = (~suit).astype(jnp.int32)[None, :]
    key = ((occ32 + unsuit) * _W_COST
           + unsuit * _W_UNSUIT
           + cap_rank[None, :]
           + _dead_rooms(pa)[None, :])                     # (T, R)
    new_rooms = jnp.argmin(key, axis=1).astype(jnp.int32)  # (T,)
    add_d = occ32[jnp.arange(T), new_rooms]
    pair_d = remove_d + add_d

    unsuit_d = ((~pa.possible[e, new_rooms]).astype(jnp.int32)
                - (~pa.possible[e, r_old]).astype(jnp.int32))

    # ---- correlated-pair delta: conflicting events per slot (one
    # segment-sum over E), minus the current slot's count
    conf = pa.conflict[e].at[e].set(0.0)                   # (E,)
    per_slot = jnp.zeros((T,), jnp.float32).at[slots].add(conf)
    corr_d = (per_slot - per_slot[s_old]).astype(jnp.int32)

    d_hcv = pair_d + unsuit_d + corr_d

    # ---- scv: last-slot-of-day term
    sc = pa.student_count[e]
    t_idx = jnp.arange(T)
    last_d = (jnp.where(t_idx % spd == spd - 1, sc, 0)
              - jnp.where(s_old % spd == spd - 1, sc, 0))

    # ---- scv: day terms. Removing e from s_old re-scores one day
    # window; adding e at target t is neighborhood-local on the
    # binarized post-removal attendance b1:
    #   consec: a new run-of-3 through an empty slot j needs two
    #           attended neighbors on one side or both sides;
    #   single: day count 0 -> 1 creates a single, 1 -> 2 removes one.
    col = pa.attends[:, e].astype(jnp.int32)               # (S,)
    att1 = att.astype(jnp.int32).at[:, s_old].add(-col)

    d0 = s_old // spd
    before = lax.dynamic_slice(att.astype(jnp.int32),
                               (0, d0 * spd), (S, spd))
    after = lax.dynamic_slice(att1, (0, d0 * spd), (S, spd))
    rm_d = _day_scv(after > 0) - _day_scv(before > 0)

    b1 = (att1 > 0).reshape(S, D, spd)                     # (S, D, spd)
    l2, l1, r1, r2 = _neighbor_masks(b1)
    free = ~b1
    # COUNT of new runs-of-3 through slot j (0..3), so each pair term
    # must be cast before summing (bool + bool is OR, not count)
    dconsec = free * ((l2 & l1).astype(jnp.int32)
                      + (l1 & r1).astype(jnp.int32)
                      + (r1 & r2).astype(jnp.int32))
    cnt = b1.sum(axis=2, dtype=jnp.int32)                  # (S, D)
    dsingle = free * ((cnt == 0).astype(jnp.int32)
                      - (cnt == 1).astype(jnp.int32))[:, :, None]
    add_per_target = jnp.einsum(
        "s,sdj->dj", col.astype(jnp.float32),
        (dconsec + dsingle).astype(jnp.float32)).reshape(T)

    d_scv = last_d + rm_d + add_per_target.astype(jnp.int32)
    # padded pivot: every term above is already zero EXCEPT the pair
    # replay (whose self-removal assumption does not hold for an event
    # that occupies nothing) — force the whole delta to its true value 0
    return d_hcv * live, d_scv * live, new_rooms


def _distinct_pad(e1, e2, E: int):
    """An event index distinct from e1 and e2 (needs E >= 3)."""
    pad = (e1 + 1) % E
    return jnp.where(pad == e2, (e1 + 2) % E, pad)


@obs_prof.scope("tt.sweep")
def event_heat(pa, slots, rooms_arr, att, occ, hcv):
    """Per-event violation involvement of ONE individual — the tensor
    form of the reference's sweep skip rule (phase 1 examines an event
    only if eventHcv(e) > 0, Solution.cpp:501-505; phase 2 only if
    eventScv(e) > 0, Solution.cpp:628-633). Near feasibility only a
    handful of events are hot, so sweeping the top-K by heat recovers
    the reference's effective O(k)-events pass without data-dependent
    shapes (the full-permutation sweep spends ~E/k of its time
    re-examining clean events — VERDICT round 3, missing #2).

    Returns (E,) float32. While the individual is infeasible (hcv > 0):
    an event's hcv involvement = room-pair clash count at its (slot,
    room) cell + unsuitable-room flag + correlated events sharing its
    slot. Once feasible: its scv involvement = last-slot-of-day cost +
    over attending students, membership in a run-of-3 at its slot +
    single-class-day flag. Heat 0 <=> the reference would skip the
    event. The involvement values are selection weights, not exact
    per-event scv attribution (the sweep's delta evaluation stays
    exact; heat only orders the pivots)."""
    E = pa.n_events
    T = pa.n_slots
    spd = pa.slots_per_day
    D = pa.n_days
    S = pa.attends.shape[0]
    ar = jnp.arange(E)
    occ32 = occ.astype(jnp.int32)

    # ---- hcv involvement (eventHcv semantics, Solution.cpp:173-191)
    pair = occ32[slots, rooms_arr] - 1                      # (E,)
    unsuit = (~pa.possible[ar, rooms_arr]).astype(jnp.int32)
    slot_oh = (slots[:, None] == jnp.arange(T)[None, :]).astype(
        jnp.float32)                                        # (E, T)
    per_slot_conf = pa.conflict @ slot_oh                   # (E, T) MXU
    corr = (per_slot_conf[ar, slots]
            - jnp.diagonal(pa.conflict))  # an event always shares its
    #                                       own slot; drop the diagonal
    hcv_heat = (pair + unsuit).astype(jnp.float32) + corr

    # ---- scv involvement (eventScv semantics, Solution.cpp:248-355)
    sc = pa.student_count.astype(jnp.float32)
    last = jnp.where(slots % spd == spd - 1, sc, 0.0)
    b = (att > 0).reshape(S, D, spd)
    l2, l1, r1, r2 = _neighbor_masks(b)
    in_run = b & ((l2 & l1) | (l1 & r1) | (r1 & r2))
    cnt = b.sum(axis=2, dtype=jnp.int32)
    single = b & (cnt == 1)[:, :, None]
    heat_slot = (in_run.astype(jnp.float32)
                 + single.astype(jnp.float32)).reshape(S, T)
    H = pa.attends.astype(jnp.float32).T @ heat_slot        # (E, T) MXU
    scv_heat = H[ar, slots] + last

    # padded events are permanently cold (heat 0): a hot-K pivot slot
    # spent on one would be pure padding waste
    return jnp.where(hcv > 0, hcv_heat, scv_heat) * pa.event_mask


@obs_prof.scope("tt.sweep")
def sweep_pass(pa, key, state: LSState, swap_block: int = 8,
               block_events: int = 1, sideways: float = 0.0,
               hot_k: int = 0, p3: float = 0.0,
               return_ops: bool = False):
    """One sweep pass (shuffled per individual).

    `block_events` = events examined per scan step. With 1 (default)
    this is the serial sweep: each event's accepted move is visible to
    the next event's deltas — maximum acceptance density per pass. With
    B > 1, B events' full candidate sets are delta-evaluated TOGETHER
    and only the single best improving move among them is applied, so
    the sequential scan depth drops from E to ceil(E/B): ~B x less
    wall-clock per pass (the per-step cost is latency- not flop-bound
    at comp scale) for at most 1/B the accepted moves per pass — a
    throughput/density trade the caller tunes. All delta semantics are
    shared with the B=1 path.

    `hot_k` > 0 switches pivot selection from a full permutation of all
    E events to the top-`hot_k` events by violation involvement (see
    `event_heat` — the reference's phase-1/phase-2 skip rule), with
    sub-integer random noise breaking ties: hot events are visited in
    random order, and when fewer than `hot_k` events are hot the rest
    of the pivots are random cold events (exploration fill). Move2/3
    PARTNERS still come from a full permutation, so hot x cold moves
    stay reachable. Scan depth drops from ceil(E/B) to ceil(K/B).

    `p3` > 0.0 adds 3-cycle candidates (the reference's Move3 sweep,
    Solution.cpp:562-615, both cycle orientations) built from adjacent
    Move2-partner pairs. The reference gates each pivot's Move3 block
    on ran01 < p3 (Solution.cpp:562); here any p3 > 0 includes the
    3-cycle block in every step — a coverage superset with identical
    move semantics, chosen over per-step Bernoulli gating to keep the
    compiled step static.

    Returns (state, improved) where `improved` is a scalar bool: did ANY
    individual accept ANY move this pass. A False means the entire
    population is at a local optimum of the examined neighborhood, the
    same fixed-point condition that ends the reference's localSearch (a
    full improving-free pass over all events, Solution.cpp:497-618
    counter semantics).

    `return_ops=True` (the tt-obs quality observatory) additionally
    returns a (3,) int32 vector of ACCEPTED moves by type — Move1 /
    Move2 / Move3, classified by which candidate block the accepted
    index fell in — summed over the pass's steps and individuals. The
    counts are derived from values the step already computes (no new
    RNG draws, no extra candidate evaluations), so the trajectory is
    bit-identical with the flag on or off; tests pin it."""
    cap_rank = capacity_rank(pa)
    P, E = state.slots.shape
    T = pa.n_slots
    assert E >= 3, "padded 3-relocation form needs E >= 3"
    # partner offsets must stay within the permutation; clamp for tiny E
    swap_block = min(max(swap_block, 0), E - 1)
    B = min(max(block_events, 1), E)
    use_hot = 0 < hot_k < E
    K = hot_k if use_hot else E
    n_steps = (K + B - 1) // B

    k_perm, k_tie, k_side, k_hot = jax.random.split(key, 4)
    # Sort-free pseudo-shuffle: per-individual affine permutation
    # j -> (a*j + b) mod E with a drawn from E's coprime residues (a
    # trace-time constant table) and b uniform. NOT jax.random.
    # permutation (or any argsort of random bits): a sort here sits
    # inside the converge while_loop, whose trip count is legitimately
    # per-island varying — and XLA's SPMD partitioner resolves the
    # shuffle's sort under shard_map by replicating its operand with
    # masked cross-device all-reduces, which (a) silently merge every
    # island's shuffle into one stream and (b) DEADLOCK when islands'
    # trip counts diverge (one device exits the loop, the other waits
    # at the rendezvous forever — the round-1 CPU-backend hang;
    # tt-analyze TT302). Elementwise arithmetic partitions locally, so
    # nothing here can be turned into a collective. Affine perms span
    # only E*phi(E) of E! orderings, but pivot-order decorrelation
    # across passes is all the sweep needs (the reference uses ONE
    # fixed order, Solution.cpp:508).
    coprimes = jnp.asarray(
        [a for a in range(1, max(E, 2)) if math.gcd(a, E) == 1],
        dtype=jnp.int32)
    k_pa, k_pb = jax.random.split(k_perm)
    a = coprimes[jax.random.randint(k_pa, (P, 1), 0, coprimes.shape[0])]
    b = jax.random.randint(k_pb, (P, 1), 0, E)
    perms = ((a * jnp.arange(E, dtype=jnp.int32)[None, :] + b)
             % E).astype(jnp.int32)

    if use_hot:
        heat = jax.vmap(lambda s, r, a, o, h: event_heat(
            pa, s, r, a, o, h))(state.slots, state.rooms, state.att,
                                state.occ, state.hcv)       # (P, E)
        # noise < 1: any event with integer heat >= 1 outranks every
        # zero-heat event; ties (and the cold fill) order randomly
        noise = jax.random.uniform(k_hot, heat.shape, maxval=0.9)
        hot_idx = lax.top_k(heat + noise, K)[1].astype(jnp.int32)

    # Pivot blocks and Move2/3 partner windows are taken with scalar-
    # start dynamic slices on wrap-padded copies, NOT index-array
    # gathers (`pivots[:, idx]` with a traced idx): under shard_map,
    # XLA's SPMD partitioner resolves a traced-index gather by
    # REPLICATING the gathered operand across the mesh — masked
    # all-reduces inside the per-island program that (a) silently merge
    # every island's shuffle into one replicated permutation and (b)
    # deadlock the CPU backend's collective rendezvous (tt-analyze
    # TT302). Scalar-start dynamic slices partition cleanly; the padded
    # copies reproduce the old modular wrap exactly.
    pivots = hot_idx if use_hot else perms
    # tile (period K) rather than a single concat: B may exceed 2*K in
    # hot mode (--ls-block-events > 2*--ls-hot-k), where one wrap of
    # padding is too narrow for the B-wide slice
    reps_p = -(-(n_steps * B) // K)                        # static ceil
    pivots_pad = jnp.tile(pivots, (1, reps_p))[:, :n_steps * B]
    if swap_block > 0:
        w_len = B - 1 + swap_block
        reps = -(-(n_steps * B + swap_block) // E) + 1     # static ceil
        perms_tiled = jnp.tile(perms, (1, reps))

    def step(st, pos):
        # block of B pivot positions (wraps at the tail when B ∤ K;
        # duplicate candidates are harmless — only one move is applied)
        e_blk = lax.dynamic_slice_in_dim(pivots_pad, pos * B, B, axis=1)

        def per_e(e_i, s, r, att, occ):
            # Move1: all T targets
            dh1, ds1, rooms1 = _move1_sweep(pa, s, r, att, occ, e_i,
                                            cap_rank)
            # anchored-objective delta per target slot: the pad events
            # below keep their slots (contribute 0), and a padded pivot
            # carries anchor_w == 0, so no `live` gating is needed
            anc_e = pa.anchor_slots[e_i]
            da1 = pa.anchor_w[e_i] * (
                (jnp.arange(T, dtype=jnp.int32) != anc_e).astype(jnp.int32)
                - (s[e_i] != anc_e).astype(jnp.int32))
            # pad events: distinct from e (and each other) so the padded
            # 3-relocation form's correlation terms stay exact
            p1 = _distinct_pad(e_i, e_i, E)
            p2 = _distinct_pad(e_i, p1, E)
            evs1 = jnp.broadcast_to(jnp.stack([e_i, p1, p2]), (T, 3))
            ns1 = jnp.stack([jnp.arange(T, dtype=jnp.int32),
                             jnp.broadcast_to(s[p1], (T,)),
                             jnp.broadcast_to(s[p2], (T,))], axis=1)
            nr1 = jnp.stack([rooms1,
                             jnp.broadcast_to(r[p1], (T,)),
                             jnp.broadcast_to(r[p2], (T,))], axis=1)
            return dh1, ds1, da1, evs1, ns1, nr1

        def per_ind(es, s, r, att, occ):
            # (B, T), (B, T, 3), ... -> flatten candidates across block
            dh1, ds1, da1, evs1, ns1, nr1 = jax.vmap(
                lambda e_i: per_e(e_i, s, r, att, occ))(es)
            return (dh1.reshape(-1), ds1.reshape(-1), da1.reshape(-1),
                    evs1.reshape(-1, 3), ns1.reshape(-1, 3),
                    nr1.reshape(-1, 3))

        # Move1 sweep for every individual
        dh1, ds1, da1, evs1, ns1, nr1 = jax.vmap(per_ind)(
            e_blk, st.slots, st.rooms, st.att, st.occ)

        cand_dh, cand_ds, cand_da = dh1, ds1, da1          # (P, B*T)
        cand_evs, cand_ns, cand_nr = evs1, ns1, nr1        # (P, B*T, 3)

        if swap_block > 0:
            # Move2 partners per block event j: the next swap_block
            # positions after its own (rotates coverage across passes,
            # as in the B=1 form). In hot mode the pivot does not come
            # from the permutation, so a partner CAN collide with it —
            # those candidates are masked unacceptable (a self-swap's
            # duplicate event indices would corrupt _apply_move's
            # occupancy bookkeeping if ever accepted).
            # partner window [pos*B+1, pos*B+B-1+SB] of the wrapped
            # permutation: one scalar-start dynamic slice, then static
            # column slices — value-identical to the old modular gather
            # offs = (pos*B + j + 1 + k) % E (see pivot-block comment)
            window = lax.dynamic_slice_in_dim(
                perms_tiled, pos * B + 1, w_len, axis=1)    # (P, w_len)
            partners = jnp.stack(
                [lax.slice_in_dim(window, j, j + swap_block, axis=1)
                 for j in range(B)], axis=1)                # (P, B, SB)
            BIG = jnp.int32(1 << 20)

            def swap_one(e_i, q, s, r, att, occ):
                pad = _distinct_pad(e_i, q, E)
                evs = jnp.stack([e_i, q, pad])
                ns = jnp.stack([s[q], s[e_i], s[pad]])
                active = jnp.array([True, True, False])
                dh, ds, nr = _delta_one(pa, s, r, att, occ, evs, ns,
                                        active, cap_rank)
                da = fitness.anchor_delta(pa, s, evs, ns)
                dh = jnp.where(q == e_i, BIG, dh)
                return dh, ds, da, evs, ns, nr

            def swaps_per_ind(es, qss, s, r, att, occ):
                dh, ds, da, evs, ns, nr = jax.vmap(jax.vmap(
                    lambda e_i, q: swap_one(e_i, q, s, r, att, occ)))(
                        jnp.broadcast_to(es[:, None], qss.shape), qss)
                return (dh.reshape(-1), ds.reshape(-1), da.reshape(-1),
                        evs.reshape(-1, 3), ns.reshape(-1, 3),
                        nr.reshape(-1, 3))

            dh2, ds2, da2, evs2, ns2, nr2 = jax.vmap(swaps_per_ind)(
                e_blk, partners, st.slots, st.rooms, st.att, st.occ)
            cand_dh = jnp.concatenate([cand_dh, dh2], axis=1)
            cand_ds = jnp.concatenate([cand_ds, ds2], axis=1)
            cand_da = jnp.concatenate([cand_da, da2], axis=1)
            cand_evs = jnp.concatenate([cand_evs, evs2], axis=1)
            cand_ns = jnp.concatenate([cand_ns, ns2], axis=1)
            cand_nr = jnp.concatenate([cand_nr, nr2], axis=1)

            if p3 > 0.0 and swap_block >= 2:
                # Move3: 3-cycles over (pivot, q_j, q_j+1) adjacent
                # partner pairs, both orientations (Solution.cpp:
                # 562-615 tries t1->t2->t3->t1 and the reverse). All
                # three relocations are active; _delta_one's padded
                # 3-relocation evaluates them exactly.
                orients = jnp.array([True, False])

                def cyc_one(e_i, q1, q2, orient, s, r, att, occ):
                    evs = jnp.stack([e_i, q1, q2])
                    ns = jnp.where(
                        orient,
                        jnp.stack([s[q1], s[q2], s[e_i]]),
                        jnp.stack([s[q2], s[e_i], s[q1]]))
                    active = jnp.array([True, True, True])
                    dh, ds, nr = _delta_one(pa, s, r, att, occ, evs,
                                            ns, active, cap_rank)
                    da = fitness.anchor_delta(pa, s, evs, ns)
                    invalid = (q1 == e_i) | (q2 == e_i) | (q1 == q2)
                    dh = jnp.where(invalid, BIG, dh)
                    return dh, ds, da, evs, ns, nr

                def cycs_per_ind(es, qss, s, r, att, occ):
                    # (B, SB-1) adjacent pairs x 2 orientations
                    q1 = qss[:, :-1]                        # (B, SB-1)
                    q2 = qss[:, 1:]
                    eb = jnp.broadcast_to(es[:, None], q1.shape)

                    def for_orient(o):
                        return jax.vmap(jax.vmap(
                            lambda e_i, a, b2: cyc_one(
                                e_i, a, b2, o, s, r, att, occ)))(
                                    eb, q1, q2)

                    dh, ds, da, evs, ns, nr = jax.vmap(for_orient)(orients)
                    return (dh.reshape(-1), ds.reshape(-1), da.reshape(-1),
                            evs.reshape(-1, 3), ns.reshape(-1, 3),
                            nr.reshape(-1, 3))

                dh3, ds3, da3, evs3, ns3, nr3 = jax.vmap(cycs_per_ind)(
                    e_blk, partners, st.slots, st.rooms, st.att, st.occ)
                cand_dh = jnp.concatenate([cand_dh, dh3], axis=1)
                cand_ds = jnp.concatenate([cand_ds, ds3], axis=1)
                cand_da = jnp.concatenate([cand_da, da3], axis=1)
                cand_evs = jnp.concatenate([cand_evs, evs3], axis=1)
                cand_ns = jnp.concatenate([cand_ns, ns3], axis=1)
                cand_nr = jnp.concatenate([cand_nr, nr3], axis=1)

        # Anchored acceptance: recover the maintained states' anchor
        # residual exactly (init_state's pen rides batch_penalty, which
        # includes the anchor term) and carry each candidate's anchor
        # delta, so the sweep optimizes the SAME anchored objective as
        # selection (fitness.compute_penalty). On unanchored instances
        # both terms are exactly 0. The scv tie-break below stays a pure
        # constraint count — the anchor only orders the primary penalty.
        anc = st.pen - fitness.base_penalty(st.hcv, st.scv)  # (P,)
        new_hcv = st.hcv[:, None] + cand_dh                # (P, C)
        new_scv = st.scv[:, None] + cand_ds
        new_pen = (fitness.base_penalty(new_hcv, new_scv)
                   + anc[:, None] + cand_da)
        ar = jnp.arange(P)
        # Candidate choice and acceptance use the LEXICOGRAPHIC
        # (penalty, scv) order — the reported evaluation's total order
        # (hcv*1e6+scv, ga.cpp:191). Among row-minimum-penalty
        # candidates the one with minimum scv is picked, and a move
        # that holds penalty while strictly reducing scv counts as a
        # STRICT improvement: when hcv is pinned at an infeasibility
        # floor (race instance `medium` never goes feasible for either
        # solver) penalty-only acceptance lets scv drift while the
        # reported metric counts every point of it. All min/tie tests
        # stay in exact integer arithmetic.
        row_min = new_pen.min(axis=1, keepdims=True)
        pen_tie = new_pen == row_min
        scv_tied = jnp.where(pen_tie, new_scv, jnp.int32(1 << 30))
        scv_min = scv_tied.min(axis=1, keepdims=True)
        lex_tie = scv_tied == scv_min
        if sideways > 0.0:
            # PLATEAU WALK: the reference's phase-1 acceptance is
            # event-LOCAL (eventAffectedHcv, Solution.cpp:519-527), so
            # it takes globally-neutral moves and drifts across hcv
            # plateaus; strict global-improvement acceptance gets stuck
            # there (measured: hcv stalls at ~3 pure correlation
            # clashes on comp05s). The sideways draw therefore picks a
            # MODE per individual per step: with probability `sideways`
            # a DRIFT step (a random penalty-tied candidate, any scv,
            # accepted at equal penalty — the original walk, whose scv
            # freedom is what moves the individual across the plateau),
            # otherwise a DESCENT step (the min-scv penalty-tied
            # candidate, accepted only on lexicographic improvement).
            # Descent-only acceptance halts at scv-local minima of the
            # plateau and can regress comp05s to never-feasible
            # (round-4 review); drift-only lets scv wander while the
            # reported metric counts it (the `medium` regime). The mix
            # keeps the escape rate and adds the descent pressure.
            noise = jax.random.uniform(
                jax.random.fold_in(k_tie, pos), new_pen.shape)
            drift_best = jnp.argmax(
                jnp.where(pen_tie, noise, -1.0), axis=1)
            lex_best = jnp.argmax(
                jnp.where(lex_tie, noise, -1.0), axis=1)
            allow = jax.random.bernoulli(
                jax.random.fold_in(k_side, pos), sideways, (P,))
            best = jnp.where(allow, drift_best, lex_best)
            best_pen = new_pen[ar, best]
            best_scv = new_scv[ar, best]
            strict = (best_pen < st.pen) | ((best_pen == st.pen)
                                            & (best_scv < st.scv))
            better = strict | (allow & (best_pen == st.pen))
        else:
            best = jnp.argmax(lex_tie, axis=1)             # (P,)
            best_pen = new_pen[ar, best]
            best_scv = new_scv[ar, best]
            better = strict = (
                (best_pen < st.pen)
                | ((best_pen == st.pen) & (best_scv < st.scv)))

        def apply_or_keep(b, s, r, att, occ, e3, ns3, nr3):
            s2, r2, att2, occ2 = _apply_move(pa, (s, r, att, occ),
                                             e3, ns3, nr3)
            return (jnp.where(b, s2, s), jnp.where(b, r2, r),
                    jnp.where(b, att2, att), jnp.where(b, occ2, occ))

        s2, r2, att2, occ2 = jax.vmap(apply_or_keep)(
            better, st.slots, st.rooms, st.att, st.occ,
            cand_evs[ar, best], cand_ns[ar, best], cand_nr[ar, best])

        st = LSState(
            slots=s2, rooms=r2, att=att2, occ=occ2,
            pen=jnp.where(better, best_pen, st.pen),
            hcv=jnp.where(better, new_hcv[ar, best], st.hcv),
            scv=jnp.where(better, new_scv[ar, best], st.scv))
        if return_ops:
            # accepted-move counts by candidate block (the concat order
            # above is Move1 | Move2 | Move3, with static block sizes):
            # every ACCEPT counts, sideways drift included — acceptance
            # is what the efficacy question is about
            n1 = B * T
            n2 = B * swap_block if swap_block > 0 else 0
            is1 = best < n1
            is2 = (best >= n1) & (best < n1 + n2)
            is3 = best >= n1 + n2
            ops = jnp.stack([
                jnp.sum((better & is1).astype(jnp.int32)),
                jnp.sum((better & is2).astype(jnp.int32)),
                jnp.sum((better & is3).astype(jnp.int32))])
        else:
            ops = jnp.zeros((3,), jnp.int32)
        # `improved` counts only STRICT improvements: sideways accepts
        # must not keep the convergence loop alive forever
        return st, (strict.any(), ops)

    state, (accepted, ops_steps) = lax.scan(step, state,
                                            jnp.arange(n_steps))
    if return_ops:
        return state, accepted.any(), jnp.sum(ops_steps, axis=0)
    return state, accepted.any()


@obs_prof.scope("tt.sweep")
def sweep_local_search(pa, key, slots, rooms_arr, n_sweeps: int,
                       swap_block: int = 8, converge: bool = False,
                       block_events: int = 1, sideways: float = 0.0,
                       hot_k: int = 0, p3: float = 0.0,
                       return_passes: bool = False,
                       return_ops: bool = False):
    """Run up to `n_sweeps` sweep passes over a (P, E) population.

    Candidate budget per pass per individual: K * (T + swap_block
    [+ 2*(swap_block-1) when p3 > 0]) delta evaluations, where K = E
    (full sweep) or `hot_k` (violation-guided top-K pivots) — vs the
    reference's per-pass Move1 coverage (Solution.cpp:508-534), Move2
    coverage (535-561) and Move3 coverage (562-615) over its non-skipped
    events.

    converge=True runs passes under a bounded `lax.while_loop` that
    exits early once a whole pass accepts no move anywhere in the
    population — the reference's run-to-local-optimum stopping rule
    (its pass counter resets on every improvement and the search ends
    after one improving-free pass, Solution.cpp:524, 653), with
    `n_sweeps` as the hard pass bound standing in for maxSteps.

    return_passes=True additionally returns the number of passes
    actually EXECUTED (the converge loop's exit count; `n_sweeps` in
    fixed-pass mode) as an int32 scalar — telemetry for the `--trace-
    mode stats` polish path (tt-obs): pass counts are the on-device
    convergence signal the host otherwise cannot see without fetching
    per-individual state. The count is already the loop carry, so
    shipping it costs nothing and perturbs no trajectory.

    return_ops=True (tt-obs quality observatory) appends a (3,) int32
    vector of accepted Move1/Move2/Move3 counts summed over every
    executed pass (sweep_pass return_ops — no new RNG, trajectory
    untouched). Return order: slots, rooms[, passes][, ops].
    """
    state = init_state(pa, slots, rooms_arr)

    # Both modes draw pass i's shuffle key as fold_in(key, i), so a
    # converge=True run and a fixed-pass run with the same key follow
    # IDENTICAL trajectories for their shared prefix of passes — the
    # converged result is then provably <= any fixed-budget result.
    ops = jnp.zeros((3,), jnp.int32)
    if converge:
        def cond(carry):
            _, i, improved, _ops = carry
            return (i < n_sweeps) & improved

        def body(carry):
            st, i, _, op = carry
            if return_ops:
                st, improved, o = sweep_pass(
                    pa, jax.random.fold_in(key, i), st, swap_block,
                    block_events, sideways, hot_k, p3, return_ops=True)
                op = op + o
            else:
                st, improved = sweep_pass(
                    pa, jax.random.fold_in(key, i), st, swap_block,
                    block_events, sideways, hot_k, p3)
            return st, i + 1, improved, op

        state, passes, _, ops = lax.while_loop(
            cond, body, (state, jnp.int32(0), jnp.bool_(True), ops))
    else:
        def one(carry, i):
            st, op = carry
            if return_ops:
                st, _, o = sweep_pass(pa, jax.random.fold_in(key, i), st,
                                      swap_block, block_events, sideways,
                                      hot_k, p3, return_ops=True)
                op = op + o
            else:
                st, _ = sweep_pass(pa, jax.random.fold_in(key, i), st,
                                   swap_block, block_events, sideways,
                                   hot_k, p3)
            return (st, op), None

        (state, ops), _ = lax.scan(one, (state, ops),
                                   jnp.arange(n_sweeps))
        passes = jnp.int32(n_sweeps)
    outs = [state.slots, state.rooms]
    if return_passes:
        outs.append(passes)
    if return_ops:
        outs.append(ops)
    return tuple(outs)


@functools.partial(jax.jit,
                   static_argnames=("n_sweeps", "swap_block", "converge",
                                    "block_events", "sideways", "hot_k",
                                    "p3", "return_passes", "return_ops"))
def jit_sweep_local_search(pa, key, slots, rooms_arr, n_sweeps: int,
                           swap_block: int = 8, converge: bool = False,
                           block_events: int = 1, sideways: float = 0.0,
                           hot_k: int = 0, p3: float = 0.0,
                           return_passes: bool = False,
                           return_ops: bool = False):
    return sweep_local_search(pa, key, slots, rooms_arr, n_sweeps,
                              swap_block, converge, block_events, sideways,
                              hot_k, p3, return_passes, return_ops)
