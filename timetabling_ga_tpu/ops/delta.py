"""Delta (incremental) candidate evaluation for the local search.

TPU-native equivalent of the reference's delta evaluators (SURVEY C6:
eventHcv / eventAffectedHcv / affectedRoomInTimeslotHcv / eventScv /
singleClassesScv, Solution.cpp:173-355), which make its local search
O(affected) instead of O(E^2) per candidate. Here the same idea is done
with maintained tensors instead of pointer-chased indexes:

  att (S, T) int16   per-(student, slot) attended-event counts
  occ (T, R) int16   per-(slot, room) occupancy counts

A candidate move relocates at most 3 events (Move1/2/3 all reduce to a
padded 3-relocation; inactive pad slots are exact no-ops), so its effect
on the penalty decomposes into:

  room-pair clashes : replay remove/add on <= 6 occ cells; each +-1 op's
                      pair delta is the current cell count (telescopes to
                      C(n_final,2)-C(n_init,2) exactly, any order)
  correlation pairs : 3 conflict-row dot products over slot equalities
                      (O(E) each) + a 3x3 within-move correction
  unsuitable room   : O(1) gathers
  scv               : recompute ONLY the <= 6 affected days' windows
                      (O(S * slots_per_day) each) from att patches,
                      deduplicating repeated days

Per-candidate cost ~O(E + S*spd) versus the full kernel's
O(E^2 + S*E); at comp scale that is ~70x less arithmetic. The batched
local search evaluates all P*K candidates' deltas in one fused dispatch.

Exactness: `batch_local_search_delta` reproduces the full-re-evaluation
search (ops/local_search.py) bit-for-bit under the same keys — same
candidates, same greedy room choices, same acceptance — which is what
tests/test_delta.py asserts.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from timetabling_ga_tpu.obs import prof as obs_prof
from timetabling_ga_tpu.ops import fitness
from timetabling_ga_tpu.ops.rooms import capacity_rank, choose_room, occupancy


class LSState(NamedTuple):
    """Per-population local-search state (all leading-axis P)."""

    slots: jnp.ndarray   # (P, E) int32
    rooms: jnp.ndarray   # (P, E) int32
    att: jnp.ndarray     # (P, S, T) int16  attendance counts
    occ: jnp.ndarray     # (P, T, R) int16  occupancy counts
    pen: jnp.ndarray     # (P,) int32
    hcv: jnp.ndarray     # (P,) int32
    scv: jnp.ndarray     # (P,) int32


@obs_prof.scope("tt.delta")
def init_state(pa, slots, rooms_arr) -> LSState:
    """Build maintained tensors + baseline fitness for a population."""
    pen, hcv, scv = fitness.batch_penalty(pa, slots, rooms_arr)
    att = jax.vmap(lambda s: fitness.attendance_matrix(pa, s))(
        slots).astype(jnp.int16)
    occ = jax.vmap(lambda s, r: occupancy(pa, s, r))(
        slots, rooms_arr).astype(jnp.int16)
    return LSState(slots=slots, rooms=rooms_arr, att=att, occ=occ,
                   pen=pen, hcv=hcv, scv=scv)


# Candidate sampling is shared with the applying path: moves.sample_move
# is the single source of truth, so the delta and full searches can never
# evaluate different candidates for the same key.
from timetabling_ga_tpu.ops.moves import sample_move as _gen_candidate  # noqa: E402,E501


def _day_scv(patch_bool):
    """scv contribution of one day's (S, spd) boolean attendance:
    runs-of->=3 (+1 per extra class) and single-class days (+1)."""
    b = patch_bool
    consec = jnp.sum((b[:, 2:] & b[:, 1:-1] & b[:, :-2]).astype(jnp.int32))
    single = jnp.sum((jnp.sum(b, axis=1) == 1).astype(jnp.int32))
    return consec + single


@obs_prof.scope("tt.delta")
def _delta_one(pa, slots, rooms_arr, att, occ, evs, new_slots, active,
               cap_rank):
    """Delta evaluation of one padded 3-relocation candidate on one
    individual. Returns (d_hcv, d_scv, new_rooms (3,))."""
    E = slots.shape[0]
    spd = pa.slots_per_day
    S = pa.attends.shape[0]

    old_slots = slots[evs]                              # (3,)
    old_rooms = rooms_arr[evs]                          # (3,)

    # ---- room-pair clashes + greedy re-rooming, replayed on occ.
    # Only ACTIVE events are removed/re-added: the greedy room choice
    # must see exactly the occupancy random_move's Move1/2/3 see
    # (ops/moves.py removes only the moved events before choosing).
    # Padded (masked-out) events never occupied a cell, so their weight
    # in the replay is 0 — they relocate freely with an exact pair delta
    # of 0 and cannot perturb a live partner's delta.
    live = pa.event_mask[evs].astype(jnp.int32)         # (3,) 0/1
    occ32 = occ.astype(jnp.int32)
    pair_d = jnp.int32(0)
    for m in range(3):
        act = active[m].astype(jnp.int32) * live[m]
        cell = occ32[old_slots[m], old_rooms[m]]
        pair_d = pair_d - act * (cell - 1)
        occ32 = occ32.at[old_slots[m], old_rooms[m]].add(-act)
    new_rooms = []
    for m in range(3):
        act = active[m].astype(jnp.int32) * live[m]
        row = occ32[new_slots[m]]
        r_choice = choose_room(pa, row, evs[m], cap_rank)
        r_new = jnp.where(active[m], r_choice, old_rooms[m])
        pair_d = pair_d + act * occ32[new_slots[m], r_new]
        occ32 = occ32.at[new_slots[m], r_new].add(act)
        new_rooms.append(r_new)
    new_rooms = jnp.stack(new_rooms)

    # ---- unsuitable-room delta
    unsuit_d = jnp.int32(0)
    for m in range(3):
        unsuit_d = (unsuit_d
                    + (~pa.possible[evs[m], new_rooms[m]]).astype(jnp.int32)
                    - (~pa.possible[evs[m], old_rooms[m]]).astype(jnp.int32))

    # ---- correlation-pair delta.
    # moved x unmoved: conflict-row dots over slot equalities, minus the
    # moved-partner columns (their rows in `slots` are stale).
    corr_d = jnp.float32(0)
    in_m = jnp.zeros((E,), jnp.float32).at[evs].set(1.0)
    for m in range(3):
        row = pa.conflict[evs[m]] * (1.0 - in_m)        # exclude moved
        eq_new = (slots == new_slots[m]).astype(jnp.float32)
        eq_old = (slots == old_slots[m]).astype(jnp.float32)
        corr_d = corr_d + jnp.dot(row, eq_new - eq_old)
    # within-moved pairs
    for m in range(3):
        for mm in range(m + 1, 3):
            c = pa.conflict[evs[m], evs[mm]]
            corr_d = corr_d + c * (
                (new_slots[m] == new_slots[mm]).astype(jnp.float32)
                - (old_slots[m] == old_slots[mm]).astype(jnp.float32))

    d_hcv = pair_d + unsuit_d + corr_d.astype(jnp.int32)

    # ---- scv: last-slot term
    last_d = jnp.int32(0)
    for m in range(3):
        sc = pa.student_count[evs[m]]
        last_d = (last_d
                  + jnp.where(new_slots[m] % spd == spd - 1, sc, 0)
                  - jnp.where(old_slots[m] % spd == spd - 1, sc, 0))

    # ---- scv: affected days (<= 6, deduplicated)
    days = jnp.concatenate([old_slots // spd, new_slots // spd])   # (6,)

    def day_delta(i, acc):
        d = days[i]
        unique = jnp.all(jnp.where(jnp.arange(6) < i, days != d, True))
        before = lax.dynamic_slice(att, (0, d * spd), (S, spd))
        patch = before.astype(jnp.int32)
        for m in range(3):
            col = pa.attends[:, evs[m]].astype(jnp.int32)           # (S,)
            oh_old = (jnp.arange(spd) == old_slots[m] % spd) & (
                old_slots[m] // spd == d)
            oh_new = (jnp.arange(spd) == new_slots[m] % spd) & (
                new_slots[m] // spd == d)
            patch = patch + col[:, None] * (
                oh_new.astype(jnp.int32) - oh_old.astype(jnp.int32)
            )[None, :]
        dlt = _day_scv(patch > 0) - _day_scv(before > 0)
        return acc + jnp.where(unique, dlt, 0)

    scv_days_d = lax.fori_loop(0, 6, day_delta, jnp.int32(0))
    d_scv = last_d + scv_days_d
    return d_hcv, d_scv, new_rooms


@obs_prof.scope("tt.delta")
def _apply_move(pa, state_i, evs, new_slots, new_rooms):
    """Commit an accepted candidate to one individual's maintained state.
    Inactive pad entries (new == old) cancel exactly in every update.
    Padded (masked-out) events carry occupancy weight 0 — their attends
    column is already all-zero — so the maintained grids stay exactly
    the mask-aware truth `init_state` computes."""
    slots, rooms_arr, att, occ = state_i
    old_slots = slots[evs]
    old_rooms = rooms_arr[evs]
    live = pa.event_mask[evs].astype(jnp.int32)         # (3,) 0/1
    att32 = att.astype(jnp.int32)
    occ32 = occ.astype(jnp.int32)
    for m in range(3):
        col = pa.attends[:, evs[m]].astype(jnp.int32)
        att32 = att32.at[:, old_slots[m]].add(-col)
        att32 = att32.at[:, new_slots[m]].add(col)
        occ32 = occ32.at[old_slots[m], old_rooms[m]].add(-live[m])
        occ32 = occ32.at[new_slots[m], new_rooms[m]].add(live[m])
    slots = slots.at[evs].set(new_slots)
    rooms_arr = rooms_arr.at[evs].set(new_rooms)
    return slots, rooms_arr, att32.astype(jnp.int16), occ32.astype(jnp.int16)


@obs_prof.scope("tt.delta")
def batch_local_search_delta(pa, key, slots, rooms_arr, n_rounds: int,
                             n_candidates: int = 8,
                             p1: float = 1.0, p2: float = 1.0,
                             p3: float = 0.0):
    """Drop-in replacement for local_search.batch_local_search using
    delta evaluation; identical results for identical keys."""
    cap_rank = capacity_rank(pa)
    P = slots.shape[0]
    state = init_state(pa, slots, rooms_arr)

    def eval_candidate(kk, st):
        """One candidate per individual: (d_hcv, d_scv, evs, new_slots,
        new_rooms) all batched over P."""
        keys = jax.random.split(kk, P)

        def per_ind(k, s, r, att, occ):
            evs, new_slots, active = _gen_candidate(pa, k, s, p1, p2, p3)
            d_hcv, d_scv, new_rooms = _delta_one(
                pa, s, r, att, occ, evs, new_slots, active, cap_rank)
            # anchored-objective delta: inactive pad lanes pass new ==
            # old and cancel; zero-weight events contribute 0, so on
            # unanchored instances d_anc is exactly 0
            d_anc = fitness.anchor_delta(pa, s, evs, new_slots)
            return d_hcv, d_scv, d_anc, evs, new_slots, new_rooms

        return jax.vmap(per_ind)(keys, st.slots, st.rooms, st.att, st.occ)

    def one_round(st, k):
        cand_keys = jax.random.split(k, n_candidates)
        d_hcv, d_scv, d_anc, evs, new_slots, new_rooms = lax.map(
            lambda kk: eval_candidate(kk, st), cand_keys)   # (K, P, ...)

        # The maintained pen includes the anchor term (init_state uses
        # batch_penalty); recover each individual's anchor residual
        # exactly and carry it through the candidate penalties, so
        # selection here agrees with fitness.compute_penalty on the
        # SAME anchored objective.
        anc = st.pen - fitness.base_penalty(st.hcv, st.scv)  # (P,)
        new_hcv = st.hcv[None, :] + d_hcv                   # (K, P)
        new_scv = st.scv[None, :] + d_scv
        new_pen = (fitness.base_penalty(new_hcv, new_scv)
                   + anc[None, :] + d_anc)
        best = jnp.argmin(new_pen, axis=0)                  # (P,)
        ar = jnp.arange(P)
        best_pen = new_pen[best, ar]
        better = best_pen < st.pen                          # (P,)

        def apply_or_keep(b, s, r, att, occ, e3, ns3, nr3):
            s2, r2, att2, occ2 = _apply_move(pa, (s, r, att, occ),
                                             e3, ns3, nr3)
            return (jnp.where(b, s2, s), jnp.where(b, r2, r),
                    jnp.where(b, att2, att), jnp.where(b, occ2, occ))

        s2, r2, att2, occ2 = jax.vmap(apply_or_keep)(
            better, st.slots, st.rooms, st.att, st.occ,
            evs[best, ar], new_slots[best, ar], new_rooms[best, ar])

        st = LSState(
            slots=s2, rooms=r2, att=att2, occ=occ2,
            pen=jnp.where(better, best_pen, st.pen),
            hcv=jnp.where(better, new_hcv[best, ar], st.hcv),
            scv=jnp.where(better, new_scv[best, ar], st.scv))
        return st, None

    keys = jax.random.split(key, n_rounds)
    state, _ = lax.scan(one_round, state, keys)
    return state.slots, state.rooms


@functools.partial(jax.jit,
                   static_argnames=("n_rounds", "n_candidates"))
def jit_batch_local_search_delta(pa, key, slots, rooms_arr, n_rounds: int,
                                 n_candidates: int = 8,
                                 p1: float = 1.0, p2: float = 1.0,
                                 p3: float = 0.0):
    return batch_local_search_delta(pa, key, slots, rooms_arr, n_rounds,
                                    n_candidates, p1, p2, p3)
