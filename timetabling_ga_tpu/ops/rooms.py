"""Room assignment: fixed-shape greedy matching kernels.

TPU-native redesign of the reference's per-timeslot bipartite max-matching
(Solution::assignRooms Solution.cpp:772-833, maxMatching 836-849,
networkFlow 852-891). The reference builds an augmenting-path matching per
timeslot and drops unmatched events into the least-busy suitable room
(Solution.cpp:814-830) — i.e. its own fallback is greedy, and the hcv
penalty absorbs any remaining clash. Data-dependent augmenting paths do not
map to XLA, so the kernel here is a *most-constrained-first greedy
matching* with deterministic fixed shapes:

  - events are processed in ascending order of their number of suitable
    rooms (fewest options first — the classic matching heuristic);
  - each event takes the best free suitable room in its timeslot,
    best-fit by capacity (smallest room that fits, minimizing blocking);
  - if no suitable room is free it takes the least-busy suitable room
    (exactly the reference's fallback, Solution.cpp:814-830);
  - if the event has no suitable room at all it takes the least-busy room.

The whole-solution form is one `lax.scan` over events (the occupancy grid
(T, R) is the carry); `vmap` batches it over a population. The single-event
form (`choose_room`) is O(R) with no scan and is what the local-search /
mutation moves use to re-room a moved event without disturbing the rest of
its slot.

Greedy most-constrained-first is not guaranteed maximum matching, but on
instances where a perfect per-slot matching exists it finds it in the vast
majority of cases, and any miss shows up as +1 hcv — the same degradation
path as the reference's fallback. See tests/test_rooms.py for the
clash-free property on room-rich instances.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# Composite-key weights: unsuitable >> busy >> capacity tie-break.
_W_UNSUIT = 1 << 24
_W_BUSY = 1 << 12


def capacity_rank(pa) -> jnp.ndarray:
    """(R,) int32 rank of each room by capacity (0 = smallest).

    Loop-invariant per problem — compute once and thread it into
    `choose_room` when calling it inside a scan/loop."""
    return jnp.argsort(jnp.argsort(pa.room_size)).astype(jnp.int32)


def _room_key(pa, occ_row: jnp.ndarray, event: jnp.ndarray,
              cap_rank: jnp.ndarray) -> jnp.ndarray:
    """Scoring key (R,) for choosing event's room in a slot; argmin wins.

    Preference order (reference parity at Solution.cpp:802-830):
      1. free suitable room, smallest capacity that fits (best-fit)
      2. least-busy suitable room (the reference's unmatched fallback)
      3. least-busy room of any kind (only if no suitable room exists;
         the resulting unsuitable-room hcv is counted by the fitness kernel)
    """
    suit = pa.possible[event]                       # (R,) bool
    return (jnp.where(suit, 0, _W_UNSUIT)
            + occ_row * _W_BUSY
            + cap_rank)


def choose_room(pa, occ_row: jnp.ndarray, event: jnp.ndarray,
                cap_rank: jnp.ndarray = None) -> jnp.ndarray:
    """Pick a room for `event` given its slot's occupancy counts (R,).

    O(R), no scan — used by moves to re-room a single moved event without
    re-matching the whole slot (cheaper than the reference's full per-slot
    re-match at Solution.cpp:372-375; any lost matching quality is
    recovered by the next full rematch at crossover)."""
    if cap_rank is None:
        cap_rank = capacity_rank(pa)
    return jnp.argmin(_room_key(pa, occ_row, event, cap_rank)).astype(
        jnp.int32)


def assign_rooms(pa, slots: jnp.ndarray) -> jnp.ndarray:
    """Full-solution room matching: (E,) slots -> (E,) rooms.

    Equivalent role to the reference's assignRooms over all 45 slots as
    done by crossover (Solution.cpp:905-908) and initial construction
    (Solution.cpp:57-60), but across all slots in one scan: processing
    events most-constrained-first interleaves slots safely because slot
    occupancies are independent.
    """
    slots = jnp.asarray(slots)
    E, R = pa.possible.shape
    # Key-packing bounds: occupancy (<= E) and cap_rank (< R) must stay
    # inside their bit fields or the preference order silently inverts.
    assert E < _W_UNSUIT // _W_BUSY and R < _W_BUSY, (E, R)
    T = pa.n_slots
    suit_count = jnp.sum(pa.possible, axis=1).astype(jnp.int32)
    order = jnp.argsort(suit_count)                 # most constrained first
    cap_rank = capacity_rank(pa)

    def step(occ, e):
        t = slots[e]
        r = choose_room(pa, occ[t], e, cap_rank)
        return occ.at[t, r].add(1), r

    occ0 = jnp.zeros((T, R), dtype=jnp.int32)
    _, rooms_in_order = lax.scan(step, occ0, order)
    return jnp.zeros((E,), jnp.int32).at[order].set(rooms_in_order)


def batch_assign_rooms(pa, slots: jnp.ndarray) -> jnp.ndarray:
    """(P, E) slots -> (P, E) rooms."""
    return jax.vmap(lambda s: assign_rooms(pa, s))(slots)


def occupancy(pa, slots: jnp.ndarray, rooms: jnp.ndarray) -> jnp.ndarray:
    """Occupancy counts (T, R) of one solution — the dense replacement for
    the reference's ragged `timeslot_events` index (Solution.h:37)."""
    occ = jnp.zeros((pa.n_slots, pa.n_rooms), dtype=jnp.int32)
    return occ.at[slots, rooms].add(1)
