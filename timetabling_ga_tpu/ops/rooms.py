"""Room assignment: fixed-shape greedy matching kernels.

TPU-native redesign of the reference's per-timeslot bipartite max-matching
(Solution::assignRooms Solution.cpp:772-833, maxMatching 836-849,
networkFlow 852-891). The reference builds an augmenting-path matching per
timeslot and drops unmatched events into the least-busy suitable room
(Solution.cpp:814-830) — i.e. its own fallback is greedy, and the hcv
penalty absorbs any remaining clash. Data-dependent augmenting paths do not
map to XLA, so the kernel here is a *most-constrained-first greedy
matching* with deterministic fixed shapes:

  - events are processed in ascending order of their number of suitable
    rooms (fewest options first — the classic matching heuristic);
  - each event takes the best free suitable room in its timeslot,
    best-fit by capacity (smallest room that fits, minimizing blocking);
  - if no suitable room is free it takes the least-busy suitable room
    (exactly the reference's fallback, Solution.cpp:814-830);
  - if the event has no suitable room at all it takes the least-busy room.

The whole-solution form is one `lax.scan` over events (the occupancy grid
(T, R) is the carry); `vmap` batches it over a population. The single-event
form (`choose_room`) is O(R) with no scan and is what the local-search /
mutation moves use to re-room a moved event without disturbing the rest of
its slot.

Greedy most-constrained-first is not guaranteed maximum matching, but on
instances where a perfect per-slot matching exists it finds it in the vast
majority of cases, and any miss shows up as +1 hcv — the same degradation
path as the reference's fallback. See tests/test_rooms.py for the
clash-free property on room-rich instances.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from timetabling_ga_tpu.obs import prof as obs_prof

# Composite-key weights: marginal hcv cost >> suitability tie >> capacity.
_W_COST = 1 << 13
_W_UNSUIT = 1 << 12
_W_BUSY = _W_COST  # alias kept for the key-packing bound checks below
# Dead-room penalty: rooms masked out by shape bucketing (pa.room_mask
# False — zero capacity, zero features; serve/bucket.py) must NEVER win
# a room argmin, or a padded instance's matching would diverge from the
# unpadded instance's. Strictly dominates every live key: live keys top
# out near (E+1)*_W_COST + _W_UNSUIT + R < 2^26 (the assert below bounds
# E and R), and 2^26 + _W_DEAD still fits int32.
_W_DEAD = 1 << 28


def _dead_rooms(pa) -> jnp.ndarray:
    """(R,) int32 additive key penalty excluding masked-out rooms from
    every room argmin (all-zero on unpadded instances)."""
    return (~pa.room_mask).astype(jnp.int32) * _W_DEAD


def capacity_rank(pa) -> jnp.ndarray:
    """(R,) int32 rank of each room by capacity (0 = smallest).

    Loop-invariant per problem — compute once and thread it into
    `choose_room` when calling it inside a scan/loop."""
    return jnp.argsort(jnp.argsort(pa.room_size)).astype(jnp.int32)


def _room_key(pa, occ_row: jnp.ndarray, event: jnp.ndarray,
              cap_rank: jnp.ndarray) -> jnp.ndarray:
    """Scoring key (R,) for choosing event's room in a slot; argmin wins.

    MARGINAL-hcv-COST ordering: putting the event into a room with n
    occupants costs n clash pairs, plus 1 if the room is unsuitable —
    so the key is (n + unsuitable) first, then prefer suitable on ties,
    then best-fit capacity. For a free suitable room this reduces to the
    reference's primary best-fit choice; where it differs is the
    overflow case: the reference parks ALL unmatched events in the
    least-busy suitable room (Solution.cpp:814-830), which stacks k
    surplus events into C(k,2) clash pairs when one suitable room
    exists, where cost-greedy spreads them at +1 hcv each. Measured on
    room-tight instances this roughly halves matcher-attributable hcv —
    a deliberate, documented improvement over reference fallback parity.
    """
    suit = pa.possible[event]                       # (R,) bool
    unsuit = (~suit).astype(jnp.int32)
    return ((occ_row + unsuit) * _W_COST
            + unsuit * _W_UNSUIT
            + cap_rank
            + _dead_rooms(pa))


@obs_prof.scope("tt.rooms")
def choose_room(pa, occ_row: jnp.ndarray, event: jnp.ndarray,
                cap_rank: jnp.ndarray = None) -> jnp.ndarray:
    """Pick a room for `event` given its slot's occupancy counts (R,).

    O(R), no scan — used by moves to re-room a single moved event without
    re-matching the whole slot (cheaper than the reference's full per-slot
    re-match at Solution.cpp:372-375; any lost matching quality is
    recovered by the next full rematch at crossover)."""
    if cap_rank is None:
        cap_rank = capacity_rank(pa)
    return jnp.argmin(_room_key(pa, occ_row, event, cap_rank)).astype(
        jnp.int32)


@obs_prof.scope("tt.rooms")
def assign_rooms(pa, slots: jnp.ndarray) -> jnp.ndarray:
    """Full-solution room matching: (E,) slots -> (E,) rooms.

    Equivalent role to the reference's assignRooms over all 45 slots as
    done by crossover (Solution.cpp:905-908) and initial construction
    (Solution.cpp:57-60), but across all slots in one scan: processing
    events most-constrained-first interleaves slots safely because slot
    occupancies are independent.
    """
    slots = jnp.asarray(slots)
    E, R = pa.possible.shape
    # Key-packing bounds: cap_rank (< R) must stay under the unsuit flag
    # field and the whole key inside int32, or the preference order
    # silently inverts. (Native Matcher::choose mirrors this bound.)
    assert E < 4096 and R < _W_UNSUIT, (E, R)
    T = pa.n_slots
    suit_count = jnp.sum(pa.possible, axis=1).astype(jnp.int32)
    order = jnp.argsort(suit_count)                 # most constrained first
    cap_rank = capacity_rank(pa)

    def step(occ, e):
        t = slots[e]
        r = choose_room(pa, occ[t], e, cap_rank)
        # padded events (event_mask 0) choose a room but occupy nothing,
        # so the occupancy every LIVE event sees — and hence its choice —
        # is identical to the unpadded instance's
        return occ.at[t, r].add(pa.event_mask[e].astype(jnp.int32)), r

    occ0 = jnp.zeros((T, R), dtype=jnp.int32)
    _, rooms_in_order = lax.scan(step, occ0, order)
    return jnp.zeros((E,), jnp.int32).at[order].set(rooms_in_order)


def batch_assign_rooms(pa, slots: jnp.ndarray) -> jnp.ndarray:
    """(P, E) slots -> (P, E) rooms."""
    return jax.vmap(lambda s: assign_rooms(pa, s))(slots)


# Python int, not jnp.int32: a module-level device constant would
# initialize the JAX backend at import time, breaking both the engine's
# backend="cpu" switch and jax.distributed.initialize (which must run
# before any backend use). Weak-typed int promotes to int32 in-trace.
_BIG = 1 << 20


@obs_prof.scope("tt.rooms")
def augment_rooms(pa, slots: jnp.ndarray, rooms_arr: jnp.ndarray,
                  n_rounds: int = 4, cap_rank: jnp.ndarray = None
                  ) -> jnp.ndarray:
    """Round-limited augmenting-path improvement of a room assignment —
    the fixed-shape analogue of the reference's exact per-slot max
    matching (Solution::maxMatching, Solution.cpp:836-849).

    An event is *matched* when it owns a suitable room alone; each round
    runs, for every slot in parallel:

      1. length-1 augments: every unmatched event grabs its best-fit free
         suitable room (conflicts resolved by min-event-index bidding);
      2. length-3 augments: an unmatched event e takes an occupied
         suitable room r whose owner f can relocate to a free suitable
         room r' in the same slot (e -> r, f -> r'), with both the r and
         r' claims resolved by bidding; colliding augments abort cleanly.

    Each successful augment increases the slot's matching size by one, so
    quality is monotone; n_rounds bounds the augmenting-path length
    explored (2*n_rounds-1), trading exactness for a fixed shape. Events
    left unmatched keep their room and the hcv penalty absorbs them —
    the same degradation path as the reference's fallback
    (Solution.cpp:814-830).
    """
    E, R = pa.possible.shape
    T = pa.n_slots
    # Same key-packing bounds as assign_rooms: the parking keys below
    # pack (occupancy, unsuit flag, cap_rank) into one int32, so R must
    # stay under the unsuit bit field or preference order inverts.
    assert E < 4096 and R < _W_UNSUIT, (E, R)
    if cap_rank is None:
        cap_rank = capacity_rank(pa)
    ev = jnp.arange(E, dtype=jnp.int32)
    SENT = jnp.int32(E)
    UNM = jnp.int32(R)      # "unmatched" sentinel column in mrooms

    # The matching state `mrooms` (E,) is DECOUPLED from the genotype
    # rooms: mrooms[e] = e's matched room, or R when unmatched. Unmatched
    # events do not occupy cells, so they can neither block an owner's
    # relocation target nor shadow a free room (the failure mode of
    # augmenting directly on the genotype: greedy leaves squatters
    # everywhere and no cell ever looks free).
    owner0 = jnp.full((T, R), E, jnp.int32).at[slots, rooms_arr].min(ev)
    matched0 = ((owner0[slots, rooms_arr] == ev)
                & pa.possible[ev, rooms_arr])
    mrooms0 = jnp.where(matched0, rooms_arr, UNM)

    def matched_grid(mrooms):
        """(T, R+1) matched owner per cell, E where none (col R = dump)."""
        return jnp.full((T, R + 1), E, jnp.int32).at[slots, mrooms].min(ev)

    def resolve_bids(room_choice, active):
        """Min-index bidding on (slot, room) cells; True where won."""
        b_r = jnp.where(active, room_choice, UNM)
        b_e = jnp.where(active, ev, SENT)
        grid = jnp.full((T, R + 1), E, jnp.int32).at[slots, b_r].min(b_e)
        return active & (grid[slots, room_choice] == ev)

    def one_round(mrooms, _):
        # ---- stage 1: length-1 augment — grab a free suitable room
        grid = matched_grid(mrooms)
        matched = mrooms < UNM
        free_row = (grid[:, :R] == SENT)[slots]              # (E, R)
        k1 = jnp.where(pa.possible & free_row, cap_rank[None, :], _BIG)
        cand1 = jnp.argmin(k1, axis=1).astype(jnp.int32)
        has1 = jnp.take_along_axis(k1, cand1[:, None], 1)[:, 0] < _BIG
        win1 = resolve_bids(cand1, ~matched & has1)
        mrooms = jnp.where(win1, cand1, mrooms)

        # ---- stage 2: length-3 augment (e -> r, owner f -> free r')
        grid = matched_grid(mrooms)
        matched = mrooms < UNM
        free_row = (grid[:, :R] == SENT)[slots]
        # every event's best free suitable room in its own slot (the
        # relocation target r' if its owner role gets evicted)
        kf = jnp.where(pa.possible & free_row, cap_rank[None, :], _BIG)
        fcand = jnp.argmin(kf, axis=1).astype(jnp.int32)
        can_move = jnp.take_along_axis(kf, fcand[:, None], 1)[:, 0] < _BIG
        movable_pad = jnp.concatenate([can_move & matched,
                                       jnp.array([False])])

        own_row = grid[slots][:, :R]                         # (E, R)
        viable = (pa.possible & (own_row != SENT)
                  & movable_pad[jnp.minimum(own_row, SENT)])
        k2 = jnp.where(viable, cap_rank[None, :], _BIG)
        cand2 = jnp.argmin(k2, axis=1).astype(jnp.int32)
        has2 = jnp.take_along_axis(k2, cand2[:, None], 1)[:, 0] < _BIG
        win_e = resolve_bids(cand2, ~matched & has2)

        # evicted owners bid for their relocation rooms (same slot)
        f = own_row[ev, cand2]                               # (E,)
        f_safe = jnp.minimum(f, SENT - 1)                    # index-safe
        fr = fcand[f_safe]
        b_f = jnp.where(win_e, f_safe, SENT)
        b_fr = jnp.where(win_e, fr, UNM)
        grid3 = jnp.full((T, R + 1), E, jnp.int32).at[slots, b_fr].min(b_f)
        win_f = win_e & (grid3[slots, fr] == f_safe)

        # apply the non-colliding augments: f moves to r', e takes r
        mrooms_ext = jnp.concatenate([mrooms, jnp.zeros((1,), jnp.int32)])
        tgt = jnp.where(win_f, f_safe, SENT)
        mrooms_ext = mrooms_ext.at[tgt].set(
            jnp.where(win_f, fr, mrooms_ext[SENT]))
        mrooms = mrooms_ext[:E]
        mrooms = jnp.where(win_f, cand2, mrooms)
        return mrooms, None

    mrooms, _ = lax.scan(one_round, mrooms0, None, length=n_rounds)

    # Park the still-unmatched at minimal marginal hcv cost (_room_key
    # ordering: n occupants cost n pairs, +1 if unsuitable — a deliberate
    # improvement over the reference's stack-into-least-busy-suitable
    # fallback, Solution.cpp:814-830; see _room_key). Two bid rounds
    # spread co-parked events instead of letting them all pick the same
    # cheapest cell. Padded events enter the park phase pre-parked: they
    # must neither bid (a won cell would add phantom occupancy the live
    # events' keys see) nor end up in a live room's count.
    live_ev = pa.event_mask > 0.5                          # (E,) bool
    matched = mrooms < UNM
    # occupancy over the matched assignment, with a dump column R
    occ = jnp.zeros((T, R + 1), jnp.int32).at[slots, mrooms].add(
        matched.astype(jnp.int32))
    unsuit = (~pa.possible).astype(jnp.int32)              # (E, R)

    def park_key(occ):
        return ((occ[slots][:, :R] + unsuit) * _W_COST
                + unsuit * _W_UNSUIT + cap_rank[None, :]
                + _dead_rooms(pa)[None, :])

    def park_round(carry, _):
        occ, mrooms, parked = carry
        pick = jnp.argmin(park_key(occ), axis=1).astype(jnp.int32)
        win = resolve_bids(pick, ~parked)
        occ = occ.at[slots, jnp.where(win, pick, R)].add(
            win.astype(jnp.int32))
        mrooms = jnp.where(win, pick, mrooms)
        return (occ, mrooms, parked | win), None

    (occ, mrooms, parked), _ = lax.scan(
        park_round, (occ, mrooms, matched | ~live_ev), None, length=2)
    # stragglers (lost both bid rounds): take current argmin, collisions
    # accepted — the hcv penalty absorbs them
    fallback = jnp.argmin(park_key(occ), axis=1).astype(jnp.int32)
    # padded events keep their incoming (valid, fitness-invisible) room:
    # their mrooms is the out-of-range UNM sentinel by construction
    return jnp.where(live_ev, jnp.where(parked, mrooms, fallback),
                     rooms_arr)


@obs_prof.scope("tt.rooms")
def parallel_assign_rooms(pa, slots: jnp.ndarray,
                          n_rounds: int = 4) -> jnp.ndarray:
    """O(1)-depth room assignment: best-fit init + bounded augmentation.

    The depth-free ALTERNATIVE to the E-deep sequential `assign_rooms`
    scan (the crossover cost dominator flagged in round 1): every event
    first picks its best-fit suitable room ignoring occupancy, then
    `augment_rooms` resolves collisions and chases augmenting paths in a
    constant number of wide parallel rounds; `vmap` batches it over
    populations with no serial E-chain anywhere. Selected on the
    breeding path via GAConfig.rooms_mode="parallel"; it trades a small
    matching-quality loss (measured: ~6% above the exact lower bound on
    room-tight instances vs ~1% for the scan) for constant depth — the
    default is decided by the bench.py wall-clock shootout.
    """
    cap_rank = capacity_rank(pa)
    k = jnp.where(pa.possible, cap_rank[None, :], _BIG)
    init = jnp.argmin(k, axis=1).astype(jnp.int32)           # (E,)
    return augment_rooms(pa, slots, init, n_rounds, cap_rank)


def batch_parallel_assign_rooms(pa, slots: jnp.ndarray,
                                n_rounds: int = 4) -> jnp.ndarray:
    """(P, E) slots -> (P, E) rooms, O(1) serial depth."""
    return jax.vmap(
        lambda s: parallel_assign_rooms(pa, s, n_rounds))(slots)


@obs_prof.scope("tt.rooms")
def occupancy(pa, slots: jnp.ndarray, rooms: jnp.ndarray) -> jnp.ndarray:
    """Occupancy counts (T, R) of one solution — the dense replacement for
    the reference's ragged `timeslot_events` index (Solution.h:37).
    Padded (masked-out) events occupy nothing, so every consumer (moves,
    delta LS, sweeps) sees exactly the unpadded instance's grid."""
    occ = jnp.zeros((pa.n_slots, pa.n_rooms), dtype=jnp.int32)
    return occ.at[slots, rooms].add(pa.event_mask.astype(jnp.int32))
