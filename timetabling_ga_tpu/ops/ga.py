"""Population-level GA operators and the generation loop.

TPU-native redesign of the reference's breeding machinery:

- tournament selection, size 5 (ga.cpp:129-145 `selection5`)
- uniform crossover with p=0.8 (Solution::crossover Solution.cpp:893-910;
  applied at ga.cpp:562-566), with a FULL room rematch of the child — the
  same thing the reference's crossover does by re-running assignRooms over
  all 45 slots (Solution.cpp:905-908), minus its stale-`timeslot_events`
  bug (SURVEY C11), which cannot exist here because occupancy is always
  recomputed from the genotype.
- mutation = one random move with p=0.5 (ga.cpp:569-571, Solution.cpp:912)
- replacement: the reference replaces the single worst member per child
  inside an OpenMP critical and re-sorts (ga.cpp:580-585, steady-state).
  Steady-state is inherently serial; the TPU variant is generational
  (mu+lambda) truncation: P children are bred in one vmapped batch,
  concatenated with the parents, and the best P survive. This preserves
  elitist pressure (documented divergence, SURVEY C13).

The whole generation is one jitted tensor program; `run` wraps it in
`lax.scan` so an entire evolution runs on-device in a single dispatch.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from timetabling_ga_tpu.obs import prof as obs_prof
from timetabling_ga_tpu.ops import fitness
from timetabling_ga_tpu.ops.moves import random_move
from timetabling_ga_tpu.ops.rooms import (
    assign_rooms, batch_assign_rooms, parallel_assign_rooms)


@dataclasses.dataclass(frozen=True)
class GAConfig:
    """Breeding hyper-parameters (reference defaults cited).

    Frozen/hashable so it can be a jit static argument."""

    pop_size: int = 10            # ga.cpp:64
    tournament_k: int = 5         # ga.cpp:129-145
    p_crossover: float = 0.8      # ga.cpp:562
    p_mutation: float = 0.5       # ga.cpp:569
    p1: float = 1.0               # move-type probs, Control.cpp:103-125
    p2: float = 1.0
    p3: float = 0.0
    ls_steps: int = 0             # local-search rounds per child (C8); 0=off
    ls_candidates: int = 8        # candidate moves per LS round
    ls_delta: bool = True         # delta-eval LS (C6) vs full re-eval
    ls_mode: str = "random"       # "random" K-candidate | "sweep"
    ls_sweeps: int = 1            # max sweep passes when ls_mode="sweep"
    ls_swap_block: int = 8        # Move2 partners per event per sweep pass
    ls_block_events: int = 1      # events per sweep scan step (B>1: scan
    #                               depth E/B, best single move of the
    #                               block applied — throughput/density
    #                               trade, ops/sweep.py sweep_pass)
    ls_sideways: float = 0.0      # P(accept equal-penalty best) per
    #                               individual per sweep step — plateau
    #                               walk, the global analogue of the
    #                               reference's event-local acceptance
    #                               (Solution.cpp:519-527)
    ls_converge: bool = False     # sweep passes early-exit at the whole-
    #                               population local optimum (the
    #                               reference's stopping rule,
    #                               Solution.cpp:524/653); ls_sweeps is
    #                               then the hard bound
    ls_hot_k: int = 0             # violation-guided sweep: examine only
    #                               the top-K events by violation
    #                               involvement per pass (the reference's
    #                               phase-1/2 skip rule, Solution.cpp:
    #                               501-505/628-633); 0 = all events
    init_sweeps: int = 0          # sweep-to-convergence passes on the
    #                               INITIAL population (the reference LS-
    #                               polishes its initial pop, ga.cpp:
    #                               429-434); 0 = off
    rooms_mode: str = "scan"      # crossover rematch: "scan" E-deep
    #                               cost-greedy | "parallel" O(1)-depth
    #                               (rooms.parallel_assign_rooms)
    multi_objective: bool = False  # NSGA-II (hcv, scv) replacement


class PopState(NamedTuple):
    """Device-resident population: the dense replacement for the
    reference's `Solution* pop[]` (ga.cpp:60). Sorted by penalty
    ascending after every generation (best first, like ga.cpp:583).

    Buffer lifetime: the engine's cached runners are jitted with
    `donate_argnums` on their PopState argument (parallel/islands.py
    `_donate`), so a state handed to a dispatch is CONSUMED — its
    buffers are deleted and aliased into the output. Treat every
    dispatched state as moved-from: read the returned state, or clone
    first (engine._clone) if the input must survive. tt-analyze TT203
    lints the read-after-donation mistake where the donating jit is in
    view."""

    slots: jnp.ndarray    # (P, E) int32
    rooms: jnp.ndarray    # (P, E) int32
    penalty: jnp.ndarray  # (P,)   int32
    hcv: jnp.ndarray      # (P,)   int32
    scv: jnp.ndarray      # (P,)   int32


def evaluate(pa, slots, rooms_arr) -> PopState:
    """Build a PopState by evaluating (P, E) genotypes, sorted best-first
    by (penalty, scv) — the reported-evaluation order (fitness.lex_order),
    so row 0 is the individual the JSONL protocol should report."""
    penalty, hcv, scv = fitness.batch_penalty(pa, slots, rooms_arr)
    order = fitness.lex_order(penalty, scv)
    return PopState(slots=slots[order], rooms=rooms_arr[order],
                    penalty=penalty[order], hcv=hcv[order], scv=scv[order])


@obs_prof.scope("tt.ga")
def init_population(pa, key, pop_size: int,
                    cfg: "GAConfig" = None) -> PopState:
    """Random initial population: uniform random timeslots then greedy room
    matching per individual (RandomInitialSolution, Solution.cpp:48-61),
    followed by an initial local search when `cfg.init_sweeps > 0` — the
    reference runs localSearch on every initial individual before the
    first generation (ga.cpp:429-434), which is how it reaches
    feasibility in well under a second on easy instances.

    Unlike the reference, every island initializes its own population from
    its own key rather than broadcasting rank 0's population everywhere
    (ga.cpp:429-444) — a documented divergence (SURVEY C17) that buys
    diversity for free.
    """
    E = pa.n_events
    do_ls = cfg is not None and cfg.init_sweeps > 0
    # Split only when the init LS is on: the default path must keep the
    # exact RNG stream of earlier rounds so recorded seeded results
    # (BENCH_r0x.json) stay reproducible.
    k_slots, k_ls = jax.random.split(key) if do_ls else (key, None)
    slots = jax.random.randint(k_slots, (pop_size, E), 0, pa.n_slots,
                               dtype=jnp.int32)
    rooms_arr = batch_assign_rooms(pa, slots)
    if do_ls:
        from timetabling_ga_tpu.ops.sweep import sweep_local_search
        slots, rooms_arr = sweep_local_search(
            pa, k_ls, slots, rooms_arr, n_sweeps=cfg.init_sweeps,
            swap_block=cfg.ls_swap_block, converge=True,
            block_events=cfg.ls_block_events, sideways=cfg.ls_sideways,
            hot_k=cfg.ls_hot_k, p3=cfg.p3)
    return evaluate(pa, slots, rooms_arr)


def tournament(key, penalty: jnp.ndarray, scv: jnp.ndarray,
               k: int) -> jnp.ndarray:
    """Tournament selection: k uniform draws, return index of the best
    by (penalty, scv) — scv breaks penalty ties toward the reported
    metric (ga.cpp:129-145 selection5: 5 draws, argmin penalty). The
    reference reads the population unlocked while other threads sort (a
    data race, SURVEY C14); here the population is immutable within a
    generation."""
    P = penalty.shape[0]
    draws = jax.random.randint(key, (k,), 0, P)
    return draws[jnp.lexsort((scv[draws], penalty[draws]))[0]]


@obs_prof.scope("tt.ga")
def _make_child(pa, key, state: PopState, cfg: GAConfig, mo_stats=None):
    """Breed one child: 2x tournament -> crossover(p) -> mutation(p).

    (ga.cpp:543-571 minus the wasteful throwaway Solution allocs at
    543-548.) Returns (slots, rooms, did_crossover, did_mutate,
    parent_a) of the child; evaluation happens batched in `generation`.
    The two operator flags and the base-parent index feed the quality
    observatory's efficacy counters (README "Search-quality
    observatory") — they are values the breeding already drew, so
    shipping them costs nothing and perturbs no RNG stream.

    `mo_stats` is None (scalar-penalty tournament, ga.cpp:129-145) or a
    (ranks, crowding) pair: then parents are drawn by the NSGA-II
    crowded-comparison tournament (Deb et al. 2002 pair selection with
    front-based replacement — both halves, not just the survivor half)."""
    k_a, k_b, k_x, k_mask, k_m, k_mv = jax.random.split(key, 6)
    if mo_stats is not None:
        from timetabling_ga_tpu.ops import nsga
        ranks, crowd = mo_stats
        ia = nsga.crowded_tournament(k_a, ranks, crowd, cfg.tournament_k)
        ib = nsga.crowded_tournament(k_b, ranks, crowd, cfg.tournament_k)
    else:
        ia = tournament(k_a, state.penalty, state.scv, cfg.tournament_k)
        ib = tournament(k_b, state.penalty, state.scv, cfg.tournament_k)
    s_a, r_a = state.slots[ia], state.rooms[ia]
    s_b = state.slots[ib]

    # uniform crossover on timeslots + full room rematch (Solution.cpp:
    # 893-910); with prob 1-p_crossover the child is a copy of parent A
    # (ga.cpp:565-566)
    mask = jax.random.bernoulli(k_mask, 0.5, (s_a.shape[0],))
    x_slots = jnp.where(mask, s_a, s_b)
    if cfg.rooms_mode == "parallel":
        # O(1)-depth matcher: removes the E-deep scan from the breeding
        # critical path at a small matching-quality cost (see
        # rooms.parallel_assign_rooms; default decided by bench.py)
        x_rooms = parallel_assign_rooms(pa, x_slots)
    else:
        x_rooms = assign_rooms(pa, x_slots)
    do_x = jax.random.bernoulli(k_x, cfg.p_crossover)
    slots = jnp.where(do_x, x_slots, s_a)
    rooms_arr = jnp.where(do_x, x_rooms, r_a)

    # mutation: one random move with p_mutation (ga.cpp:569-571)
    m_slots, m_rooms = random_move(pa, k_mv, slots, rooms_arr,
                                   cfg.p1, cfg.p2, cfg.p3)
    do_m = jax.random.bernoulli(k_m, cfg.p_mutation)
    slots = jnp.where(do_m, m_slots, slots)
    rooms_arr = jnp.where(do_m, m_rooms, rooms_arr)
    return slots, rooms_arr, do_x, do_m, ia


@obs_prof.scope("tt.ga")
def generation(pa, key, state: PopState, cfg: GAConfig,
               with_quality: bool = False):
    """One generation: breed P children in a single vmapped batch, then
    mu+lambda truncation over parents+children.

    `with_quality=True` (the tt-obs quality observatory) additionally
    returns a (quality.N_OPS,) int32 vector of operator-efficacy
    counters for this generation: crossover attempts/wins, mutation
    attempts/wins (a WIN is a child whose evaluated penalty strictly
    beats its base parent's — credited to every operator that touched
    the child, the honest attribution available without re-evaluating
    each operator's output separately), then the sweep LS's accepted
    Move1/Move2/Move3 counts (sweep_local_search return_ops; zeros for
    the random-candidate LS). Derived entirely from values the breeding
    already computes: no extra RNG draws, no extra fitness evaluations
    — the trajectory is bit-identical with the flag on or off."""
    keys = jax.random.split(key, cfg.pop_size)
    mo_stats = None
    if cfg.multi_objective:
        # ranks/crowding computed ONCE per generation, shared by all
        # parent draws (the population is immutable within a generation)
        from timetabling_ga_tpu.ops import nsga
        ranks = nsga.nondominated_ranks(state.hcv, state.scv)
        crowd = nsga.crowding_distance(state.hcv, state.scv, ranks)
        mo_stats = (ranks, crowd)
    ch_slots, ch_rooms, did_x, did_m, parent_a = jax.vmap(
        lambda k: _make_child(pa, k, state, cfg, mo_stats))(keys)

    sweep_ops = jnp.zeros((3,), jnp.int32)
    if cfg.ls_mode == "sweep" and cfg.ls_sweeps > 0:
        # systematic Move1+Move2 sweep (Solution.cpp:508-561 analogue)
        from timetabling_ga_tpu.ops.sweep import sweep_local_search
        k_ls = jax.random.fold_in(key, 0x15)
        out = sweep_local_search(
            pa, k_ls, ch_slots, ch_rooms,
            n_sweeps=cfg.ls_sweeps, swap_block=cfg.ls_swap_block,
            converge=cfg.ls_converge, block_events=cfg.ls_block_events,
            sideways=cfg.ls_sideways, hot_k=cfg.ls_hot_k, p3=cfg.p3,
            return_ops=with_quality)
        ch_slots, ch_rooms = out[0], out[1]
        if with_quality:
            sweep_ops = out[2]
    elif cfg.ls_steps > 0:
        if cfg.ls_delta:
            from timetabling_ga_tpu.ops.delta import (
                batch_local_search_delta as ls_fn)
        else:
            from timetabling_ga_tpu.ops.local_search import (
                batch_local_search as ls_fn)
        k_ls = jax.random.fold_in(key, 0x15)
        ch_slots, ch_rooms = ls_fn(
            pa, k_ls, ch_slots, ch_rooms,
            n_rounds=cfg.ls_steps, n_candidates=cfg.ls_candidates,
            p1=cfg.p1, p2=cfg.p2, p3=cfg.p3)

    c_pen, c_hcv, c_scv = fitness.batch_penalty(pa, ch_slots, ch_rooms)
    all_slots = jnp.concatenate([state.slots, ch_slots])
    all_rooms = jnp.concatenate([state.rooms, ch_rooms])
    all_pen = jnp.concatenate([state.penalty, c_pen])
    all_hcv = jnp.concatenate([state.hcv, c_hcv])
    all_scv = jnp.concatenate([state.scv, c_scv])
    if cfg.multi_objective:
        # NSGA-II replacement on (hcv, scv); the population stays
        # penalty-sorted within the survivor set so rows 0/1 remain the
        # migration emigrants (parallel/islands.py relies on that)
        from timetabling_ga_tpu.ops.nsga import nsga_survivor_indices
        keep = nsga_survivor_indices(all_hcv, all_scv, cfg.pop_size)
        order = keep[fitness.lex_order(all_pen[keep], all_scv[keep])]
    else:
        order = fitness.lex_order(all_pen, all_scv)[:cfg.pop_size]
    new_state = PopState(slots=all_slots[order], rooms=all_rooms[order],
                         penalty=all_pen[order], hcv=all_hcv[order],
                         scv=all_scv[order])
    if not with_quality:
        return new_state
    improved = c_pen < state.penalty[parent_a]
    q = jnp.stack([
        jnp.sum(did_x.astype(jnp.int32)),
        jnp.sum((did_x & improved).astype(jnp.int32)),
        jnp.sum(did_m.astype(jnp.int32)),
        jnp.sum((did_m & improved).astype(jnp.int32))])
    return new_state, jnp.concatenate([q, sweep_ops])


@functools.partial(jax.jit, static_argnames=("cfg", "n_generations"))
def run(pa, key, state: PopState, cfg: GAConfig, n_generations: int):
    """Evolve `n_generations` on-device in one dispatch.

    The reference's generation loop is ~2001 iterations statically split
    over OpenMP threads (ga.cpp:510); here it is a lax.scan whose body
    breeds the whole population at once. Returns the final state and the
    per-generation best penalty trace (the data behind the JSONL
    `logEntry` records, ga.cpp:203-228)."""

    def step(st, k):
        st = generation(pa, k, st, cfg)
        return st, st.penalty[0]

    keys = jax.random.split(key, n_generations)
    state, best_trace = lax.scan(step, state, keys)
    return state, best_trace
