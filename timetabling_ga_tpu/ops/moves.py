"""Neighborhood moves as pure-functional tensor updates.

TPU-native redesign of the reference's mutating moves (Solution::Move1/2/3,
Solution.cpp:357-439, randomMove 441-469). Where the reference mutates a
`vector<pair>` plus a ragged `timeslot_events` index and re-runs per-slot
matching, each move here is a pure function

    (slots (E,), rooms (E,)) -> (slots', rooms')

that relocates events and re-rooms ONLY the moved events via the O(R)
greedy insert (`rooms.choose_room`) — cheaper than the reference's full
per-slot rematch, with matching quality restored at the next full
`assign_rooms` (crossover / re-init). All moves keep the invariant that
every event has exactly one (slot, room); there is no ragged index to go
stale (the reference's crossover stale-index bug, SURVEY C11, cannot
exist here by construction).

`random_move` mirrors the reference's move-type sampling (p1/p2/p3
normalized, distinct events, uniform target slot) with threefry keys.

Moves are objective-agnostic: they sample and apply relocations but
never score them. Under the anchored objective (serve/editsolve.py) the
anchor term is charged where moves are EVALUATED — `fitness.anchor_cost`
in the full penalty, `fitness.anchor_delta` at every delta-acceptance
site (ops/delta.py, ops/sweep.py, ops/lahc.py) — so nothing here changes
and the sampled candidate streams stay bit-identical.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from timetabling_ga_tpu.obs import prof as obs_prof
from timetabling_ga_tpu.ops.rooms import (
    capacity_rank, choose_room, occupancy)


@obs_prof.scope("tt.moves")
def move1(pa, slots, rooms_arr, e, t, cap_rank=None):
    """Move event `e` to timeslot `t` (Solution::Move1, Solution.cpp:357).

    The moved event is re-roomed by greedy insert into its new slot; all
    other events are untouched.
    """
    if cap_rank is None:
        cap_rank = capacity_rank(pa)
    occ = occupancy(pa, slots, rooms_arr)
    # self-removals are weighted by the event mask throughout this
    # module: a padded (masked-out) event never occupied its cell, so an
    # unweighted -1 would leave a phantom vacancy that skews the greedy
    # room choice of the OTHER moved events
    occ = occ.at[slots[e], rooms_arr[e]].add(
        -pa.event_mask[e].astype(jnp.int32))
    r = choose_room(pa, occ[t], e, cap_rank)
    return slots.at[e].set(t), rooms_arr.at[e].set(r)


@obs_prof.scope("tt.moves")
def move2(pa, slots, rooms_arr, e1, e2, cap_rank=None):
    """Swap the timeslots of events e1, e2 (Solution::Move2,
    Solution.cpp:378); both are re-roomed in their new slots."""
    if cap_rank is None:
        cap_rank = capacity_rank(pa)
    t1, t2 = slots[e1], slots[e2]
    w1 = pa.event_mask[e1].astype(jnp.int32)
    w2 = pa.event_mask[e2].astype(jnp.int32)
    occ = occupancy(pa, slots, rooms_arr)
    occ = occ.at[t1, rooms_arr[e1]].add(-w1)
    occ = occ.at[t2, rooms_arr[e2]].add(-w2)
    r1 = choose_room(pa, occ[t2], e1, cap_rank)
    occ = occ.at[t2, r1].add(w1)
    r2 = choose_room(pa, occ[t1], e2, cap_rank)
    slots = slots.at[e1].set(t2).at[e2].set(t1)
    rooms_arr = rooms_arr.at[e1].set(r1).at[e2].set(r2)
    return slots, rooms_arr


@obs_prof.scope("tt.moves")
def move3(pa, slots, rooms_arr, e1, e2, e3, cap_rank=None):
    """3-cycle: e1 -> slot of e2, e2 -> slot of e3, e3 -> slot of e1
    (Solution::Move3, Solution.cpp:405; the local search tries both cycle
    orientations — callers get the reverse cycle by permuting args)."""
    if cap_rank is None:
        cap_rank = capacity_rank(pa)
    t1, t2, t3 = slots[e1], slots[e2], slots[e3]
    w1 = pa.event_mask[e1].astype(jnp.int32)
    w2 = pa.event_mask[e2].astype(jnp.int32)
    w3 = pa.event_mask[e3].astype(jnp.int32)
    occ = occupancy(pa, slots, rooms_arr)
    occ = occ.at[t1, rooms_arr[e1]].add(-w1)
    occ = occ.at[t2, rooms_arr[e2]].add(-w2)
    occ = occ.at[t3, rooms_arr[e3]].add(-w3)
    r1 = choose_room(pa, occ[t2], e1, cap_rank)
    occ = occ.at[t2, r1].add(w1)
    r2 = choose_room(pa, occ[t3], e2, cap_rank)
    occ = occ.at[t3, r2].add(w2)
    r3 = choose_room(pa, occ[t1], e3, cap_rank)
    slots = slots.at[e1].set(t2).at[e2].set(t3).at[e3].set(t1)
    rooms_arr = rooms_arr.at[e1].set(r1).at[e2].set(r2).at[e3].set(r3)
    return slots, rooms_arr


@obs_prof.scope("tt.moves")
def sample_move(pa, key, slots,
                p1: float = 1.0, p2: float = 1.0, p3: float = 0.0):
    """Sample one random move in padded 3-relocation form.

    The single source of truth for Solution::randomMove's sampling
    (Solution.cpp:441-469): move type drawn with probabilities p1:p2:p3
    (normalized), distinct events, uniform target slot. Returns
    (events (3,), new_slots (3,), active (3,) bool); inactive pad
    entries keep their current slot (exact no-ops). Both the applying
    path (`random_move`) and the delta-evaluation path (ops/delta.py)
    consume THIS function, so they can never desynchronize."""
    E = slots.shape[0]
    k_type, k_ev, k_slot = jax.random.split(key, 3)
    probs = jnp.array([p1, p2, p3], dtype=jnp.float32)
    # categorical + top_k of uniforms, NOT jax.random.choice: choice's
    # replace=False path shuffles via an internal jit(_shuffle) whose
    # sort escapes shard_map's manual sharding on JAX 0.4.x and emits
    # cross-device all-reduces inside the per-island program — a CPU-
    # backend collective deadlock (tt-analyze TT302; same hazard as the
    # sweep shuffle). top_k over iid uniforms yields a uniformly random
    # ORDERED triple of distinct events, exactly choice's semantics.
    mtype = jax.random.categorical(k_type, jnp.log(probs))
    evs = lax.top_k(jax.random.uniform(k_ev, (E,)), 3)[1].astype(
        slots.dtype)
    t = jax.random.randint(k_slot, (), 0, pa.n_slots, dtype=slots.dtype)

    cur = slots[evs]                                   # (3,)
    new_slots = lax.switch(
        mtype,
        [lambda: jnp.stack([t, cur[1], cur[2]]),                 # Move1
         lambda: jnp.stack([cur[1], cur[0], cur[2]]),            # Move2
         lambda: jnp.stack([cur[1], cur[2], cur[0]])],           # Move3
    )
    active = lax.switch(
        mtype,
        [lambda: jnp.array([True, False, False]),
         lambda: jnp.array([True, True, False]),
         lambda: jnp.array([True, True, True])],
    )
    return evs, new_slots, active


@obs_prof.scope("tt.moves")
def apply_relocation(pa, slots, rooms_arr, evs, new_slots, active,
                     cap_rank=None):
    """Apply a padded 3-relocation: remove the active events from the
    occupancy grid, then re-slot and greedily re-room them in order
    (the shared application semantics of Move1/2/3)."""
    if cap_rank is None:
        cap_rank = capacity_rank(pa)
    occ = occupancy(pa, slots, rooms_arr)
    old_slots = slots[evs]
    old_rooms = rooms_arr[evs]
    live = pa.event_mask[evs].astype(occ.dtype)     # (3,) 0/1; see move1
    for m in range(3):
        act = active[m].astype(occ.dtype) * live[m]
        occ = occ.at[old_slots[m], old_rooms[m]].add(-act)
    for m in range(3):
        act = active[m].astype(occ.dtype) * live[m]
        r_choice = choose_room(pa, occ[new_slots[m]], evs[m], cap_rank)
        r_new = jnp.where(active[m], r_choice, old_rooms[m])
        occ = occ.at[new_slots[m], r_new].add(act)
        slots = slots.at[evs[m]].set(new_slots[m])
        rooms_arr = rooms_arr.at[evs[m]].set(r_new)
    return slots, rooms_arr


@obs_prof.scope("tt.moves")
def random_move(pa, key, slots, rooms_arr,
                p1: float = 1.0, p2: float = 1.0, p3: float = 0.0,
                cap_rank=None):
    """One random neighborhood move (Solution::randomMove,
    Solution.cpp:441-469): sample_move + apply_relocation."""
    evs, new_slots, active = sample_move(pa, key, slots, p1, p2, p3)
    return apply_relocation(pa, slots, rooms_arr, evs, new_slots, active,
                            cap_rank)
