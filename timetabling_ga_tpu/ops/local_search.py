"""Batched local search: the TPU redesign of the reference's hot loop.

The reference's `Solution::localSearch` (Solution.cpp:471-769) is a
sequential first-improvement sweep: for each event it tries all 45 target
slots (Move1), all swap partners (Move2), optionally 3-cycles (Move3),
deep-copying the solution per candidate and accepting the first strictly
improving move; its step counter resets on every improvement, and >95% of
program time is spent here (SURVEY section 3.2). Data-dependent loops and
per-candidate allocations cannot map onto XLA.

The redesign (SURVEY section 7.4): each round proposes K random candidate
moves per individual, evaluates them ALL with the batched population
kernel (`fitness.batch_penalty` on a (P,)-shaped candidate batch per
candidate slot, sequenced over K with `lax.map` to bound memory), and
accepts each individual's best candidate if it strictly improves. Rounds
run under `lax.scan` with fixed shapes; one TPU dispatch performs
P*K*n_rounds candidate evaluations.

The reference's two phases — hcv repair while infeasible
(Solution.cpp:497-618), then scv polish that never re-breaks feasibility
(619-768) — need no explicit gate here: acceptance compares the scalar
penalty `scv if feasible else 1e6+hcv` (Solution.cpp:162-170), whose
ordering makes any hcv reduction dominate while infeasible and makes any
feasibility-breaking move unacceptable once feasible.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from timetabling_ga_tpu.ops import fitness
from timetabling_ga_tpu.ops.moves import random_move
from timetabling_ga_tpu.ops.rooms import capacity_rank


def batch_local_search(pa, key, slots, rooms_arr, n_rounds: int,
                       n_candidates: int = 8,
                       p1: float = 1.0, p2: float = 1.0, p3: float = 0.0):
    """Hill-climb a whole population (P, E) for `n_rounds` rounds.

    Returns improved (slots, rooms). Population-level: every round all P
    individuals propose and evaluate K candidates simultaneously.
    """
    cap_rank = capacity_rank(pa)
    P = slots.shape[0]

    def propose(k, s, r):
        """One candidate move for every individual: (P, E) -> (P, E)."""
        keys = jax.random.split(k, P)
        return jax.vmap(
            lambda kk, ss, rr: random_move(pa, kk, ss, rr, p1, p2, p3,
                                           cap_rank))(keys, s, r)

    def one_round(carry, k):
        s, r, pen = carry

        def eval_candidate(kk):
            cs, cr = propose(kk, s, r)
            cpen, _, _ = fitness.batch_penalty(pa, cs, cr)
            return cs, cr, cpen

        # K sequential P-wide evaluations: full MXU utilization per
        # evaluation, O(P) (not O(P*K)) peak memory.
        cand_keys = jax.random.split(k, n_candidates)
        c_slots, c_rooms, c_pen = lax.map(eval_candidate, cand_keys)

        best = jnp.argmin(c_pen, axis=0)                  # (P,)
        ar = jnp.arange(P)
        best_pen = c_pen[best, ar]
        better = best_pen < pen                           # (P,)
        s = jnp.where(better[:, None], c_slots[best, ar], s)
        r = jnp.where(better[:, None], c_rooms[best, ar], r)
        pen = jnp.where(better, best_pen, pen)
        return (s, r, pen), None

    pen0, _, _ = fitness.batch_penalty(pa, slots, rooms_arr)
    keys = jax.random.split(key, n_rounds)
    (slots, rooms_arr, _), _ = lax.scan(
        one_round, (slots, rooms_arr, pen0), keys)
    return slots, rooms_arr


def local_search(pa, key, slots, rooms_arr, n_rounds: int,
                 n_candidates: int = 8,
                 p1: float = 1.0, p2: float = 1.0, p3: float = 0.0):
    """Single-individual form (E,) — thin wrapper over the batched path."""
    s, r = batch_local_search(pa, key, slots[None], rooms_arr[None],
                              n_rounds, n_candidates, p1, p2, p3)
    return s[0], r[0]


@functools.partial(jax.jit,
                   static_argnames=("n_rounds", "n_candidates"))
def jit_batch_local_search(pa, key, slots, rooms_arr, n_rounds: int,
                           n_candidates: int = 8,
                           p1: float = 1.0, p2: float = 1.0,
                           p3: float = 0.0):
    return batch_local_search(pa, key, slots, rooms_arr, n_rounds,
                              n_candidates, p1, p2, p3)
