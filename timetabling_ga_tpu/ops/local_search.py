"""Batched local search: the TPU redesign of the reference's hot loop.

The reference's `Solution::localSearch` (Solution.cpp:471-769) is a
sequential first-improvement sweep: for each event it tries all 45 target
slots (Move1), all swap partners (Move2), optionally 3-cycles (Move3),
deep-copying the solution per candidate and accepting the first strictly
improving move; its step counter resets on every improvement, and >95% of
program time is spent here (SURVEY section 3.2). Data-dependent loops and
per-candidate allocations cannot map onto XLA.

The redesign (SURVEY section 7.4): per individual, each round proposes K
random candidate moves, evaluates ALL of them with the batched fitness
kernels, and accepts the best candidate if it strictly improves. Rounds
run under `lax.scan` with fixed shapes; `vmap` runs every individual's
search simultaneously, so one TPU dispatch performs P*K candidate
evaluations per round.

The reference's two phases — hcv repair while infeasible
(Solution.cpp:497-618), then scv polish that never re-breaks feasibility
(619-768) — need no explicit gate here: acceptance compares the scalar
penalty `scv if feasible else 1e6+hcv` (Solution.cpp:162-170), whose
ordering makes any hcv reduction dominate while infeasible and makes any
feasibility-breaking move unacceptable once feasible.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from timetabling_ga_tpu.ops import fitness
from timetabling_ga_tpu.ops.moves import random_move
from timetabling_ga_tpu.ops.rooms import capacity_rank


def local_search(pa, key, slots, rooms_arr, n_rounds: int,
                 n_candidates: int = 8,
                 p1: float = 1.0, p2: float = 1.0, p3: float = 0.0):
    """Hill-climb one individual for `n_rounds` fixed-shape rounds.

    Each round: K random moves -> evaluate all -> accept argmin penalty if
    strictly better (the batched analogue of first-improvement with
    counter reset, Solution.cpp:521-527). Returns (slots, rooms).
    """
    cap_rank = capacity_rank(pa)

    def one_round(carry, k):
        s, r, pen = carry
        keys = jax.random.split(k, n_candidates)
        c_slots, c_rooms = jax.vmap(
            lambda kk: random_move(pa, kk, s, r, p1, p2, p3, cap_rank)
        )(keys)                                        # (K, E) each
        c_pen, _, _ = jax.vmap(
            lambda cs, cr: fitness.compute_penalty(pa, cs, cr)
        )(c_slots, c_rooms)                            # (K,)
        best = jnp.argmin(c_pen)
        better = c_pen[best] < pen
        s = jnp.where(better, c_slots[best], s)
        r = jnp.where(better, c_rooms[best], r)
        pen = jnp.where(better, c_pen[best], pen)
        return (s, r, pen), None

    pen0, _, _ = fitness.compute_penalty(pa, slots, rooms_arr)
    keys = jax.random.split(key, n_rounds)
    (slots, rooms_arr, _), _ = lax.scan(
        one_round, (slots, rooms_arr, pen0), keys)
    return slots, rooms_arr


def batch_local_search(pa, key, slots, rooms_arr, n_rounds: int,
                       n_candidates: int = 8,
                       p1: float = 1.0, p2: float = 1.0, p3: float = 0.0):
    """Run `local_search` on a whole population (P, E) simultaneously."""
    P = slots.shape[0]
    keys = jax.random.split(key, P)
    return jax.vmap(
        lambda k, s, r: local_search(pa, k, s, r, n_rounds, n_candidates,
                                     p1, p2, p3)
    )(keys, slots, rooms_arr)


@functools.partial(jax.jit,
                   static_argnames=("n_rounds", "n_candidates"))
def jit_batch_local_search(pa, key, slots, rooms_arr, n_rounds: int,
                           n_candidates: int = 8,
                           p1: float = 1.0, p2: float = 1.0,
                           p3: float = 0.0):
    return batch_local_search(pa, key, slots, rooms_arr, n_rounds,
                              n_candidates, p1, p2, p3)
