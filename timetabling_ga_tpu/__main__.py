"""`python -m timetabling_ga_tpu` == `python -m timetabling_ga_tpu.cli`."""

import sys

from timetabling_ga_tpu.cli import main

sys.exit(main())
