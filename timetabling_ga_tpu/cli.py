"""Command-line entry point.

Usage mirrors the reference binary (`timetabling.ga.uk.2 -i instance.tim
-s 42 -c 4 -p 1`, Control.cpp:3-176) plus the TPU extensions:

    python -m timetabling_ga_tpu.cli -i comp01.tim -s 42 -p 1 \
        --islands 8 --pop-size 128 --generations 2001

Output is the reference's JSONL protocol on stdout (or -o <file>).

`serve` subcommand — the multi-tenant solver service (README
"Serving"; timetabling_ga_tpu/serve): line-JSON solve requests in,
job-tagged JSONL records out:

    python -m timetabling_ga_tpu.cli serve --lanes 4 --quantum 25 \
        -i requests.jsonl -o records.jsonl

`trace` / `stats` subcommands — offline observability (README
"Observability"; timetabling_ga_tpu/obs). Device-free: they read a
JSONL record stream, never a device.

    python -m timetabling_ga_tpu.cli trace run.jsonl -o trace.json
        export spanEntry/phase/metricsEntry records as Chrome
        trace-event JSON (Perfetto / chrome://tracing), with flow
        arrows connecting causal chains across thread lanes
    python -m timetabling_ga_tpu.cli trace --job j42 serve.jsonl
        one serve job's end-to-end timeline (admit -> pack -> quantum
        -> park -> resume), co-tenant noise filtered out
    python -m timetabling_ga_tpu.cli trace --job j42 \
            gateway.jsonl tt-fleet-r0.jsonl tt-fleet-r1.jsonl
        several logs stitch into ONE timeline: a process lane per log
        and flow arrows crossing the process boundary — a routed
        job's gateway leg (route/submit/settle) connected to its
        replica solve leg by the X-TT-Flow chain (tt-obs v5)
    python -m timetabling_ga_tpu.cli stats run.jsonl [more.jsonl ...]
        summarize: best-so-far curves, recoveries, per-job latency
        (for serve logs: queued/routed/packed/executing/parked
        breakdown; for gateway logs: the routeEntry placement summary)
    python -m timetabling_ga_tpu.cli quality run.jsonl
        summarize the search-quality telemetry (--quality runs):
        diversity trend, operator hit rates, migration gain, stalls
    python -m timetabling_ga_tpu.cli usage serve.jsonl [more.jsonl]
    python -m timetabling_ga_tpu.cli usage http://127.0.0.1:8070
        per-tenant / per-job usage report (tt-meter, README "Usage
        metering"): who consumed the fleet — device seconds, FLOPs,
        queue/park wall, compile amortization — from usageEntry logs
        or a live replica/gateway /v1/usage endpoint (the gateway
        aggregates fleet-wide, dead replicas' ledgers included)
    python -m timetabling_ga_tpu.cli scale gateway.jsonl
        render the tt-scale autoscaler's decision log (README
        "Autoscaling"): every spawn/retire/blocked decision with the
        sustained-window evidence that justified it
    python -m timetabling_ga_tpu.cli incident ./incidents [--job ID]
        summarize the flight recorder's bundles (--incident-dir) and
        render the newest — a stitched gateway bundle renders the
        cross-process gateway+replica timeline — as Perfetto JSON;
        `tt trace` also accepts bundle files next to JSONL logs

`profile` subcommand — the cost observatory's on-demand capture
trigger (README "Cost observatory"; obs/cost.py): ask a live run or
serve process (its `--obs-listen` front) to record a jax.profiler
trace of its next N dispatches into its `--profile-dir`.

    python -m timetabling_ga_tpu.cli profile 127.0.0.1:9100 --for 5

`hotspots` subcommand — phase-level device-time attribution (README
"Phase profiler (tt-prof)"; obs/prof.py): walk a jax.profiler capture
directory (or the profEntry records of a run's JSONL log), bucket
device-op durations by their tt.* named_scope phase, and print a
ranked phase/op table; `--diff A B` prints per-phase deltas between
two captures.

    python -m timetabling_ga_tpu.cli hotspots /tmp/prof-dir
    python -m timetabling_ga_tpu.cli hotspots --diff before/ after/

`fleet` / `submit` subcommands — the N-replica serving front (README
"Fleet"; timetabling_ga_tpu/fleet): a gateway HTTP API with a
bucket-affine router over replicas (`tt serve --http` workers), and
the stdlib client that submits one instance and waits.

    python -m timetabling_ga_tpu.cli fleet --listen 127.0.0.1:8070 \
        -o gateway.jsonl --slo-p99 30 --spawn 2 -- --backend cpu \
        --lanes 4
    python -m timetabling_ga_tpu.cli submit http://127.0.0.1:8070 \
        comp01.tim -s 42 --generations 200 --records-out job.jsonl
"""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "serve":
        # deferred import: the single-run path must not pay the serve
        # subsystem's import, and vice versa
        from timetabling_ga_tpu.serve.service import main_serve
        return main_serve(argv[1:])
    if argv and argv[0] == "trace":
        # deferred + jax-free: log exporting must work on any machine
        # the log was copied to (obs/trace_export.py docstring)
        from timetabling_ga_tpu.obs.trace_export import main_trace
        return main_trace(argv[1:])
    if argv and argv[0] == "stats":
        from timetabling_ga_tpu.obs.logstats import main_stats
        return main_stats(argv[1:])
    if argv and argv[0] == "quality":
        # deferred + jax-free like trace/stats: summarize a record
        # stream's qualityEntry search telemetry (obs/quality.py,
        # README "Search-quality observatory")
        from timetabling_ga_tpu.obs.quality import main_quality
        return main_quality(argv[1:])
    if argv and argv[0] == "incident":
        # deferred + jax-free like trace/stats: summarize/render the
        # flight recorder's incident bundles (obs/flight.py, README
        # "Flight recorder & history") — a stitched gateway bundle
        # renders the cross-process Perfetto timeline
        from timetabling_ga_tpu.obs.flight import main_incident
        return main_incident(argv[1:])
    if argv and argv[0] == "usage":
        # deferred + jax-free like trace/stats: per-tenant / per-job
        # usage report from usageEntry logs or a live /v1/usage
        # endpoint (tt-meter, obs/usage.py, README "Usage metering")
        from timetabling_ga_tpu.obs.usage import main_usage
        return main_usage(argv[1:])
    if argv and argv[0] == "profile":
        # deferred + jax-free like trace/stats: `tt profile` is a
        # stdlib HTTP client asking a LIVE run's --obs-listen front to
        # capture its next N dispatches (obs/cost.py ProfileCapture)
        from timetabling_ga_tpu.obs.cost import main_profile
        return main_profile(argv[1:])
    if argv and argv[0] == "hotspots":
        # deferred + jax-free like trace/stats: rank device time by
        # tt.* phase from a profiler capture dir (or a log's profEntry
        # records) and diff two captures (obs/prof.py, README "Phase
        # profiler")
        from timetabling_ga_tpu.obs.prof import main_hotspots
        return main_hotspots(argv[1:])
    if argv and argv[0] == "scale":
        # deferred + jax-free like trace/stats: render the tt-scale
        # autoscaler's decision log (scaleEntry records with their
        # sustained-window evidence — fleet/autoscaler.py, README
        # "Autoscaling")
        from timetabling_ga_tpu.fleet.autoscaler import main_scale
        return main_scale(argv[1:])
    if argv and argv[0] == "fleet":
        # the fleet gateway (README "Fleet"; timetabling_ga_tpu/fleet):
        # HTTP solve front + bucket-affine router over N replicas —
        # the gateway process routes, it never solves
        from timetabling_ga_tpu.fleet.gateway import main_fleet
        return main_fleet(argv[1:])
    if argv and argv[0] == "submit":
        # stdlib HTTP solve client against a gateway or replica front
        from timetabling_ga_tpu.fleet.client import main_submit
        return main_submit(argv[1:])
    # runtime imports deferred past the subcommand dispatch (and the
    # package __init__ is PEP 562-lazy): `tt trace`/`tt stats` must
    # work without importing jax (the log may be on a machine with no
    # accelerator stack at all)
    from timetabling_ga_tpu.runtime import parse_args
    cfg = parse_args(argv)
    from timetabling_ga_tpu.runtime.engine import precompile, run
    # compile-then-run, like the reference binary (mpicxx compiles
    # before anyone races it): XLA compilation happens BEFORE the per-
    # try clock starts, so -t bounds solve time, not compile time — a
    # cold CLI run otherwise spends several times its budget compiling
    # inside it. Also seeds the sec/gen estimates the budget-aware
    # dispatch sizing needs on its very first dispatch. --no-precompile
    # skips the probe dispatches (ADVICE round 4) at the cost of
    # compiling inside -t.
    if cfg.precompile:
        precompile(cfg)
    from timetabling_ga_tpu.runtime import control_channel
    try:
        run(cfg)
    except control_channel.PeerLost as e:
        # A peer process died mid-run. The abort faultEntry and the
        # final checkpoint are already durable (engine's PeerLost
        # path flushes before re-raising); what remains CANNOT be
        # done cleanly: the dead peer's collective never completes,
        # so the XLA execution thread is parked forever and
        # jax.distributed's atexit shutdown barrier would wait on
        # the missing process indefinitely. Skip interpreter
        # teardown entirely — a hard exit is the only exit.
        import os as _os
        print(f"tt: aborting run: {e}", file=sys.stderr)
        sys.stderr.flush()
        sys.stdout.flush()
        _os._exit(70)   # EX_SOFTWARE: abnormal, deliberate
    return 0


if __name__ == "__main__":
    sys.exit(main())
