"""TT608 — fleet actuation off the scaler thread.

The tt-scale contract (fleet/autoscaler.py): the autoscaler's control
loop is the ONLY legal actuation site for replica-count mutation.
Actuator calls — spawning workers (`spawn_one` / `spawn_local` /
`subprocess.Popen` / a handle's `respawn`), retiring them
(`preempt_replica` / `retire_replica` / `terminate`), adopting them
(`adopt_replica`), or grabbing ports (`free_port`) — are banned in two
places:

  - ON HTTP HANDLER PATHS (TT602's `_reachable` walk, including the
    configured `*Api` roots): a handler that spawns or preempts turns
    request traffic into process churn — any client (or scrape storm)
    could resize the fleet, bypassing the policy's sustained-window
    evidence, cooldown hysteresis, and warmth guard entirely. Handlers
    ENQUEUE; the decision belongs to the scaler.
  - INSIDE DISPATCHER-TICK BODIES (`scale-tick-pattern` function
    names — the gateway's `_dispatch_loop`/`_handle`/`_poll*`/
    `_tick*`/`_drain_tick` family): a spawn is seconds of process
    launch and a preempt is an HTTP round trip with policy
    consequences; on the ONE dispatcher thread either stalls routing,
    polling, and failover (the `dispatcher_stalled` watchdog's exact
    failure class) and actuates without the policy's guards. The
    dispatcher executes the preempt COMMAND the scaler enqueued
    (`handle.drain(mode=...)`) — it never originates scale decisions.

Scope: the configured fleet modules (`fleet-modules` in pyproject —
the gateway/replica/router layer, where both handler paths and the
dispatcher live). fleet/autoscaler.py itself is exempt — it IS the
sanctioned actuation site.
"""

from __future__ import annotations

import ast
import re

from timetabling_ga_tpu.analysis.core import (
    Finding, qual_matches, qualname)
from timetabling_ga_tpu.analysis.rules_http import _reachable

RULE = "TT608"

# attribute-call actuators: replica-count / process mutation verbs on
# any receiver (a gateway, a ReplicaSet, a handle)
_ACTUATOR_ATTRS = {"preempt_replica", "retire_replica",
                   "adopt_replica", "spawn_one", "spawn_local",
                   "respawn", "terminate"}

# qualified/bare-name actuators: process and port mutation
_ACTUATOR_CALLEES = {"subprocess.Popen", "Popen", "spawn_one",
                     "spawn_local", "free_port"}

_EXEMPT_SUFFIXES = ("fleet/autoscaler.py",)


def _in_scope(path: str, ctx) -> bool:
    rel = path.replace("\\", "/")
    modules = getattr(ctx.config, "fleet_modules", ["fleet/"])
    return any(m in rel for m in modules)


def _actuator(node: ast.Call) -> str | None:
    """The actuator callee's display name, or None."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _ACTUATOR_ATTRS:
        qn = qualname(f)
        return qn if qn is not None else f.attr
    qn = qualname(f)
    if qual_matches(qn, _ACTUATOR_CALLEES):
        return qn
    return None


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    norm = path.replace("\\", "/")
    if norm.endswith(_EXEMPT_SUFFIXES) or not _in_scope(path, ctx):
        return []
    findings: list[Finding] = []
    # half 1: handler-reachable paths (incl. the *Api roots) — an
    # actuator there lets request traffic resize the fleet
    suffixes = tuple(getattr(ctx.config, "handler_api_suffixes",
                             ("Api",)))
    for where, fn in _reachable(tree, suffixes):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _actuator(node)
            if name is not None:
                findings.append(Finding(
                    RULE, path, node.lineno, node.col_offset,
                    f"fleet actuator call `{name}(...)` on the HTTP "
                    f"handler path `{where}` — spawning, preempting, "
                    f"or adopting replicas from a handler bypasses "
                    f"the autoscaler's evidence/cooldown/warmth "
                    f"policy and turns request traffic into process "
                    f"churn; handlers enqueue, the tt-scale scaler "
                    f"thread actuates (fleet/autoscaler.py, TT608)"))
    # half 2: dispatcher-tick bodies — the one dispatcher thread must
    # execute enqueued commands, never originate actuation
    tick_re = re.compile(getattr(
        ctx.config, "scale_tick_pattern",
        r"^_dispatch_loop$|^_handle$|^_poll|^_tick|^_drain_tick$"))
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        if not tick_re.search(node.name):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            name = _actuator(sub)
            if name is not None:
                findings.append(Finding(
                    RULE, path, sub.lineno, sub.col_offset,
                    f"fleet actuator call `{name}(...)` inside the "
                    f"dispatcher-tick body `{node.name}` — a spawn "
                    f"or preempt on the one dispatcher thread stalls "
                    f"routing/polling/failover and actuates without "
                    f"the policy's guards; the tt-scale scaler "
                    f"thread is the only legal actuation site "
                    f"(fleet/autoscaler.py, TT608)"))
    # a call can be both handler- and tick-reachable at one line;
    # dedupe by (line, col) like TT606/TT607
    seen: set = set()
    out = []
    for f in findings:
        k = (f.line, f.col)
        if k in seen:
            continue
        seen.add(k)
        out.append(f)
    return out
