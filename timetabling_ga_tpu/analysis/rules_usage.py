"""TT607 — usage-ledger mutation and wall-clock metering off its home
threads.

The tt-meter contract (obs/usage.py) mirrors the flight recorder's:

  - THE LEDGER IS FED FROM THE DRIVE LOOP AND FOLDED ON ITS OWN
    THREAD. A ledger mutation (`.job()` / `.dispatch()` / `.final()`
    / `.close()`) inside a TRACE TARGET executes at trace time — the
    meter would count the compile once and nothing ever after, while
    baking a python object into the program — and on an HTTP HANDLER
    path it couples billing truth to scrape traffic: a poller that
    bumps the meter turns monitoring into revenue (the TT602
    registry-mutation hazard, with money attached). Handlers READ the
    ledger (`totals()`, a job's `usage` dict); only the scheduler's
    park fence feeds it.
  - METERING TIMESTAMPS BELONG TO THE DRIVE LOOP. A wall-clock read
    (`time.monotonic()` and friends) on a handler path means someone
    is measuring usage where requests land, not where work retires —
    numbers from the wrong clock domain that drift from the fence
    components the ledger conserves. (Clocks inside trace targets are
    TT601's finding; this rule covers the handler half so the two
    compose without double-reporting.)

Scope: ledger mutations in trace targets (TT101's collection)
module-wide AND on handler-reachable paths (TT602's `_reachable` walk,
including the configured `*Api` roots); wall-clock reads on the
handler paths only. obs/usage.py itself is exempt — it IS the
sanctioned ledger-thread home.
"""

from __future__ import annotations

import ast
import re

from timetabling_ga_tpu.analysis.core import Finding, qualname, qual_matches
from timetabling_ga_tpu.analysis.rules_http import _reachable
from timetabling_ga_tpu.analysis.rules_obs import _CLOCK_CALLEES
from timetabling_ga_tpu.analysis.rules_trace import _collect_targets

RULE = "TT607"

# receiver shapes that ARE the usage ledger: `usage`, `self._usage`,
# `svc.usage`, `ledger`, `usage_ledger`, ...
_LEDGER_RECV = re.compile(r"(^|\.)_?(usage|ledger|usage_ledger)$",
                          re.IGNORECASE)

# the ledger's mutating surface (obs/usage.py UsageLedger): reads —
# totals() / alive() — stay allowed everywhere
_LEDGER_MUTATORS = {"job", "dispatch", "final", "close", "drain",
                    "poll_once"}

# the sanctioned ledger home (and the metrics module its counters
# live in, already exempt from TT602's walk)
_EXEMPT_SUFFIXES = ("obs/usage.py",)


def _ledger_mutation(node: ast.Call):
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr in _LEDGER_MUTATORS):
        return None
    qn = qualname(f.value)
    if qn is not None and _LEDGER_RECV.search(qn):
        return qn
    return None


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    if path.replace("\\", "/").endswith(_EXEMPT_SUFFIXES):
        return []
    findings: list[Finding] = []
    # half 1: ledger mutations inside trace targets, module-wide
    for fn in _collect_targets(tree):
        name = getattr(fn, "name", "<lambda>")
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            qn = _ledger_mutation(node)
            if qn is not None:
                findings.append(Finding(
                    RULE, path, node.lineno, node.col_offset,
                    f"usage-ledger mutation `{qn}.{node.func.attr}"
                    f"(...)` inside jit/vmap/shard_map target `{name}`"
                    f" — executes at TRACE time (the meter counts the "
                    f"compile once and nothing after); metering feeds "
                    f"from the scheduler's park fence on the host "
                    f"(obs/usage.py design rules)"))
    # half 2: handler paths (TT602's reachability walk incl. *Api
    # roots) — no ledger mutation, no wall-clock metering
    suffixes = tuple(getattr(ctx.config, "handler_api_suffixes",
                             ("Api",)))
    for where, fn in _reachable(tree, suffixes):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            qn = _ledger_mutation(node)
            if qn is not None:
                findings.append(Finding(
                    RULE, path, node.lineno, node.col_offset,
                    f"usage-ledger mutation `{qn}.{node.func.attr}"
                    f"(...)` on the HTTP handler path `{where}` — "
                    f"handlers READ the meter (totals(), a job's "
                    f"usage dict); a scrape that bumps it turns "
                    f"monitoring traffic into billed capacity "
                    f"(obs/usage.py design rules)"))
                continue
            if qual_matches(qualname(node.func), _CLOCK_CALLEES):
                findings.append(Finding(
                    RULE, path, node.lineno, node.col_offset,
                    f"wall-clock read "
                    f"`{qualname(node.func)}` on the HTTP handler "
                    f"path `{where}` — metering timestamps belong to "
                    f"the drive loop's fence brackets; a handler-side "
                    f"clock meters where requests land, not where "
                    f"work retires (obs/usage.py design rules)"))
    # a call can be both trace-target- and handler-reachable at one
    # line; dedupe by (line, col) like TT603/TT606
    seen: set = set()
    out = []
    for f in findings:
        k = (f.line, f.col)
        if k in seen:
            continue
        seen.add(k)
        out.append(f)
    return out
