"""TT602 — blocking I/O / registry mutation in HTTP handler paths.

The tt-obs pull front (obs/http.py) has one contract: a scrape is a
PURE OBSERVER of the run it lands on. Its handlers only read registry
snapshots/expositions and only write their own response socket. Two
classes of code break that:

  - MetricsRegistry mutation — counter bumps, gauge writes, histogram
    observes, and the get-or-create accessors themselves (`counter()` /
    `gauge()` / `gauge_fn()` / `histogram()` CREATE an instrument when
    the name is new). A scrape that mutates the registry changes the
    numbers every other consumer (metricsEntry snapshots, `tt serve`
    stats, the next scrape) reads, and a scrape storm contends the one
    registry lock the dispatch path takes for its own updates.
  - blocking I/O beyond the response socket — `open()`, `time.sleep`,
    subprocess spawns, outbound sockets/HTTP. Handler threads are
    daemons the server never joins; a handler that blocks on foreign
    I/O turns "the listener can never stall the run" from a design
    rule into a hope.

Scope: classes that look like HTTP handlers — a base named
`*HTTPRequestHandler`, or any `do_*` method (the `http.server` routing
convention, so duck-typed handlers are covered too) — plus everything
reachable from their methods within the module (`self.helper()` calls
and bare-name calls to module functions), PLUS classes whose name ends
with a configured `handler-api-suffixes` entry (default `Api`): the
fleet fronts route every request into an enqueue-or-read-only `api`
object (`self.server.api.accept_solve(...)` — fleet/gateway.py
GatewayApi, fleet/replicas.py ReplicaApi), whose methods run ON the
handler thread but in a class the do_* heuristic cannot see, often in
a different module from the handler. Cross-module calls are otherwise
out of scope: the rule guards the handler modules themselves, and the
registry's own module is exempt (it IS the lock-holding implementation
the rule keeps handlers out of).

Reads stay allowed: `snapshot()`, `to_prometheus()`,
`to_openmetrics()`, and `self.wfile.write(...)` are exactly what a
handler is for.
"""

from __future__ import annotations

import ast
import re

from timetabling_ga_tpu.analysis.core import Finding, qual_matches, qualname

RULE = "TT602"

# receiver shapes that mean "the metrics registry": REGISTRY,
# obs_metrics.REGISTRY, self.server.registry, self._metrics, ...
_REGISTRY_RECV = re.compile(r"(^|\.)_?(registry|metrics)$", re.IGNORECASE)

# get-or-create accessors and direct registry mutators: every one of
# these writes registry state (accessors create instruments)
_REGISTRY_MUTATORS = {"counter", "gauge", "gauge_fn", "histogram",
                      "freeze", "reset"}

# blocking calls a handler thread must not make (tail-matched)
_BLOCKING_CALLEES = {
    "time.sleep", "sleep",
    "subprocess.run", "subprocess.Popen", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "os.system", "os.popen", "os.wait", "os.waitpid",
    "socket.create_connection",
    "urllib.request.urlopen", "urlopen",
    "requests.get", "requests.post", "requests.request",
}

# modules exempt from the scan: the registry implementation itself
# (its methods legitimately touch instruments under the lock)
_EXEMPT_SUFFIXES = ("obs/metrics.py",)


def _is_handler_class(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        qn = qualname(base)
        if qn is not None and qn.split(".")[-1].endswith(
                "HTTPRequestHandler"):
            return True
    return any(isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
               and n.name.startswith("do_") for n in cls.body)


def _reachable(tree: ast.Module, api_suffixes: tuple = ()
               ) -> list[tuple[str, ast.AST]]:
    """Handler-reachable function bodies: every method of a handler
    class — and of any class named `*<api_suffix>` (the fleet fronts'
    enqueue-or-read-only api objects, called as `self.server.api.x()`
    from handler threads) — plus (transitively, intra-module)
    same-class methods called as `self.x(...)` and module functions
    called by bare name."""
    mod_funcs = {n.name: n for n in tree.body
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    work: list[tuple[str, str, ast.AST]] = []   # (owner, name, node)
    classes: dict[str, dict[str, ast.AST]] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        methods = {n.name: n for n in node.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        classes[node.name] = methods
        if _is_handler_class(node) or any(
                node.name.endswith(sfx) for sfx in api_suffixes
                if sfx):
            for name, fn in methods.items():
                work.append((node.name, name, fn))
    seen: set[tuple[str, str]] = {(o, n) for o, n, _ in work}
    out: list[tuple[str, ast.AST]] = []
    while work:
        owner, name, fn = work.pop()
        out.append((f"{owner}.{name}" if owner else name, fn))
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self" and owner
                    and f.attr in classes.get(owner, {})):
                key = (owner, f.attr)
                if key not in seen:
                    seen.add(key)
                    work.append((owner, f.attr,
                                 classes[owner][f.attr]))
            elif isinstance(f, ast.Name) and f.id in mod_funcs:
                key = ("", f.id)
                if key not in seen:
                    seen.add(key)
                    work.append(("", f.id, mod_funcs[f.id]))
    return out


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    if path.replace("\\", "/").endswith(_EXEMPT_SUFFIXES):
        return []
    suffixes = tuple(getattr(ctx.config, "handler_api_suffixes",
                             ("Api",)))
    findings: list[Finding] = []
    for where, fn in _reachable(tree, suffixes):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and f.attr in _REGISTRY_MUTATORS
                    and (qn_recv := qualname(f.value)) is not None
                    and _REGISTRY_RECV.search(qn_recv)):
                findings.append(Finding(
                    RULE, path, node.lineno, node.col_offset,
                    f"registry write `{qn_recv}.{f.attr}(...)` on the "
                    f"HTTP handler path `{where}` — handlers must only "
                    f"READ snapshots/expositions: get-or-create and "
                    f"mutation change the numbers every other consumer "
                    f"reads and contend the dispatch path's registry "
                    f"lock (obs/http.py design rules)"))
                continue
            qn = qualname(f)
            if qual_matches(qn, _BLOCKING_CALLEES) or (
                    isinstance(f, ast.Name) and f.id == "open"):
                findings.append(Finding(
                    RULE, path, node.lineno, node.col_offset,
                    f"blocking call `{qn or 'open'}` on the HTTP "
                    f"handler path `{where}` — handlers may only block "
                    f"on their own response socket; foreign I/O on a "
                    f"scrape thread is how a listener learns to stall "
                    f"the run it observes (obs/http.py design rules)"))
    return findings
