"""TT501 — pinned JAX API surface.

Every `import jax...` in the package must be declared in the
compatibility table (`JAX_COMPAT_TABLE` in timetabling_ga_tpu/compat.py
by default): the table is the set of JAX symbols known to exist on every
JAX version we support. An import of an undeclared symbol is exactly how
`from jax import shard_map` (a 0.6+ export) broke the whole suite on the
installed JAX 0.4.37 — this rule fails that at lint time instead.

Imports inside a `try:` whose handler catches ImportError are exempt:
that is the sanctioned version-tolerance idiom (see compat.py), where a
missing symbol is handled, not fatal.
"""

from __future__ import annotations

import ast

from timetabling_ga_tpu.analysis.core import Finding, qualname

RULE = "TT501"

_IMPORT_ERRORS = {"ImportError", "ModuleNotFoundError", "Exception",
                  "BaseException"}


def _guarded_lines(tree: ast.Module) -> set[int]:
    """Line numbers inside try/except-ImportError bodies and their
    handlers (the whole construct is version-tolerant by design)."""
    lines: set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        catches_import = False
        for h in node.handlers:
            types = []
            if h.type is None:
                catches_import = True
            elif isinstance(h.type, ast.Tuple):
                types = h.type.elts
            else:
                types = [h.type]
            for t in types:
                qn = qualname(t)
                if qn and qn.rsplit(".", 1)[-1] in _IMPORT_ERRORS:
                    catches_import = True
        if not catches_import:
            continue
        for part in ([node.body] + [h.body for h in node.handlers]
                     + [node.orelse]):
            for st in part:
                lines.update(range(st.lineno,
                                   (st.end_lineno or st.lineno) + 1))
    return lines


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    table = ctx.compat_table
    if not table:
        return []
    findings: list[Finding] = []
    guarded = _guarded_lines(tree)

    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                mod = alias.name
                if mod != "jax" and not mod.startswith("jax."):
                    continue
                if node.lineno in guarded:
                    continue
                if mod not in table:
                    findings.append(Finding(
                        RULE, path, node.lineno, node.col_offset,
                        f"`import {mod}` is outside the pinned JAX API "
                        f"surface — declare it in JAX_COMPAT_TABLE "
                        f"(compat.py) or resolve it through compat"))
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if node.level or (mod != "jax" and not mod.startswith("jax.")):
                continue
            if node.lineno in guarded:
                continue
            allowed = table.get(mod)
            for alias in node.names:
                if allowed is not None and (
                        "*" in allowed or alias.name in allowed):
                    continue
                findings.append(Finding(
                    RULE, path, node.lineno, node.col_offset,
                    f"`from {mod} import {alias.name}` is outside the "
                    f"pinned JAX API surface — not every supported JAX "
                    f"version exports it; declare it in JAX_COMPAT_TABLE "
                    f"or add a guarded resolver in compat.py"))
    return findings
