"""TT501/TT502 — pinned JAX API surface.

TT501: every `import jax...` in the package must be declared in the
compatibility table (`JAX_COMPAT_TABLE` in timetabling_ga_tpu/compat.py
by default): the table is the set of JAX symbols known to exist on every
JAX version we support. An import of an undeclared symbol is exactly how
`from jax import shard_map` (a 0.6+ export) broke the whole suite on the
installed JAX 0.4.37 — this rule fails that at lint time instead.

TT502: the same pinning for ATTRIBUTE access. `jax.profiler.start_trace`
and `jax.distributed.initialize` never appear in an import statement, so
they bypass TT501 entirely — yet an attribute that a supported JAX
version does not export fails at exactly the same place an undeclared
import does, just later (first call instead of import time). Every
maximal `jax.a.b...` attribute chain must resolve through the table:
the longest table-key module prefix is found, and the next component
must be in that entry's allowed list ("*" = anything). Chains are only
checked in files that actually bind the name via `import jax` (aliases
included), so unrelated locals named `jax` never fire.

Constructs inside a `try:` whose handler catches ImportError (TT501) or
ImportError/AttributeError (TT502) are exempt: those are the sanctioned
version-tolerance idioms (see compat.py), where a missing symbol is
handled, not fatal. `getattr(jax, "name", default)` probing is
naturally exempt — it is not an attribute chain.
"""

from __future__ import annotations

import ast

from timetabling_ga_tpu.analysis.core import Finding, qualname

RULE = "TT501"
RULE_ATTR = "TT502"

_IMPORT_ERRORS = {"ImportError", "ModuleNotFoundError", "Exception",
                  "BaseException"}
_ATTR_ERRORS = _IMPORT_ERRORS | {"AttributeError"}


def _guarded_lines(tree: ast.Module,
                   error_names: set[str] = _IMPORT_ERRORS) -> set[int]:
    """Line numbers inside try/except bodies whose handlers catch one
    of `error_names` (the whole construct is version-tolerant by
    design)."""
    lines: set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        catches_import = False
        for h in node.handlers:
            types = []
            if h.type is None:
                catches_import = True
            elif isinstance(h.type, ast.Tuple):
                types = h.type.elts
            else:
                types = [h.type]
            for t in types:
                qn = qualname(t)
                if qn and qn.rsplit(".", 1)[-1] in error_names:
                    catches_import = True
        if not catches_import:
            continue
        for part in ([node.body] + [h.body for h in node.handlers]
                     + [node.orelse]):
            for st in part:
                lines.update(range(st.lineno,
                                   (st.end_lineno or st.lineno) + 1))
    return lines


def _jax_aliases(tree: ast.Module) -> set[str]:
    """Names the module binds to the `jax` package itself."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax":
                    names.add(alias.asname or "jax")
    return names


def _check_attrs(tree: ast.Module, src: str, path: str, ctx
                 ) -> list[Finding]:
    """TT502: maximal jax-rooted attribute chains vs the table."""
    table = ctx.compat_table
    aliases = _jax_aliases(tree)
    if not table or not aliases:
        return []
    guarded = _guarded_lines(tree, _ATTR_ERRORS)
    # attribute nodes that are the `.value` of another attribute are
    # sub-chains; only the maximal chain is checked (one finding per
    # use, anchored at its full dotted path)
    sub_chains = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Attribute)):
            sub_chains.add(id(node.value))

    findings: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Attribute) or id(node) in sub_chains:
            continue
        qn = qualname(node)
        if qn is None:
            continue
        root = qn.split(".", 1)[0]
        if root not in aliases:
            continue
        q = "jax" + qn[len(root):]
        if node.lineno in guarded:
            continue
        parts = q.split(".")
        for i in range(len(parts), 0, -1):
            prefix = ".".join(parts[:i])
            allowed = table.get(prefix)
            if allowed is None:
                continue
            nxt = parts[i] if i < len(parts) else None
            if not (nxt is None or "*" in allowed or nxt in allowed):
                findings.append(Finding(
                    RULE_ATTR, path, node.lineno, node.col_offset,
                    f"`{q}` is outside the pinned JAX API surface — "
                    f"`{nxt}` is not declared under `{prefix}` in "
                    f"JAX_COMPAT_TABLE (compat.py); declare it or "
                    f"resolve it through compat"))
            break
    return findings


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    table = ctx.compat_table
    if not table:
        return []
    findings: list[Finding] = list(_check_attrs(tree, src, path, ctx))
    guarded = _guarded_lines(tree)

    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                mod = alias.name
                if mod != "jax" and not mod.startswith("jax."):
                    continue
                if node.lineno in guarded:
                    continue
                if mod not in table:
                    findings.append(Finding(
                        RULE, path, node.lineno, node.col_offset,
                        f"`import {mod}` is outside the pinned JAX API "
                        f"surface — declare it in JAX_COMPAT_TABLE "
                        f"(compat.py) or resolve it through compat"))
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if node.level or (mod != "jax" and not mod.startswith("jax.")):
                continue
            if node.lineno in guarded:
                continue
            allowed = table.get(mod)
            for alias in node.names:
                if allowed is not None and (
                        "*" in allowed or alias.name in allowed):
                    continue
                findings.append(Finding(
                    RULE, path, node.lineno, node.col_offset,
                    f"`from {mod} import {alias.name}` is outside the "
                    f"pinned JAX API surface — not every supported JAX "
                    f"version exports it; declare it in JAX_COMPAT_TABLE "
                    f"or add a guarded resolver in compat.py"))
    return findings
