"""Analyzer configuration: `[tool.tt-analyze]` in pyproject.toml plus
the pinned JAX compatibility table (extracted from compat.py by AST, so
the analyzer never has to import JAX).

Python 3.10 has no tomllib; we fall back to tomli when present and to a
minimal line parser (enough for our own table-free key = value / list
entries) when neither library exists — the analyzer must never be the
thing that breaks on a missing dependency.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

ALL_RULES = ("TT101", "TT102", "TT201", "TT202", "TT203", "TT301",
             "TT302", "TT303", "TT304", "TT305", "TT306", "TT307",
             "TT309", "TT310", "TT401", "TT402", "TT501", "TT502",
             "TT601", "TT602", "TT603", "TT604", "TT605", "TT606",
             "TT607", "TT608")


@dataclasses.dataclass
class AnalyzerConfig:
    # default scan roots when the CLI gives no paths
    paths: list[str] = dataclasses.field(
        default_factory=lambda: ["timetabling_ga_tpu"])
    rules: list[str] = dataclasses.field(
        default_factory=lambda: list(ALL_RULES))
    # module (file) holding JAX_COMPAT_TABLE for TT501
    compat_table: str = "timetabling_ga_tpu/compat.py"
    # files whose host loops TT301 audits (path suffix match)
    dispatch_modules: list[str] = dataclasses.field(
        default_factory=lambda: ["runtime/engine.py", "parallel/islands.py"])
    # sanctioned device->host fetch helpers: calls to these are THE sync
    # points, and their bodies are exempt
    sync_helpers: list[str] = dataclasses.field(
        default_factory=lambda: ["_fetch", "_fetch_final"])
    # paths (substring match) whose code executes inside shard_map
    # bodies — TT302 bans collective-bearing random ops there
    sharded_modules: list[str] = dataclasses.field(
        default_factory=lambda: ["ops/", "parallel/"])
    # callee patterns whose results are compiled programs (calling one
    # yields device arrays) for TT301's taint seeding
    device_producers: list[str] = dataclasses.field(
        default_factory=lambda: [r"^cached_\w+$", r"^jax\.jit$", r"^jit$"])
    # factory-name patterns seeding the WHOLE-PROGRAM taint pass
    # (TT303/TT304/TT305, analysis/project.py): a function matching one
    # returns a compiled dispatch program, and calling that program in
    # ANY module yields device-tainted values
    taint_sources: list[str] = dataclasses.field(
        default_factory=lambda: [r"^cached_\w+$", r"^make_\w+_runner$"])
    # host-forcing sink callables TT303 flags on tainted values inside
    # dispatch loops (method names match `.x()` receivers)
    taint_sinks: list[str] = dataclasses.field(
        default_factory=lambda: ["float", "int", "bool", "np.asarray",
                                 "np.array", "item", "tolist"])
    # files (path suffix match) forming the tt-accord control side
    # channel: TT307 bans device collectives and multihost_utils.*
    # there wholesale (recovery/agreement code must never ride the
    # possibly-poisoned collective program), alongside the
    # *Supervisor-class scope the rule applies everywhere
    accord_modules: list[str] = dataclasses.field(
        default_factory=lambda: ["runtime/control_channel.py"])
    # attribute names holding device-RESIDENT group state (TT306: a
    # host fetch rooted in one of these stores may only happen inside
    # a fence helper — serve/scheduler.py RESIDENCY)
    resident_stores: list[str] = dataclasses.field(
        default_factory=lambda: ["_resident"])
    # park-fence helper function names whose bodies are the SANCTIONED
    # host-fetch sites for resident-group state (exempt from TT306):
    # the flush path, where snapshot/ship units re-sync
    fence_helpers: list[str] = dataclasses.field(
        default_factory=lambda: ["_flush_bucket", "_flush_job",
                                 "flush_resident"])
    # report stale `# tt-analyze: ignore[...]` markers (CLI
    # --warn-unused-ignores sets this)
    warn_unused_ignores: bool = False
    # module-level compile-cache dict names for TT202
    cache_name_pattern: str = r"^_?[A-Z0-9_]*CACHES?$"
    # factory callees whose results get cached (TT202 key completeness)
    factory_pattern: str = r"^(make_\w+|jit)$"
    # parameter names treated as PRNG keys by TT401
    rng_param_pattern: str = r"(^key$|^rng(_key)?$|_key$|^key_|^k_[a-z]$)"
    # callees that may receive a key without consuming randomness
    # (checkpointing, serialization)
    rng_exempt_callees: list[str] = dataclasses.field(
        default_factory=lambda: ["save", "key_data", "log_entry"])
    # population-evaluation callees TT604 flags inside dispatch-loop
    # bodies (host-side per-generation quality recompute)
    quality_recompute_callees: list[str] = dataclasses.field(
        default_factory=lambda: ["batch_penalty", "evaluate",
                                 "event_heat"])
    # function-name pattern marking quality-reduction helpers (TT604
    # bans collectives and collective-bearing random ops inside them)
    quality_path_pattern: str = r"quality|hamming|div_stats|div_rows"
    # modules (path substring match) whose handler-reachable code
    # TT605 audits for inline device work and unbounded socket reads
    fleet_modules: list[str] = dataclasses.field(
        default_factory=lambda: ["fleet/"])
    # class-name suffixes treated as handler-path ROOTS by the
    # TT602/TT605 reachability walk, in addition to handler classes
    # themselves: the fleet fronts route every request into an
    # enqueue-or-read-only `api` object (GatewayApi / ReplicaApi —
    # fleet/gateway.py handler discipline), whose methods run ON the
    # handler thread but live in a class the do_*-method heuristic
    # cannot see
    handler_api_suffixes: list[str] = dataclasses.field(
        default_factory=lambda: ["Api"])
    # function-name pattern marking dispatcher-tick bodies (TT608 bans
    # fleet actuator calls — spawn / preempt / process+port mutation —
    # inside them: the tt-scale scaler thread is the only legal
    # actuation site, fleet/autoscaler.py)
    scale_tick_pattern: str = (r"^_dispatch_loop$|^_handle$|^_poll"
                               r"|^_tick|^_drain_tick$")

    root: str = "."


def _parse_toml(text: str) -> dict:
    try:
        import tomllib  # Python >= 3.11
        return tomllib.loads(text)
    except ModuleNotFoundError:
        pass
    try:
        import tomli
        return tomli.loads(text)
    except ModuleNotFoundError:
        pass
    return _parse_toml_minimal(text)


def _toml_unescape(s: str) -> str:
    """Decode TOML basic-string escapes (the subset our config uses).
    Without this, a pattern like "^cached_\\\\w+$" reaches the analyzer
    with a literal double backslash and silently never matches."""
    return (s.replace("\\\\", "\0").replace('\\"', '"')
            .replace("\\n", "\n").replace("\\t", "\t")
            .replace("\0", "\\"))


def _parse_toml_minimal(text: str) -> dict:
    """Tiny fallback parser: tables, string/bool/int scalars, and flat
    string lists — the subset [tool.tt-analyze] uses."""
    out: dict = {}
    cur = out
    buf = None  # (key, accumulated-list-text) while a [...] spans lines

    def strings(chunk: str) -> list[str]:
        return [_toml_unescape(s)
                for s in re.findall(r'"([^"]*)"', chunk)]

    for raw in text.splitlines():
        line = raw.strip()
        if buf is not None:
            buf = (buf[0], buf[1] + " " + line)
            if line.endswith("]"):
                cur[buf[0]] = strings(buf[1])
                buf = None
            continue
        if not line or line.startswith("#"):
            continue
        if line.startswith("[") and line.endswith("]"):
            cur = out
            for part in line[1:-1].strip().split("."):
                part = part.strip().strip('"')
                cur = cur.setdefault(part, {})
            continue
        if "=" not in line:
            continue
        key, _, val = line.partition("=")
        key, val = key.strip().strip('"'), val.strip()
        if val.startswith("[") and not val.endswith("]"):
            buf = (key, val)
        elif val.startswith("["):
            cur[key] = strings(val)
        elif val.startswith('"'):
            cur[key] = _toml_unescape(val.strip('"'))
        elif val in ("true", "false"):
            cur[key] = val == "true"
        else:
            try:
                cur[key] = int(val)
            except ValueError:
                cur[key] = val
    return out


def load_config(root: str = ".") -> AnalyzerConfig:
    cfg = AnalyzerConfig(root=root)
    pyproject = os.path.join(root, "pyproject.toml")
    if not os.path.exists(pyproject):
        return cfg
    with open(pyproject, encoding="utf-8") as f:
        data = _parse_toml(f.read())
    section = data.get("tool", {}).get("tt-analyze", {})
    for key, val in section.items():
        field = key.replace("-", "_")
        if hasattr(cfg, field) and field != "root":
            setattr(cfg, field, val)
    return cfg


def load_compat_table(cfg: AnalyzerConfig) -> dict[str, list[str]]:
    """Extract JAX_COMPAT_TABLE from the configured module by AST —
    lint-time must not import jax (or anything else)."""
    path = cfg.compat_table
    if not os.path.isabs(path):
        path = os.path.join(cfg.root, path)
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (isinstance(tgt, ast.Name)
                        and tgt.id == "JAX_COMPAT_TABLE"):
                    try:
                        return ast.literal_eval(node.value)
                    except ValueError:
                        return {}
    return {}
