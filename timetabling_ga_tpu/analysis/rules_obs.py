"""TT601 — wall-clock reads and span emission inside trace targets.

A `time.time()` / `time.monotonic()` / `time.perf_counter()` call (or a
span tracer's `span()` / `record()` — obs/spans.py) inside a function
that jit / vmap / shard_map / lax control flow traces executes at TRACE
time, not at run time: the clock value is read once while XLA builds
the program and baked into it as a constant, so every later dispatch
reports the COMPILE's wall clock — telemetry that looks alive and is
wrong forever after. The tt-obs design rule is that all timing is
host-side (runtime/engine.py brackets its dispatches from the host;
spans ride the AsyncWriter); on-device observability ships *data* the
host timestamps (`--trace-mode` improvement events, streamed moments),
never clock reads.

The rule reuses TT101's trace-target collection: any function handed to
a tracing callee (decorator or call argument) is scanned, including its
nested lambdas/defs (anything lexically inside traced code is traced
with it).
"""

from __future__ import annotations

import ast
import re

from timetabling_ga_tpu.analysis.core import Finding, qual_matches, qualname
from timetabling_ga_tpu.analysis.rules_trace import _collect_targets

RULE = "TT601"

# dotted clock callees (tail-matched, so `time.monotonic` also catches
# an aliased `t.monotonic` import form) plus the bare from-imports.
# `time` alone is deliberately absent: a bare `time()` cannot be told
# from a local named `time`, and the dotted form covers real usage.
_CLOCK_CALLEES = {
    "time.time", "time.time_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.process_time", "time.process_time_ns",
    "time.thread_time", "time.thread_time_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "monotonic", "monotonic_ns", "perf_counter", "perf_counter_ns",
    "process_time", "process_time_ns",
}

# span-tracer entry points: `<receiver>.span(...)` / `.record(...)`
# where the receiver is tracer-shaped (`tracer`, `self.tracer`,
# `self._tracer`, `NULL_TRACER`, a SpanTracer(...) literal)
_SPAN_METHODS = {"span", "record"}
_TRACER_RECV = re.compile(r"(^|\.)_?(tracer|null_tracer|span_tracer)$",
                          re.IGNORECASE)


def _is_span_call(call: ast.Call) -> bool:
    fn = call.func
    if not (isinstance(fn, ast.Attribute) and fn.attr in _SPAN_METHODS):
        return False
    recv = fn.value
    if isinstance(recv, ast.Call):          # SpanTracer(...).span(...)
        return qual_matches(qualname(recv.func),
                            {"SpanTracer", "spans.SpanTracer"})
    qn = qualname(recv)
    return qn is not None and bool(_TRACER_RECV.search(qn))


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    findings: list[Finding] = []
    for fn in _collect_targets(tree):
        name = getattr(fn, "name", "<lambda>")
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            qn = qualname(node.func)
            if qual_matches(qn, _CLOCK_CALLEES):
                findings.append(Finding(
                    RULE, path, node.lineno, node.col_offset,
                    f"wall-clock read `{qn}` inside jit/vmap/shard_map "
                    f"target `{name}` — executes at TRACE time and "
                    f"bakes the compile's clock into the program; time "
                    f"on the host (engine/scheduler brackets) and ship "
                    f"data, not clock reads (README \"Observability\")"))
            elif _is_span_call(node):
                findings.append(Finding(
                    RULE, path, node.lineno, node.col_offset,
                    f"span tracer call "
                    f"`{qualname(node.func) or 'tracer.span'}` inside "
                    f"jit/vmap/shard_map target `{name}` — spans are "
                    f"host-side telemetry (obs/spans.py); a span "
                    f"entered under tracing measures the COMPILE, "
                    f"emits at trace time only, and its writer I/O is "
                    f"a side effect XLA may drop"))
    return findings
