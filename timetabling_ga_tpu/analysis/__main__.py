"""`python -m timetabling_ga_tpu.analysis` — the tt-analyze CLI."""

import sys

from timetabling_ga_tpu.analysis import main

sys.exit(main())
