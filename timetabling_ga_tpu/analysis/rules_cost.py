"""TT603 — cost/memory introspection on hot paths.

`compiled.cost_analysis()` / `compiled.memory_analysis()` and
`device.memory_stats()` are host-synchronizing introspection calls:
the analyses exist only on a compiled executable (obtaining one
anywhere else forces a fresh lower+compile — seconds of XLA work), and
`memory_stats()` is a runtime RPC into the device allocator (a full
round trip on tunneled devices). Neither belongs anywhere near the
dispatch stream:

  - inside a TRACE TARGET (jit / vmap / shard_map / lax control flow)
    the call executes at trace time against a tracer, fails outright
    or bakes a stale answer into the program;
  - inside a DISPATCH LOOP (the configured dispatch modules' host
    loops, TT301's scope) it serializes the pipeline the loops exist
    to keep full — exactly the per-dispatch stall class TT301 bans for
    array readbacks.

The sanctioned homes are the obs paths (obs/cost.py): the cost
observatory extracts `cost_analysis`/`memory_analysis` ONCE at compile
time — the only moment they are free — and polls `memory_stats` from
its own daemon thread on the metricsEntry cadence. Everything else
reads the resulting registry gauges.
"""

from __future__ import annotations

import ast

from timetabling_ga_tpu.analysis.core import Finding
from timetabling_ga_tpu.analysis.rules_trace import _collect_targets

RULE = "TT603"

_COST_METHODS = {"cost_analysis", "memory_analysis", "memory_stats"}

# modules whose own bodies ARE the sanctioned obs paths
_EXEMPT_SUFFIXES = ("obs/cost.py",)


def _cost_calls(fn: ast.AST):
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _COST_METHODS):
            yield node


def _flag(findings, path, node, where: str) -> None:
    findings.append(Finding(
        RULE, path, node.lineno, node.col_offset,
        f"`.{node.func.attr}()` {where} — cost/memory introspection is "
        f"a host-sync (and, off an executable, a recompile) hazard; it "
        f"belongs in the obs paths only: the cost observatory extracts "
        f"analyses at compile time and polls memory_stats from its own "
        f"thread (obs/cost.py, README \"Cost observatory\")"))


class _LoopScanner:
    """Flag the cost methods inside any For/While body of a host
    function — the dispatch-loop half of the rule, scoped to the
    configured dispatch modules like TT301."""

    def __init__(self, path, findings):
        self.path = path
        self.findings = findings

    def scan(self, fn: ast.AST) -> None:
        self._stmts(getattr(fn, "body", []), in_loop=False)

    def _check(self, node: ast.AST, in_loop: bool) -> None:
        if not in_loop:
            return
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _COST_METHODS):
                _flag(self.findings, self.path, sub,
                      "inside a dispatch loop")

    def _stmts(self, stmts, in_loop: bool) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.For, ast.While)):
                if isinstance(st, ast.While):
                    self._check(st.test, in_loop)
                else:
                    self._check(st.iter, in_loop)
                self._stmts(st.body, True)
                self._stmts(st.orelse, True)
                continue
            for field in ("value", "test", "iter"):
                v = getattr(st, field, None)
                if isinstance(v, ast.expr):
                    self._check(v, in_loop)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(st, field, None)
                if isinstance(sub, list):
                    self._stmts(sub, in_loop)
            for h in getattr(st, "handlers", []) or []:
                self._stmts(h.body, in_loop)


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    norm = path.replace("\\", "/")
    if norm.endswith(_EXEMPT_SUFFIXES):
        return []
    findings: list[Finding] = []
    # half 1: trace targets, module-wide (TT601's collection — anything
    # lexically inside traced code is traced with it)
    for fn in _collect_targets(tree):
        for node in _cost_calls(fn):
            _flag(findings, path, node, "inside a jit/vmap/shard_map "
                                        "target")
    # half 2: dispatch loops, in the configured dispatch modules only
    if any(norm.endswith(suffix)
           for suffix in ctx.config.dispatch_modules):
        scanner = _LoopScanner(path, findings)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scanner.scan(node)
    # a call both traced and looped would double-report at one line;
    # the analyzer's set-dedupe collapses identical findings, and the
    # two message variants differ, so dedupe here by (line, col)
    seen: set = set()
    out = []
    for f in findings:
        k = (f.line, f.col)
        if k in seen:
            continue
        seen.add(k)
        out.append(f)
    return out
