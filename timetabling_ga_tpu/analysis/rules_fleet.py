"""TT605 — fleet handler discipline: no device work, bounded reads.

The fleet front's one contract (fleet/gateway.py docstring): HTTP
handlers ENQUEUE and READ ONLY. The drive loop owns every device call;
the dispatcher thread owns every piece of outbound I/O. Two ways a
handler silently breaks that:

  - DEVICE WORK INLINE: calling `block_until_ready` (or anything that
    forces one — `device_put`, `copy_to_host_async`), touching the
    solve path's dispatch-loop callees (`step`, `drive`, `submit`,
    `prepare`), or materializing device buffers (`device_arrays`,
    `reshard_state`, `fetch_state`) from a handler thread. A handler
    that dispatches device work serializes tenant requests behind the
    accelerator AND races the drive loop's control fences — the exact
    coupling the inbox exists to prevent.
  - UNBOUNDED SOCKET READS: `self.rfile.read()` with no size parks the
    handler thread until the CLIENT closes the connection (HTTP/1.1
    keep-alive: possibly never) — a tenant-controlled hang. Bodies
    must be read with an explicit Content-Length-derived bound
    (ApiHandler._body is the sanctioned shape).

Scope: handler-reachable code (the TT602 reachability walk — handler
classes' methods plus intra-module `self.x()` / bare-name callees) in
the configured fleet modules (`fleet-modules` in pyproject, default
the fleet/ package).
"""

from __future__ import annotations

import ast

from timetabling_ga_tpu.analysis.core import Finding, qual_matches, qualname
from timetabling_ga_tpu.analysis.rules_http import _reachable

RULE = "TT605"

# callee tails that mean "device work" when reached from a handler:
# jax sync points plus the solve path's dispatch-loop entries
_DEVICE_CALLEES = {
    "block_until_ready", "jax.block_until_ready",
    "device_put", "jax.device_put", "copy_to_host_async",
    "device_arrays", "reshard_state", "fetch_state",
    # scheduler/service dispatch entries: a handler may enqueue a
    # command FOR these, never call them
    "scheduler.step", "scheduler.drive", "svc.step", "svc.drive",
    "svc.submit", "scheduler.prepare",
}


def _in_scope(path: str, ctx) -> bool:
    rel = path.replace("\\", "/")
    modules = getattr(ctx.config, "fleet_modules", ["fleet/"])
    return any(m in rel for m in modules)


def _is_unbounded_rfile_read(node: ast.Call) -> str | None:
    """`<...>.rfile.read()` (or a bare `rfile.read()`) with no size
    argument — the read that blocks until the peer hangs up."""
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr == "read"):
        return None
    if node.args or node.keywords:
        return None
    recv = qualname(f.value)
    if recv is not None and recv.split(".")[-1] == "rfile":
        return recv
    return None


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    if not _in_scope(path, ctx):
        return []
    # same roots as TT602: handler classes PLUS the *Api surfaces the
    # handlers call into (handler-api-suffixes in pyproject)
    suffixes = tuple(getattr(ctx.config, "handler_api_suffixes",
                             ("Api",)))
    findings: list[Finding] = []
    for where, fn in _reachable(tree, suffixes):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            qn = qualname(node.func)
            if qual_matches(qn, _DEVICE_CALLEES):
                findings.append(Finding(
                    RULE, path, node.lineno, node.col_offset,
                    f"device/dispatch call `{qn}` on the fleet "
                    f"handler path `{where}` — handlers enqueue and "
                    f"read only; device work belongs to the drive "
                    f"loop, outbound I/O to the dispatcher thread "
                    f"(fleet/gateway.py handler discipline)"))
                continue
            recv = _is_unbounded_rfile_read(node)
            if recv is not None:
                findings.append(Finding(
                    RULE, path, node.lineno, node.col_offset,
                    f"unbounded socket read `{recv}.read()` on the "
                    f"fleet handler path `{where}` — a body read with "
                    f"no Content-Length bound parks this handler "
                    f"thread until the CLIENT closes the connection; "
                    f"read exactly the declared length "
                    f"(fleet/gateway.py ApiHandler._body)"))
    return findings
