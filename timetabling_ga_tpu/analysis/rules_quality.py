"""TT604 — quality accounting must stay on device.

The search-quality observatory (obs/quality.py, README "Search-quality
observatory") ships diversity/operator/migration telemetry as packed
int32 columns on the telemetry leaf the dispatch loop ALREADY fetches:
one leaf, no extra device round trips, no host math beyond a numpy
decode. Two ways to silently lose that property:

  - HOST RECOMPUTE: calling a population-evaluation routine
    (`batch_penalty`, `evaluate`, `event_heat`, ...) inside a dispatch
    loop's body to derive quality numbers from the fetched population —
    a per-dispatch O(pop x E) host bill (and, on device arrays, a
    hidden sync) that the on-device reduction exists to avoid. Scoped
    to the configured dispatch modules' For/While bodies, like TT301 /
    TT603's loop halves.

  - NEW COLLECTIVES: a quality-reduction helper (any function whose
    name matches the configured quality-path pattern, in the
    shard_map-executed modules) introducing a collective (`ppermute`,
    `psum`, `pmin`, ...) or a collective-bearing random op
    (`permutation` / `shuffle` / `choice` — TT302's shuffle-sort
    hazard). Telemetry must ride existing exchanges: the migration-gain
    reduction reads the sorted blocks the ring ALREADY holds, and the
    Hamming sample uses a deterministic coprime stride precisely so no
    shuffle (and no replicated-sort all-reduce) ever enters the
    telemetry path.

The sanctioned shape: reductions in parallel/islands.py pack the block
on device; runtime/engine.py and serve/scheduler.py only slice and
numpy-decode the fetched rows (obs_quality.decode_rows).
"""

from __future__ import annotations

import ast
import re

from timetabling_ga_tpu.analysis.core import Finding

RULE = "TT604"

# collectives + TT302-adjacent collective-bearing random ops: none may
# be INTRODUCED by a quality-reduction helper
_COLLECTIVES = {"ppermute", "psum", "pmin", "pmax", "all_gather",
                "all_to_all", "pbroadcast", "pshuffle"}
_RANDOM_OPS = {"permutation", "shuffle", "choice"}


def _call_name(node: ast.Call) -> str | None:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


class _LoopScanner:
    """Flag quality-recompute callees inside any For/While body of a
    host function — structurally the TT603 loop half, with the callee
    set configured as `quality-recompute-callees`."""

    def __init__(self, path, callees, findings):
        self.path = path
        self.callees = set(callees)
        self.findings = findings

    def scan(self, fn: ast.AST) -> None:
        self._stmts(getattr(fn, "body", []), in_loop=False)

    def _check(self, node: ast.AST, in_loop: bool) -> None:
        if not in_loop:
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and _call_name(
                    sub) in self.callees:
                self.findings.append(Finding(
                    RULE, self.path, sub.lineno, sub.col_offset,
                    f"`{_call_name(sub)}(...)` inside a dispatch loop — "
                    f"host-side per-generation quality recompute; the "
                    f"on-device quality block already carries these "
                    f"numbers on the fetched leaf (obs/quality.py, "
                    f"README \"Search-quality observatory\")"))

    def _stmts(self, stmts, in_loop: bool) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.For, ast.While)):
                if isinstance(st, ast.While):
                    self._check(st.test, in_loop)
                else:
                    self._check(st.iter, in_loop)
                self._stmts(st.body, True)
                self._stmts(st.orelse, True)
                continue
            for field in ("value", "test", "iter"):
                v = getattr(st, field, None)
                if isinstance(v, ast.expr):
                    self._check(v, in_loop)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(st, field, None)
                if isinstance(sub, list):
                    self._stmts(sub, in_loop)
            for h in getattr(st, "handlers", []) or []:
                self._stmts(h.body, in_loop)


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    norm = path.replace("\\", "/")
    findings: list[Finding] = []
    cfg = ctx.config
    # half 1: dispatch-loop host recompute, configured modules only
    if any(norm.endswith(suffix) for suffix in cfg.dispatch_modules):
        scanner = _LoopScanner(path, cfg.quality_recompute_callees,
                               findings)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scanner.scan(node)
    # half 2: collectives / collective-bearing random ops introduced in
    # quality-reduction helpers of the shard_map-executed modules
    if any(frag in norm for frag in cfg.sharded_modules):
        qpat = re.compile(cfg.quality_path_pattern)
        banned = _COLLECTIVES | _RANDOM_OPS
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not qpat.search(node.name):
                continue
            for sub in ast.walk(node):
                # both call forms: `lax.ppermute(...)` AND a bare
                # `ppermute(...)` after `from jax.lax import ppermute`
                # — same hazard, same flag (_call_name covers both)
                name = (_call_name(sub) if isinstance(sub, ast.Call)
                        else None)
                if name in banned:
                    kind = ("collective" if name in _COLLECTIVES
                            else "collective-bearing random op")
                    findings.append(Finding(
                        RULE, path, sub.lineno, sub.col_offset,
                        f"`{name}` is a {kind} inside quality-"
                        f"reduction helper `{node.name}` — quality "
                        f"telemetry must ride existing exchanges and "
                        f"deterministic strides, never add collectives "
                        f"(TT302-adjacent; parallel/islands.py "
                        f"_div_stats / _migrate return_gain are the "
                        f"sanctioned patterns)"))
    # a nested quality helper inside a scanned loop could double-report
    # one line; dedupe by (line, col) like TT603
    seen: set = set()
    out = []
    for f in findings:
        k = (f.line, f.col)
        if k in seen:
            continue
        seen.add(k)
        out.append(f)
    return out
