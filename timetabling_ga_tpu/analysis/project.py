"""The whole-program layer under TT303/TT304/TT305: module graph,
per-function summaries, and a cross-module call graph.

Every rule before this layer was single-module AST scanning; the three
interprocedural rules need to see a compiled program built by a factory
in `runtime/engine.py` get CALLED in `serve/scheduler.py`, a
`donate_argnums` declared in `parallel/islands.py` kill a buffer two
modules away, and a fetch helper defined in `runtime/dispatch_core.py`
clear device taint wherever it is imported. This module provides the
minimum machinery for that:

  Project        all scanned files loaded as one unit. Modules get
                 dotted names rooted at their outermost package (the
                 nearest ancestor directory without an __init__.py),
                 so resolution works identically for the shipped
                 package and for test fixture packages.
  import maps    per-module alias -> dotted target, from `import a.b`,
                 `import a.b as c`, `from a.b import c [as d]`, and
                 explicit-relative forms. Star imports are ignored
                 (the package bans them; the analyzer must not guess).
  resolve()      a call expression's dotted name, resolved through the
                 importing module's alias map to a FunctionInfo in
                 another scanned module — the generalization of the
                 TT602 `_reachable` idiom from "same module only" to
                 the whole scan set. Tail matching mirrors
                 core.qual_matches: `timetabling_ga_tpu.runtime.engine`
                 resolves an import written as `runtime.engine` or
                 `engine` alike.
  summaries      fixpoint-computed per-function facts the rules
                 consume: `program_factories` (returns a compiled
                 dispatch program — the `cached_*`/`make_*_runner`
                 contract), `device_returning` (returns a value a
                 dispatch program produced), `donators` (returns a
                 callable that donates specific positional args — read
                 off `jax.jit(..., donate_argnums=...)` /
                 `donate_argnames` through the factory's return, one
                 tuple level deep: the `return runner, False` caching
                 idiom).

Stdlib-only, like every other analysis module: linting must never need
JAX or a device.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

from timetabling_ga_tpu.analysis.core import func_params, qualname

_JIT_NAMES = ("jax.jit", "jit")


@dataclasses.dataclass
class ModuleInfo:
    name: str                     # dotted, rooted at outermost package
    path: str
    rel: str                      # path relative to config root
    tree: ast.Module
    src: str
    imports: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class FunctionInfo:
    qname: str                    # "pkg.mod.func" / "pkg.mod.Cls.func"
    name: str                     # bare function name
    module: ModuleInfo
    node: ast.AST
    cls: str | None = None


@dataclasses.dataclass
class DonationSpec:
    positions: tuple              # donated positional indices
    tuple_result: bool            # factory returns (callable, flag)
    origin: str                   # qname of the jit-declaring factory


def _module_name(path: str) -> str:
    """Dotted module name rooted at the outermost enclosing package."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    name = ".".join(reversed(parts))
    return name[:-len(".__init__")] if name.endswith(".__init__") else name


def _import_map(tree: ast.Module, modname: str) -> dict[str, str]:
    """Local alias -> dotted target for one module's import statements."""
    pkg = modname.rsplit(".", 1)[0] if "." in modname else ""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    out[alias.asname] = alias.name
                else:
                    # `import a.b.c` binds `a`; attribute chains off it
                    # spell the full dotted path themselves
                    out[alias.name.split(".")[0]] = \
                        alias.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                up = pkg.split(".") if pkg else []
                up = up[:len(up) - (node.level - 1)] if node.level > 1 \
                    else up
                base = ".".join(x for x in [".".join(up), base] if x)
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                out[local] = f"{base}.{alias.name}" if base \
                    else alias.name
    return out


class Project:
    """All scanned sources as one unit; built once per analyzer run."""

    def __init__(self, sources, config):
        # sources: iterable of (path, rel, tree, src) for parsed files
        self.config = config
        self.modules: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}
        for path, rel, tree, src in sources:
            name = _module_name(path)
            mod = ModuleInfo(name, path, rel, tree, src)
            mod.imports = _import_map(tree, name)
            self.modules[name] = mod
        for mod in self.modules.values():
            self._index_functions(mod)
        self._factory_res = [re.compile(p) for p in getattr(
            config, "taint_sources", [r"^cached_\w+$",
                                      r"^make_\w+_runner$"])]
        self.program_factories: set[str] = set()
        self.device_returning: set[str] = set()
        self.donators: dict[str, DonationSpec] = {}
        self._summarize()

    # -- loading --------------------------------------------------------

    def _index_functions(self, mod: ModuleInfo) -> None:
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{mod.name}.{node.name}"
                self.functions[qn] = FunctionInfo(qn, node.name, mod,
                                                  node)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        qn = f"{mod.name}.{node.name}.{sub.name}"
                        self.functions[qn] = FunctionInfo(
                            qn, sub.name, mod, sub, cls=node.name)

    # -- resolution -----------------------------------------------------

    def _module_by_tail(self, dotted: str) -> ModuleInfo | None:
        if dotted in self.modules:
            return self.modules[dotted]
        best = None
        for name, mod in self.modules.items():
            if name.endswith("." + dotted):
                if best is None or len(name) < len(best.name):
                    best = mod
        return best

    def resolve(self, caller_mod: ModuleInfo, func_expr: ast.AST
                ) -> FunctionInfo | None:
        """The FunctionInfo a call expression resolves to, through the
        calling module's import aliases; None when the callee is not a
        scanned module-level function (method calls, builtins, foreign
        libraries)."""
        qn = qualname(func_expr)
        if qn is None:
            return None
        parts = qn.split(".")
        if len(parts) == 1:
            # bare name: same-module function, or `from mod import f`
            fi = self.functions.get(f"{caller_mod.name}.{parts[0]}")
            if fi is not None:
                return fi
            target = caller_mod.imports.get(parts[0])
            if target is None:
                return None
            parts = target.split(".")
        else:
            target = caller_mod.imports.get(parts[0])
            if target is not None:
                parts = target.split(".") + parts[1:]
        if len(parts) < 2:
            return None
        mod = self._module_by_tail(".".join(parts[:-1]))
        if mod is None:
            return None
        return self.functions.get(f"{mod.name}.{parts[-1]}")

    def is_cross_module(self, caller_mod: ModuleInfo,
                        callee: FunctionInfo) -> bool:
        return callee.module.name != caller_mod.name

    # -- summaries ------------------------------------------------------

    def _jit_donations(self, fn: ast.AST) -> dict[str, tuple]:
        """Names (and '<return>') bound in `fn` to a jit call carrying
        donate_argnums/donate_argnames, mapped to donated positions."""
        out: dict[str, tuple] = {}

        def spec(call: ast.Call, wrapped: ast.AST | None) -> tuple:
            nums, names = [], []
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    nums += [n.value for n in ast.walk(kw.value)
                             if isinstance(n, ast.Constant)
                             and isinstance(n.value, int)]
                elif kw.arg == "donate_argnames":
                    names += [n.value for n in ast.walk(kw.value)
                             if isinstance(n, ast.Constant)
                             and isinstance(n.value, str)]
            if names and wrapped is not None:
                wname = (qualname(wrapped) or "").rsplit(".", 1)[-1]
                for node in ast.walk(fn):
                    if (isinstance(node, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                            and node.name == wname):
                        params = func_params(node)
                        nums += [params.index(p) for p in names
                                 if p in params]
            return tuple(sorted(set(nums)))

        def jit_spec(expr: ast.AST) -> tuple | None:
            if not isinstance(expr, ast.Call):
                return None
            qn = qualname(expr.func)
            if qn is None or qn.rsplit(".", 1)[-1] not in (
                    "jit",) and qn not in _JIT_NAMES:
                return None
            s = spec(expr, expr.args[0] if expr.args else None)
            return s or None

        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                s = jit_spec(node.value)
                if s:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            out[tgt.id] = s
            elif isinstance(node, ast.Return) and node.value is not None:
                s = jit_spec(node.value)
                if s:
                    out["<return>"] = s
        return out

    def _return_exprs(self, fn: ast.AST):
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                yield node.value

    def _summarize(self) -> None:
        # seed: name-pattern factories (the cached_*/make_*_runner
        # contract) and functions whose body returns a donating jit
        for qn, fi in self.functions.items():
            if any(r.match(fi.name) for r in self._factory_res):
                self.program_factories.add(qn)
            jits = self._jit_donations(fi.node)
            for ret in self._return_exprs(fi.node):
                spec, tup = self._donation_of(ret, jits)
                if spec:
                    self.donators[qn] = DonationSpec(spec, tup, qn)
                    break
        # fixpoint: returning another factory's product / another
        # donator's callable / a device value propagates the fact
        for _ in range(len(self.modules) + 2):
            changed = False
            for qn, fi in self.functions.items():
                for ret in self._return_exprs(fi.node):
                    changed |= self._propagate(qn, fi, ret)
            if not changed:
                break

    def _donation_of(self, ret: ast.AST, jits: dict) -> tuple:
        """(positions, tuple_result) a return expression carries from
        this function's own jit bindings."""
        def direct(expr: ast.AST):
            if isinstance(expr, ast.Name) and expr.id in jits:
                return jits[expr.id]
            if isinstance(expr, ast.Call):
                # return jax.jit(f, donate_argnums=...) handled via the
                # '<return>' pseudo-binding
                return jits.get("<return>") \
                    if ret is expr and "<return>" in jits else None
            return None

        s = direct(ret)
        if s:
            return s, False
        if isinstance(ret, ast.Tuple) and ret.elts:
            s = direct(ret.elts[0])
            if s:
                return s, True
        return (), False

    def _propagate(self, qn: str, fi: FunctionInfo, ret: ast.AST
                   ) -> bool:
        changed = False

        def callee_of(expr):
            if isinstance(expr, ast.Call):
                return self.resolve(fi.module, expr.func)
            return None

        head = ret.elts[0] if (isinstance(ret, ast.Tuple) and ret.elts) \
            else ret
        tup = head is not ret
        callee = callee_of(head)
        if callee is not None:
            # factory-product passthrough: return other_factory(...)
            if (callee.qname in self.program_factories
                    and qn not in self.program_factories):
                self.program_factories.add(qn)
                changed = True
            if (callee.qname in self.donators
                    and qn not in self.donators):
                inner = self.donators[callee.qname]
                self.donators[qn] = DonationSpec(
                    inner.positions, tup or inner.tuple_result,
                    inner.origin)
                changed = True
            if (callee.qname in self.device_returning
                    and qn not in self.device_returning):
                self.device_returning.add(qn)
                changed = True
        # device value: return <program>(...) where <program> was bound
        # from a factory call inside this function
        if isinstance(head, ast.Call) and qn not in self.device_returning:
            inner = head.func
            prog_names = self._program_bindings(fi)
            if ((isinstance(inner, ast.Name) and inner.id in prog_names)
                    or (isinstance(inner, ast.Call)
                        and callee_of(inner) is not None
                        and callee_of(inner).qname
                        in self.program_factories)):
                self.device_returning.add(qn)
                changed = True
        return changed

    def _program_bindings(self, fi: FunctionInfo) -> set[str]:
        """Names bound inside `fi` to a dispatch program (a factory
        call's result, first element on tuple unpack)."""
        names: set[str] = set()
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            callee = self.resolve(fi.module, node.value.func)
            if callee is None \
                    or callee.qname not in self.program_factories:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
                elif isinstance(tgt, (ast.Tuple, ast.List)) and tgt.elts \
                        and isinstance(tgt.elts[0], ast.Name):
                    names.add(tgt.elts[0].id)
        return names
