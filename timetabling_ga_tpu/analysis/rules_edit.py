"""TT309 — edit-solve work on the dispatch path or in trace targets.

tt-edit (serve/editsolve.py) is ADMISSION-TIME machinery: edit-spec
parsing, base-problem loading, diff/apply, anchor attachment, and the
population transplant are host-side numpy (plus one batched
re-evaluation) that run once per submitted edit. Two placements are
banned:

  - inside the For/While loops of the configured dispatch modules
    (runtime/engine.py, parallel/islands.py, serve/scheduler.py, ...):
    a per-quantum diff or transplant re-derives admission-time state
    on every control fence — the drive loop's per-dispatch cost must
    stay O(lanes), never O(edit);
  - inside jit/trace-target functions anywhere: editsolve is host
    numpy + JSON — traced, it either constant-folds a stale edit into
    a compiled program (silently wrong after the next edit) or fails
    at trace time; either way the edit seam belongs OUTSIDE the
    compiled region (the anchored objective already rides
    ProblemArrays as data).

Binding-aware: the rule recognizes `editsolve.f(...)` /
`tga.serve.editsolve.f(...)` via import aliases and names imported
with `from ...editsolve import f` — lazy function-level imports
included (the scheduler's own sanctioned use is a lazy import OUTSIDE
any loop).
"""

from __future__ import annotations

import ast

from timetabling_ga_tpu.analysis.core import Finding, qualname

RULE = "TT309"

_MODULE = "timetabling_ga_tpu.serve.editsolve"


def _edit_bindings(tree: ast.Module):
    """(prefixes, names): dotted call prefixes bound to the editsolve
    module and bare callables imported from it, across the whole file
    (function-level lazy imports included)."""
    prefixes: set[str] = set()
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == _MODULE or a.name.endswith(".editsolve"):
                    prefixes.add((a.asname or a.name) + ".")
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == _MODULE or mod.endswith(".editsolve"):
                for a in node.names:
                    names.add(a.asname or a.name)
            elif a_editsolve := [a for a in node.names
                                 if a.name == "editsolve"]:
                for a in a_editsolve:
                    prefixes.add((a.asname or a.name) + ".")
    return prefixes, names


def _is_edit_call(call: ast.Call, prefixes, names) -> bool:
    qn = qualname(call.func)
    if qn is None:
        return False
    if qn in names:
        return True
    return any(qn.startswith(p) for p in prefixes)


def _is_jitted(fn) -> bool:
    """The decorated function is a trace target: jax.jit / jit /
    functools.partial(jax.jit, ...)."""
    for deco in fn.decorator_list:
        target = deco.func if isinstance(deco, ast.Call) else deco
        qn = qualname(target)
        if qn in ("jax.jit", "jit"):
            return True
        if qn in ("functools.partial", "partial") \
                and isinstance(deco, ast.Call) and deco.args:
            if qualname(deco.args[0]) in ("jax.jit", "jit"):
                return True
    return False


def _flag(findings, path, node, where):
    qn = qualname(node.func)
    findings.append(Finding(
        RULE, path, node.lineno, node.col_offset,
        f"`{qn}` (serve/editsolve.py) {where} — edit resolution and "
        f"population transplant are admission-time host work: hoist "
        f"to the submit/prepare seam (Scheduler.prepare_edit), "
        f"outside loops and compiled regions"))


def _walk_loops(stmts, in_loop, prefixes, names, findings, path):
    for st in stmts:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            continue          # nested defs get their own pass
        nested_loop = in_loop or isinstance(st, (ast.For, ast.While))
        if in_loop:
            for sub in ast.walk(st):
                if isinstance(sub, ast.Call) and _is_edit_call(
                        sub, prefixes, names):
                    _flag(findings, path, sub,
                          "inside a dispatch loop")
            continue          # everything below is already covered
        for field in ("body", "orelse", "finalbody"):
            inner = getattr(st, field, None)
            if inner:
                _walk_loops(inner, nested_loop, prefixes, names,
                            findings, path)
        if isinstance(st, ast.Try):
            for h in st.handlers:
                _walk_loops(h.body, nested_loop, prefixes, names,
                            findings, path)


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    if RULE not in ctx.config.rules:
        return []
    prefixes, names = _edit_bindings(tree)
    if not prefixes and not names:
        return []
    findings: list[Finding] = []
    # trace targets: editsolve anywhere inside a jitted function
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and _is_jitted(node):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and _is_edit_call(
                        sub, prefixes, names):
                    _flag(findings, path, sub,
                          "inside a jit trace target")
    # dispatch loops: only in the configured dispatch modules
    norm = path.replace("\\", "/")
    if any(norm.endswith(suffix)
           for suffix in ctx.config.dispatch_modules):
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                _walk_loops(node.body, False, prefixes, names,
                            findings, path)
    return findings
