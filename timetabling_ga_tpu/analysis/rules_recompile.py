"""TT201 / TT202 — recompile hazards.

TT201: a `jax.jit` static argument (static_argnums / static_argnames)
receiving an unhashable value (list/dict/set display, np/jnp array) —
a TypeError at call time — or a run-varying value (the loop variable of
an enclosing Python `for`), which recompiles the program every
iteration.

TT202: compile-cache completeness. A hand-rolled compiled-program cache
(`_RUNNER_CACHE`-style module dict) must key on EVERY value the traced
program closes over: a factory argument that does not appear in the
cache-key tuple means two configs that differ only in that value
collide on one cache entry — the cached program silently runs with the
first config's constant baked in.
"""

from __future__ import annotations

import ast
import re

from timetabling_ga_tpu.analysis.core import (
    Finding, func_params, qual_matches, qualname, target_names)

RULE_STATIC = "TT201"
RULE_CACHE = "TT202"

_JIT_NAMES = {"jax.jit", "jit"}
_UNHASHABLE_CALLS = {"np.array", "np.asarray", "numpy.array",
                     "numpy.asarray", "jnp.array", "jnp.asarray",
                     "jax.numpy.array", "jax.numpy.asarray", "list",
                     "dict", "set"}


def _jit_static_spec(call: ast.Call):
    """(static_positions, static_names) from a jax.jit(...) call, or
    None when it declares no statics."""
    nums, names = [], []
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.append(n.value)
        elif kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.append(n.value)
    return (nums, names) if (nums or names) else None


def _is_unhashable(expr: ast.AST) -> bool:
    if isinstance(expr, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call):
        return qual_matches(qualname(expr.func), _UNHASHABLE_CALLS)
    return False


def _check_static_args(tree: ast.Module, path: str) -> list[Finding]:
    findings: list[Finding] = []
    # jitted-name -> (static positions, static names, param names or None)
    jitted: dict[str, tuple[list[int], list[str], list[str] | None]] = {}

    for node in ast.walk(tree):
        # g = jax.jit(f, static_argnums=...)
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if qual_matches(qualname(call.func), _JIT_NAMES):
                spec = _jit_static_spec(call)
                if spec:
                    for tgt in node.targets:
                        for name in target_names(tgt):
                            jitted[name] = (spec[0], spec[1], None)
        # @jax.jit(static_argnums=...) / @partial(jax.jit, static_...=)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                is_jit = qual_matches(qualname(dec.func), _JIT_NAMES)
                is_partial_jit = (
                    qual_matches(qualname(dec.func),
                                 {"functools.partial", "partial"})
                    and dec.args
                    and qual_matches(qualname(dec.args[0]), _JIT_NAMES))
                if is_jit or is_partial_jit:
                    spec = _jit_static_spec(dec)
                    if spec:
                        jitted[node.name] = (spec[0], spec[1],
                                             func_params(node))

    if not jitted:
        return findings

    # walk call sites with the enclosing-for-loop-variable set in scope
    class V(ast.NodeVisitor):
        def __init__(self):
            self.loop_vars: list[set[str]] = []

        def visit_For(self, node: ast.For):
            self.loop_vars.append(set(target_names(node.target)))
            self.generic_visit(node)
            self.loop_vars.pop()

        def _flag(self, expr, name, what):
            findings.append(Finding(
                RULE_STATIC, path, expr.lineno, expr.col_offset,
                f"static argument of jitted `{name}` receives {what} — "
                f"unhashable statics raise at call time; run-varying "
                f"statics recompile on every call"))

        def _check_expr(self, expr, name):
            if _is_unhashable(expr):
                self._flag(expr, name, "an unhashable value")
            elif (isinstance(expr, ast.Name)
                  and any(expr.id in lv for lv in self.loop_vars)):
                self._flag(expr, name, f"loop variable `{expr.id}`")

        def visit_Call(self, node: ast.Call):
            fname = qualname(node.func)
            if fname in jitted:
                nums, names, params = jitted[fname]
                for pos in nums:
                    if pos < len(node.args):
                        self._check_expr(node.args[pos], fname)
                for kw in node.keywords:
                    if kw.arg in names:
                        self._check_expr(kw.value, fname)
                    elif (kw.arg is None and params is None):
                        pass
                # positional args bound to static_argnames params
                if params:
                    for pos, arg in enumerate(node.args):
                        if pos < len(params) and params[pos] in names:
                            self._check_expr(arg, fname)
            self.generic_visit(node)

    V().visit(tree)
    return findings


def _value_names(node: ast.AST) -> set[str]:
    """Data names an expression depends on: Name ids excluding callee
    chains (`islands.make_runner(mesh)` depends on `mesh`, not
    `islands`) and lambda-bound parameters."""
    names: set[str] = set()

    def rec(n, bound: frozenset):
        if isinstance(n, ast.Call):
            for a in list(n.args) + [kw.value for kw in n.keywords]:
                rec(a, bound)      # skip n.func: callee, not data
        elif isinstance(n, ast.Lambda):
            rec(n.body, bound | frozenset(func_params(n)))
        elif isinstance(n, ast.Name):
            if n.id not in bound:
                names.add(n.id)
        else:
            for c in ast.iter_child_nodes(n):
                rec(c, bound)

    rec(node, frozenset())
    return names


def _factory_arg_names(call: ast.Call) -> set[str]:
    """Names a compiled-program factory closes over: every data name in
    its arguments; lambda arguments contribute their free names."""
    names: set[str] = set()
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        names |= _value_names(arg)
    return names


def _walk_scope(scope: ast.AST):
    """Walk a scope's own statements, not those of nested functions or
    classes (they are separate scopes with their own analysis)."""
    todo = list(ast.iter_child_nodes(scope))
    while todo:
        node = todo.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            todo.extend(ast.iter_child_nodes(node))


def _check_cache_keys(tree: ast.Module, path: str, ctx) -> list[Finding]:
    findings: list[Finding] = []
    cache_re = re.compile(ctx.config.cache_name_pattern)
    factory_re = re.compile(ctx.config.factory_pattern)

    scopes = [tree] + [n for n in ast.walk(tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
    for scope in scopes:
        # gather per-scope: key tuples, factory-call assignments, and
        # cache stores — one linear pass over the scope's own statements
        key_tuples: dict[str, ast.Tuple] = {}
        factory_calls: dict[str, ast.Call] = {}
        stores: list[tuple[str, ast.AST]] = []  # (key var, value expr)

        for node in _walk_scope(scope):
            if isinstance(node, ast.Assign):
                val = node.value
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        if isinstance(val, ast.Tuple):
                            key_tuples[tgt.id] = val
                        elif isinstance(val, ast.Call):
                            fq = qualname(val.func)
                            last = fq.rsplit(".", 1)[-1] if fq else ""
                            if factory_re.match(last):
                                factory_calls[tgt.id] = val
                    elif (isinstance(tgt, ast.Subscript)
                          and isinstance(tgt.value, ast.Name)
                          and cache_re.match(tgt.value.id)
                          and isinstance(tgt.slice, ast.Name)):
                        stores.append((tgt.slice.id, val))

        for key_var, value in stores:
            key_node = key_tuples.get(key_var)
            if key_node is None:
                continue
            call = None
            if isinstance(value, ast.Call):
                fq = qualname(value.func)
                last = fq.rsplit(".", 1)[-1] if fq else ""
                if factory_re.match(last):
                    call = value
            elif isinstance(value, ast.Name):
                call = factory_calls.get(value.id)
            if call is None:
                continue
            key_names = _value_names(key_node)
            missing = sorted(_factory_arg_names(call) - key_names)
            for name in missing:
                findings.append(Finding(
                    RULE_CACHE, path, call.lineno, call.col_offset,
                    f"compile-cache key `{key_var}` omits `{name}`, which "
                    f"the cached program is built from — two configs "
                    f"differing only in `{name}` collide on one compiled "
                    f"program"))
    return findings


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    out = []
    if "TT201" in ctx.config.rules:
        out += _check_static_args(tree, path)
    if "TT202" in ctx.config.rules:
        out += _check_cache_keys(tree, path, ctx)
    return out
