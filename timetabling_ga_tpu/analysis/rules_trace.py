"""TT101/TT102 — tracer-unsafe control flow.

TT101: Python `if` / `while` / `assert` / `for` statements whose
condition (or iterable) derives from a parameter of a function that is
a jit / vmap / shard_map / lax-control-flow target execute at TRACE
time: at best they bake one branch into the compiled program, at worst
they raise TracerBoolConversionError at runtime. Inside traced code the
data-dependent forms are `lax.cond` / `lax.while_loop` / `jnp.where`.

TT102: `and` / `or` expressions with a traced operand inside the same
targets. Short-circuit operators call `bool()` on their left operand —
the SAME tracer-bool hazard TT101 catches in `if`, hidden in expression
position where no statement-level rule sees it (`ok = (x > 0) and
(y > 0)` fails identically to `if x > 0:`). The element-wise forms are
`jnp.logical_and` / `jnp.logical_or` (or `&` / `|`).

Shape- and dtype-derived values (`x.shape`, `x.ndim`, `x.dtype`,
`len(x)`) are static under tracing and do not taint; neither do params
declared static via `static_argnums` / `static_argnames`.
"""

from __future__ import annotations

import ast

from timetabling_ga_tpu.analysis.core import (
    Finding, decorator_static_params, func_params, qual_matches, qualname,
    target_names)

RULE = "TT101"
RULE_BOOLOP = "TT102"

# callees whose function-valued arguments are traced
_TRACING_CALLEES = {
    "jax.jit", "jit", "jax.vmap", "vmap", "jax.pmap", "pmap",
    "shard_map", "jax.experimental.shard_map.shard_map",
    "lax.scan", "jax.lax.scan", "lax.fori_loop", "jax.lax.fori_loop",
    "lax.while_loop", "jax.lax.while_loop", "lax.cond", "jax.lax.cond",
    "lax.switch", "jax.lax.switch", "jax.checkpoint", "jax.remat",
    "jax.grad", "grad", "jax.value_and_grad",
}

# attribute reads that yield static (trace-time Python) values
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
# calls that yield static values regardless of argument taint
_STATIC_CALLS = {"len", "isinstance", "type", "hasattr", "range"}


def _collect_targets(tree: ast.Module):
    """FunctionDef/Lambda nodes that are trace targets in this module."""
    defs_by_name: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    targets: list[ast.AST] = []
    seen: set[int] = set()

    def add(node):
        if id(node) not in seen:
            seen.add(id(node))
            targets.append(node)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                inner = dec
                if isinstance(dec, ast.Call):
                    # @functools.partial(jax.jit, ...) / @jax.jit(...)
                    inner = dec.func
                    if qual_matches(qualname(inner),
                                    {"functools.partial", "partial"}):
                        if dec.args and qual_matches(
                                qualname(dec.args[0]), _TRACING_CALLEES):
                            add(node)
                        continue
                if qual_matches(qualname(inner), _TRACING_CALLEES):
                    add(node)
        elif isinstance(node, ast.Call):
            if not qual_matches(qualname(node.func), _TRACING_CALLEES):
                continue
            # any function-valued argument (incl. inside list literals,
            # e.g. lax.switch branch lists) becomes a trace target
            cands = list(node.args) + [kw.value for kw in node.keywords]
            for arg in list(cands):
                if isinstance(arg, (ast.List, ast.Tuple)):
                    cands.extend(arg.elts)
            for arg in cands:
                if isinstance(arg, ast.Lambda):
                    add(arg)
                elif isinstance(arg, ast.Name) and arg.id in defs_by_name:
                    for fn in defs_by_name[arg.id]:
                        add(fn)
    return targets


class _TaintChecker:
    def __init__(self, fn, path: str, findings: list[Finding]):
        self.path = path
        self.findings = findings
        self.fn = fn
        static = (decorator_static_params(fn)
                  if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                  else set())
        self.tainted: set[str] = {p for p in func_params(fn)
                                  if p not in static}

    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            if qual_matches(qualname(node.func), _STATIC_CALLS):
                return False
            parts = ([node.func] if not isinstance(node.func, ast.Name)
                     else [])
            return any(self.is_tainted(a)
                       for a in parts + list(node.args)
                       + [kw.value for kw in node.keywords])
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.keyword)):
                if self.is_tainted(child):
                    return True
        return False

    def flag(self, node: ast.AST, what: str):
        name = getattr(self.fn, "name", "<lambda>")
        self.findings.append(Finding(
            RULE, self.path, node.lineno, node.col_offset,
            f"Python `{what}` on a traced value inside jit/vmap/shard_map "
            f"target `{name}` — use lax.cond/lax.while_loop/jnp.where "
            f"(or hoist the value to a static argument)"))

    def flag_boolop(self, node: ast.BoolOp):
        name = getattr(self.fn, "name", "<lambda>")
        op = "and" if isinstance(node.op, ast.And) else "or"
        self.findings.append(Finding(
            RULE_BOOLOP, self.path, node.lineno, node.col_offset,
            f"`{op}` short-circuit on a traced value inside "
            f"jit/vmap/shard_map target `{name}` — short-circuit calls "
            f"bool() on the tracer (the TT101 hazard in expression "
            f"position); use jnp.logical_{op} (or `{'&' if op == 'and' else '|'}`)"))

    def _boolops(self, node: ast.AST):
        """Flag the OUTERMOST tainted BoolOp under `node` (one finding
        per short-circuit chain; nested tainted operands are the same
        defect)."""
        if node is None:
            return
        # bool() is called on every operand EXCEPT the last (the chain's
        # result is returned unevaluated), so a traced value in final
        # position is legal: `use_default or (x > 0)` with a static
        # first operand short-circuits on the static only
        if isinstance(node, ast.BoolOp) and any(
                self.is_tainted(v) for v in node.values[:-1]):
            self.flag_boolop(node)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.keyword,
                                  ast.comprehension)):
                self._boolops(child)

    def run(self):
        body = (self.fn.body if isinstance(self.fn.body, list)
                else [ast.Expr(self.fn.body)])
        self._stmts(body)

    def _stmts(self, stmts):
        for st in stmts:
            self._stmt(st)

    def _stmt(self, st: ast.stmt):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # nested defs are analyzed iff they are targets
        # TT102: short-circuit chains in this statement's expression
        # slots, checked against the CURRENT taint state (bodies of
        # compound statements recurse below and re-check per statement)
        for field in ("value", "test", "iter"):
            self._boolops(getattr(st, field, None))
        if isinstance(st, ast.With):
            for item in st.items:
                self._boolops(item.context_expr)
        if isinstance(st, ast.Assign):
            t = self.is_tainted(st.value)
            for tgt in st.targets:
                for name in target_names(tgt):
                    (self.tainted.add if t
                     else self.tainted.discard)(name)
        elif isinstance(st, ast.AugAssign):
            if self.is_tainted(st.value) and isinstance(st.target, ast.Name):
                self.tainted.add(st.target.id)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None and isinstance(st.target, ast.Name):
                (self.tainted.add if self.is_tainted(st.value)
                 else self.tainted.discard)(st.target.id)
        elif isinstance(st, ast.If):
            if self.is_tainted(st.test):
                self.flag(st, "if")
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.While):
            if self.is_tainted(st.test):
                self.flag(st, "while")
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.Assert):
            if self.is_tainted(st.test):
                self.flag(st, "assert")
        elif isinstance(st, ast.For):
            if self.is_tainted(st.iter):
                self.flag(st, "for")
                for name in target_names(st.target):
                    self.tainted.add(name)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, (ast.With,)):
            self._stmts(st.body)
        elif isinstance(st, ast.Try):
            self._stmts(st.body)
            for h in st.handlers:
                self._stmts(h.body)
            self._stmts(st.orelse)
            self._stmts(st.finalbody)


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    findings: list[Finding] = []
    for fn in _collect_targets(tree):
        _TaintChecker(fn, path, findings).run()
    return findings
