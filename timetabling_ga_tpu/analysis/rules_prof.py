"""TT310 — phase scopes outside the tt-prof registry, or on handler
paths.

tt-prof (obs/prof.py) attributes device time to phases by joining
profiler events back to `jax.named_scope` strings. That join is only
as good as the scope discipline:

  - every phase scope must come from the ONE registry
    (`obs.prof.PHASES`): a free-hand `jax.named_scope("my_phase")`
    (or an `obs_prof.scope(...)` with an unregistered / non-literal
    name) silently lands in the profiler's `unattributed` bucket —
    or worse, collides with a future registry name and mis-attributes
    someone else's ops. Scope names are a shared namespace; the
    registry is where they are declared.
  - HTTP handler paths (the TT602-reachable set: `do_*` methods, their
    intra-module callees, `*Api` fronts) must not ENTER scopes at all:
    `jax.named_scope` pushes onto jax's thread-local trace-name stack,
    i.e. it is jax machinery on a scrape thread — the pull front's
    contract is stdlib-only reads (obs/http.py design rules), and a
    scope pushed around a handler body would stamp the NEXT trace on
    that thread with a phase that never ran.

Binding-aware like TT309: recognizes `jax.named_scope(...)` directly,
`obs_prof.scope(...)` / `prof.scope(...)` via import aliases of
`timetabling_ga_tpu.obs.prof`, and bare names imported with
`from ...prof import scope` — decorator position included (that is how
the ops modules thread phases). obs/prof.py itself is exempt: it is
the registry's implementation and constructs scopes from validated
variables.
"""

from __future__ import annotations

import ast

from timetabling_ga_tpu.analysis.core import Finding, qualname
from timetabling_ga_tpu.analysis.rules_http import _reachable
from timetabling_ga_tpu.obs.prof import PHASES

RULE = "TT310"

_MODULE = "timetabling_ga_tpu.obs.prof"
_PHASE_SET = frozenset(PHASES)

# the registry implementation itself (validates names at runtime)
_EXEMPT_SUFFIXES = ("obs/prof.py",)


def _prof_bindings(tree: ast.Module):
    """(prefixes, names): dotted call prefixes bound to the obs.prof
    module and bare callables imported from it, across the whole file
    (function-level lazy imports included)."""
    prefixes: set[str] = set()
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == _MODULE or a.name.endswith(".prof"):
                    prefixes.add((a.asname or a.name) + ".")
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == _MODULE or mod.endswith(".prof"):
                for a in node.names:
                    if a.name == "scope":
                        names.add(a.asname or a.name)
            else:
                for a in node.names:
                    if a.name == "prof":
                        prefixes.add((a.asname or a.name) + ".")
    return prefixes, names


def _scope_call(call: ast.Call, prefixes, names):
    """The phase-name argument node when `call` enters a phase scope
    (jax.named_scope or a bound obs.prof scope()), else None-marker
    False."""
    qn = qualname(call.func)
    if qn is None:
        return False
    if qn in ("jax.named_scope", "named_scope"):
        return call.args[0] if call.args else None
    if qn in names:
        return call.args[0] if call.args else None
    if any(qn == p + "scope" for p in prefixes):
        return call.args[0] if call.args else None
    return False


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    if RULE not in ctx.config.rules:
        return []
    if path.replace("\\", "/").endswith(_EXEMPT_SUFFIXES):
        return []
    prefixes, names = _prof_bindings(tree)
    findings: list[Finding] = []

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        arg = _scope_call(node, prefixes, names)
        if arg is False:
            continue
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if arg.value not in _PHASE_SET:
                findings.append(Finding(
                    RULE, path, node.lineno, node.col_offset,
                    f"phase scope {arg.value!r} is not in the tt-prof "
                    f"registry (obs/prof.py PHASES) — unregistered "
                    f"scopes land in the profiler's `unattributed` "
                    f"bucket or collide with future registry names; "
                    f"declare the phase in PHASES or reuse an "
                    f"existing one"))
        else:
            findings.append(Finding(
                RULE, path, node.lineno, node.col_offset,
                "phase scope name is not a string literal — the "
                "tt-prof attribution join is static (registry "
                "membership must be checkable at lint time); pass a "
                "literal from obs/prof.py PHASES"))

    # handler paths: entering ANY scope is jax machinery on a scrape
    # thread (same reachable set as TT602)
    suffixes = tuple(getattr(ctx.config, "handler_api_suffixes",
                             ("Api",)))
    for where, fn in _reachable(tree, suffixes):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if _scope_call(node, prefixes, names) is False:
                continue
            findings.append(Finding(
                RULE, path, node.lineno, node.col_offset,
                f"phase scope entered on the HTTP handler path "
                f"`{where}` — named_scope pushes jax's thread-local "
                f"trace-name stack from a scrape thread; handlers are "
                f"stdlib-only readers (obs/http.py design rules) and "
                f"a scope pushed here mis-stamps the next trace on "
                f"this thread"))
    return findings
