"""SARIF 2.1.0 export for tt-analyze findings (stdlib-only).

`tt analyze --sarif` emits one run in the Static Analysis Results
Interchange Format so CI hosts render findings as inline annotations.
Only the core subset is produced — tool.driver.rules, results with one
physical location each — which is exactly what the annotation UIs
consume. Columns are 1-based in SARIF; `Finding.col` carries the
0-based AST offset, hence the +1.
"""

from __future__ import annotations

_SCHEMA = ("https://docs.oasis-open.org/sarif/sarif/v2.1.0/errata01/"
           "os/schemas/sarif-schema-2.1.0.json")


def to_sarif(findings, rule_docs: dict[str, str]) -> dict:
    """A SARIF 2.1.0 log dict for `findings`; `rule_docs` maps rule id
    -> one-line description for the tool.driver.rules table."""
    rule_ids = sorted({f.rule for f in findings})
    return {
        "$schema": _SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tt-analyze",
                "rules": [{
                    "id": rid,
                    "shortDescription": {
                        "text": rule_docs.get(rid, rid)},
                } for rid in rule_ids],
            }},
            "results": [{
                "ruleId": f.rule,
                "level": "error" if f.rule == "TT000" else "warning",
                "message": {"text": f.message},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path.replace("\\", "/")},
                        "region": {"startLine": f.line,
                                   "startColumn": f.col + 1},
                    },
                }],
            } for f in findings],
        }],
    }
