"""Shared infrastructure for the tt-analyze rules: findings, the
inline-suppression protocol, and small AST utilities every rule uses.

Deliberately stdlib-only — the analyzer must run (in CI, pre-commit,
editors) without importing JAX or touching a device.
"""

from __future__ import annotations

import ast
import dataclasses
import re


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    rule: str          # e.g. "TT101"
    path: str
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


# `# tt-analyze: ignore` suppresses every rule on that line;
# `# tt-analyze: ignore[TT301]` / `ignore[TT301,TT401]` only those.
_SUPPRESS_RE = re.compile(
    r"#\s*tt-analyze:\s*ignore(?:\[([A-Za-z0-9_,\s]+)\])?")


def iter_markers(src: str):
    """Every `# tt-analyze: ignore` marker in `src` as
    (marker_line, rules | None, covered_lines): a marker covers its own
    line, and — on a comment-only line — the line below it too.

    A marker is a COMMENT TOKEN that begins with the marker text:
    tokenizing (not line-grepping) keeps docstrings and prose comments
    that merely MENTION the syntax from acting as suppressions — and,
    under --warn-unused-ignores, from being reported as stale."""
    import io
    import tokenize
    lines = src.splitlines()
    try:
        toks = list(tokenize.generate_tokens(io.StringIO(src).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return
    for tok in toks:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.match(tok.string)
        if not m:
            continue
        i = tok.start[0]
        rules = (None if m.group(1) is None
                 else {r.strip() for r in m.group(1).split(",")
                       if r.strip()})
        covered = {i}
        if i <= len(lines) and lines[i - 1].lstrip().startswith("#"):
            covered.add(i + 1)
        yield i, rules, covered


def suppressions(src: str) -> dict[int, set[str] | None]:
    """Map 1-based line number -> suppressed rule ids (None = all rules).

    A marker suppresses findings on its own line; a marker on a
    comment-only line also suppresses findings on the line below it.
    """
    out: dict[int, set[str] | None] = {}
    for _, rules, covered in iter_markers(src):
        for ln in covered:
            cur = out.get(ln, set())
            out[ln] = None if (rules is None or cur is None) \
                else cur | rules
    return out


def filter_suppressed(findings: list[Finding], src: str) -> list[Finding]:
    supp = suppressions(src)
    kept = []
    for f in findings:
        rules = supp.get(f.line, set())
        if rules is None or (rules and f.rule in rules):
            continue
        kept.append(f)
    return kept


def unused_suppressions(findings: list[Finding], src: str, path: str
                        ) -> list[Finding]:
    """Markers that suppress nothing — the unused-noqa analogue.

    `findings` must be the PRE-suppression list for this file: a marker
    is used iff some finding on a covered line matches its rule scope.
    A marker scoped to a disabled rule is unused (like flake8)."""
    out = []
    for line, rules, covered in iter_markers(src):
        used = any(f.line in covered
                   and (rules is None or f.rule in rules)
                   for f in findings)
        if not used:
            scope = "all rules" if rules is None \
                else ",".join(sorted(rules))
            out.append(Finding(
                "TT901", path, line, 0,
                f"unused suppression: `# tt-analyze: ignore` marker "
                f"({scope}) suppresses no finding — drop the stale "
                f"marker"))
    return out


def qualname(node: ast.AST) -> str | None:
    """Dotted name of a Name/Attribute chain ('jax.random.split'), else
    None for anything not a plain attribute path."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def qual_matches(qn: str | None, names: set[str]) -> bool:
    """True if the dotted name's tail matches any entry: 'jax.lax.scan'
    matches both 'lax.scan' and 'scan' entries."""
    if qn is None:
        return False
    parts = qn.split(".")
    for i in range(len(parts)):
        if ".".join(parts[i:]) in names:
            return True
    return False


def target_names(target: ast.AST):
    """Bound names of an assignment target (handles tuple/list/star)."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, ast.Starred):
        yield from target_names(target.value)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from target_names(elt)


def name_ids(node: ast.AST) -> set[str]:
    """Every Name id appearing anywhere under `node`."""
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def func_params(fn: ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda
                ) -> list[str]:
    a = fn.args
    params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        params.append(a.vararg.arg)
    if a.kwarg:
        params.append(a.kwarg.arg)
    return params


def decorator_static_params(fn: ast.FunctionDef) -> set[str]:
    """Param names declared static via static_argnames/static_argnums in
    a jit-ish decorator (plain or functools.partial-wrapped)."""
    static: set[str] = set()
    params = func_params(fn)
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, str):
                        static.add(n.value)
            elif kw.arg == "static_argnums":
                for n in ast.walk(kw.value):
                    if (isinstance(n, ast.Constant)
                            and isinstance(n.value, int)
                            and 0 <= n.value < len(params)):
                        static.add(params[n.value])
    return static


class ParentedVisitor(ast.NodeVisitor):
    """NodeVisitor that tracks the stack of enclosing nodes."""

    def __init__(self):
        self.stack: list[ast.AST] = []

    def generic_visit(self, node):
        self.stack.append(node)
        try:
            super().generic_visit(node)
        finally:
            self.stack.pop()

    def enclosing(self, *types):
        for n in reversed(self.stack):
            if isinstance(n, types):
                return n
        return None
