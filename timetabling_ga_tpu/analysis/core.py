"""Shared infrastructure for the tt-analyze rules: findings, the
inline-suppression protocol, and small AST utilities every rule uses.

Deliberately stdlib-only — the analyzer must run (in CI, pre-commit,
editors) without importing JAX or touching a device.
"""

from __future__ import annotations

import ast
import dataclasses
import re


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    rule: str          # e.g. "TT101"
    path: str
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


# `# tt-analyze: ignore` suppresses every rule on that line;
# `# tt-analyze: ignore[TT301]` / `ignore[TT301,TT401]` only those.
_SUPPRESS_RE = re.compile(
    r"#\s*tt-analyze:\s*ignore(?:\[([A-Za-z0-9_,\s]+)\])?")


def suppressions(src: str) -> dict[int, set[str] | None]:
    """Map 1-based line number -> suppressed rule ids (None = all rules).

    A marker suppresses findings on its own line; a marker on a
    comment-only line also suppresses findings on the line below it.
    """
    out: dict[int, set[str] | None] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = (None if m.group(1) is None
                 else {r.strip() for r in m.group(1).split(",") if r.strip()})

        def merge(ln: int, rules=rules):
            cur = out.get(ln, set())
            out[ln] = None if (rules is None or cur is None) else cur | rules

        merge(i)
        if line.lstrip().startswith("#"):
            merge(i + 1)
    return out


def filter_suppressed(findings: list[Finding], src: str) -> list[Finding]:
    supp = suppressions(src)
    kept = []
    for f in findings:
        rules = supp.get(f.line, set())
        if rules is None or (rules and f.rule in rules):
            continue
        kept.append(f)
    return kept


def qualname(node: ast.AST) -> str | None:
    """Dotted name of a Name/Attribute chain ('jax.random.split'), else
    None for anything not a plain attribute path."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def qual_matches(qn: str | None, names: set[str]) -> bool:
    """True if the dotted name's tail matches any entry: 'jax.lax.scan'
    matches both 'lax.scan' and 'scan' entries."""
    if qn is None:
        return False
    parts = qn.split(".")
    for i in range(len(parts)):
        if ".".join(parts[i:]) in names:
            return True
    return False


def target_names(target: ast.AST):
    """Bound names of an assignment target (handles tuple/list/star)."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, ast.Starred):
        yield from target_names(target.value)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from target_names(elt)


def name_ids(node: ast.AST) -> set[str]:
    """Every Name id appearing anywhere under `node`."""
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def func_params(fn: ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda
                ) -> list[str]:
    a = fn.args
    params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        params.append(a.vararg.arg)
    if a.kwarg:
        params.append(a.kwarg.arg)
    return params


def decorator_static_params(fn: ast.FunctionDef) -> set[str]:
    """Param names declared static via static_argnames/static_argnums in
    a jit-ish decorator (plain or functools.partial-wrapped)."""
    static: set[str] = set()
    params = func_params(fn)
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, str):
                        static.add(n.value)
            elif kw.arg == "static_argnums":
                for n in ast.walk(kw.value):
                    if (isinstance(n, ast.Constant)
                            and isinstance(n.value, int)
                            and 0 <= n.value < len(params)):
                        static.add(params[n.value])
    return static


class ParentedVisitor(ast.NodeVisitor):
    """NodeVisitor that tracks the stack of enclosing nodes."""

    def __init__(self):
        self.stack: list[ast.AST] = []

    def generic_visit(self, node):
        self.stack.append(node)
        try:
            super().generic_visit(node)
        finally:
            self.stack.pop()

    def enclosing(self, *types):
        for n in reversed(self.stack):
            if isinstance(n, types):
                return n
        return None
