"""TT203 — donated-buffer reuse.

`jax.jit(f, donate_argnums=...)` DELETES the donated input buffers at
dispatch so XLA can alias them into the outputs (the engine's
population states ride this between dispatches). Reading a donated
array afterwards raises `Array has been deleted` — but only at runtime,
only on backends that implement donation, and only on the code path
that actually reuses it; the canonical failure is code that passes
tests on one backend and dies on the device.

The analysis is a linear per-function scan, like TT401's:

  - donating callables are seeded from `g = jax.jit(f, donate_argnums=
    (2,))` assignments and `@jax.jit(donate_argnums=...)` /
    `@functools.partial(jax.jit, donate_argnums=...)` decorated
    functions; `donate_argnames` resolve to positions through the
    wrapped function's parameter list (the decorated def, or `f`'s def
    when the assignment form wraps a function of this module);
  - at a call site of a donating callable, every bare-Name positional
    argument in a donated slot becomes DEAD;
  - any later load of a dead name — including attribute reads like
    `state.penalty` — flags, until an assignment rebinds it (so the
    engine's `state = runner(pa, k, state)` pattern, which donates and
    rebinds in one statement, is clean by construction).

Interprocedural donation (a runner built by a factory in another module
— the engine's `cached_*` programs) is invisible here by design; that
is the TT303 device-taint work (ROADMAP). This rule is the local guard
that keeps the donation discipline honest where the jit is in view.
"""

from __future__ import annotations

import ast

from timetabling_ga_tpu.analysis.core import (
    Finding, func_params, qual_matches, qualname, target_names)

RULE = "TT203"

_JIT_NAMES = {"jax.jit", "jit"}


def _donate_spec(call: ast.Call):
    """(donated_argnums, donated_argnames) declared by a jit-ish call,
    or None when it donates nothing."""
    nums, names = [], []
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.append(n.value)
        elif kw.arg == "donate_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.append(n.value)
    return (nums, names) if (nums or names) else None


def _collect_donators(tree: ast.Module) -> dict[str, list[int]]:
    """name -> donated positional indices, for every donating callable
    visible at module scope or bound by assignment anywhere."""
    donators: dict[str, list[int]] = {}
    # parameter lists of every visible function def, so donate_argnames
    # resolve to positions in BOTH forms — the decorator form (via the
    # decorated def itself) and the assignment form `g = jax.jit(f,
    # donate_argnames=...)` (via f's def, when it is in this module)
    fn_params = {n.name: func_params(n) for n in ast.walk(tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for node in ast.walk(tree):
        # g = jax.jit(f, donate_argnums=(2,) / donate_argnames=(...))
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if qual_matches(qualname(call.func), _JIT_NAMES):
                spec = _donate_spec(call)
                if spec:
                    nums = list(spec[0])
                    wrapped = (qualname(call.args[0])
                               if call.args else None)
                    params = fn_params.get((wrapped or "").rsplit(
                        ".", 1)[-1], [])
                    for pname in spec[1]:
                        if pname in params:
                            nums.append(params.index(pname))
                    if nums:
                        for tgt in node.targets:
                            for name in target_names(tgt):
                                donators[name] = sorted(set(nums))
        # @jax.jit(donate_argnums=...) / @partial(jax.jit, donate_...)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                is_jit = qual_matches(qualname(dec.func), _JIT_NAMES)
                is_partial_jit = (
                    qual_matches(qualname(dec.func),
                                 {"functools.partial", "partial"})
                    and dec.args
                    and qual_matches(qualname(dec.args[0]), _JIT_NAMES))
                if not (is_jit or is_partial_jit):
                    continue
                spec = _donate_spec(dec)
                if not spec:
                    continue
                nums = list(spec[0])
                params = func_params(node)
                for pname in spec[1]:
                    if pname in params:
                        nums.append(params.index(pname))
                if nums:
                    donators[node.name] = sorted(set(nums))
    return donators


class _Scan:
    """Linear statement walk of one scope: donated names die at the
    donating call, revive on rebind, and flag on any read in between."""

    def __init__(self, fn, path, donators, findings):
        self.fn = fn
        self.path = path
        self.donators = donators
        self.findings = findings
        self.dead: dict[str, int] = {}   # name -> donating call lineno

    def _flag(self, node, name):
        self.findings.append(Finding(
            RULE, self.path, node.lineno, node.col_offset,
            f"`{name}` was donated to a jitted call on line "
            f"{self.dead[name]} (donate_argnums) and read again — the "
            f"donated buffer is deleted at dispatch; use the call's "
            f"output or clone before donating"))

    def _check_reads(self, node: ast.AST):
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
                    and sub.id in self.dead):
                self._flag(sub, sub.id)
                # one report per death: rebirth via flag keeps a single
                # misuse from cascading into a finding per read
                del self.dead[sub.id]

    def _handle_donations(self, node: ast.AST):
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            qn = qualname(sub.func)
            name = qn.rsplit(".", 1)[-1] if qn else None
            positions = self.donators.get(name)
            if not positions:
                continue
            for pos in positions:
                if pos < len(sub.args) and isinstance(sub.args[pos],
                                                      ast.Name):
                    self.dead[sub.args[pos].id] = sub.lineno

    def _stmt(self, st):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # nested scopes are scanned separately
        if isinstance(st, ast.Assign):
            self._check_reads(st.value)
            self._handle_donations(st.value)
            for tgt in st.targets:
                for name in target_names(tgt):
                    self.dead.pop(name, None)   # rebind revives
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign, ast.Expr,
                             ast.Return, ast.Raise, ast.Assert)):
            val = getattr(st, "value", None) or getattr(st, "test", None)
            if val is not None:
                self._check_reads(val)
                self._handle_donations(val)
        elif isinstance(st, (ast.If, ast.While)):
            self._check_reads(st.test)
            self._handle_donations(st.test)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.For):
            self._check_reads(st.iter)
            self._handle_donations(st.iter)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.With):
            for item in st.items:
                self._check_reads(item.context_expr)
                self._handle_donations(item.context_expr)
            self._stmts(st.body)
        elif isinstance(st, ast.Try):
            self._stmts(st.body)
            for h in st.handlers:
                self._stmts(h.body)
            self._stmts(st.orelse)
            self._stmts(st.finalbody)

    def _stmts(self, stmts):
        for st in stmts:
            self._stmt(st)

    def run(self):
        self._stmts(self.fn.body if isinstance(self.fn.body, list) else [])


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    donators = _collect_donators(tree)
    if not donators:
        return []
    findings: list[Finding] = []
    scopes = [tree] + [n for n in ast.walk(tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
    for scope in scopes:
        _Scan(scope, path, donators, findings).run()
    return findings
