"""TT303/TT304/TT305/TT306 — whole-program device-taint, donation, and
fence discipline (the interprocedural upgrade of TT301/TT203).

The rules run over `analysis/project.py`'s view of the scan set —
module graph, import resolution, per-function summaries — so a program
built by a factory in one module is tracked into the module that calls
it. They deliberately cover ONLY what the single-module rules cannot:
taint and donation whose source resolves ACROSS a module boundary
(local producers stay TT301's and TT203's job, so no line ever carries
both the local and the interprocedural finding).

TT303 — cross-module device-taint reaching a host-forcing sink.
Values produced by dispatch programs (results of calling a
`cached_*`/`make_*_runner` factory product, or of a function summarized
as device-returning) are device-tainted through assignments, tuple
unpacks, and calls. Inside a loop of a configured dispatch module,
`float()`/`int()`/`bool()`, `np.asarray`/`np.array`, `.item()`/
`.tolist()`, and control-flow-steering comparisons on a tainted value
each force a device round trip that serializes the dispatch pipeline —
exactly the syncs the sanctioned fetch helpers (`sync_helpers` config;
calling one clears taint) exist to batch.

TT304 — interprocedurally-donated buffer read after the donating
dispatch. A factory whose returned callable carries
`jax.jit(..., donate_argnums=...)` — directly, through a passthrough
return, or as the first element of the `(runner, cache_hit)` caching
tuple — donates those positions AT EVERY CALL SITE in every module.
A bare-name argument in a donated slot is deleted at dispatch; any
later read before a rebind flags. The engine/scheduler idiom
`state, trace = runner(pa, seeds, chunks, state, gens)` (donate and
rebind in one statement) is clean by construction.

TT305 — fence discipline inside dispatch loops: a control-classified
host read must precede the next dispatch, telemetry must not.
  (a) a sanctioned-fetch result that never steers control flow
      (telemetry) fetched BEFORE a later dispatch in the same loop
      iteration fences that dispatch for data nobody decides on —
      move it after the dispatch, off the fence path. A bare
      `fetch(x)` expression statement is exempt: an unbound fetch is
      a deliberate fence.
  (b) control flow steered through `jax.block_until_ready(...)` — a
      whole-buffer blocking wait where the discipline wants the
      sanctioned packed readback (`fetch`) that batches the round
      trip and feeds the watchdog.

TT306 — host fetch of device-RESIDENT group state outside a park
fence. The serving residency optimization (serve/scheduler.py
RESIDENCY) keeps a stacked group's population on device between
quanta, indexed by a store attribute named in `resident_stores`
(default `_resident`). Any value rooted in that store — a direct
subscript/`get` read, or a name assigned from one — reaching a host
fetch (a `sync_helpers` call, or a `taint_sinks` conversion) in a
dispatch module flags, UNLESS the enclosing function is a configured
`fence_helpers` park-fence helper: fetching resident state anywhere
else bypasses the flush state machine, so the bytes move without the
snapshot/ship units re-syncing (a handler would then serve a unit
that matches neither the cursors nor the device). A rebind from a
plain call clears rootedness — `state, trace = runner(..., state, ...)`
makes `state` the quantum's OUTPUT, whose park-path fetch is the
legal fence.

Scope notes: function bodies named in `sync_helpers` are exempt (they
ARE the sanctioned sync points), as are `fence_helpers` bodies for
TT306; nested closures are not scanned (the dispatch loops under
audit live in module-level functions and methods).
"""

from __future__ import annotations

import ast

from timetabling_ga_tpu.analysis.core import (
    Finding, qual_matches, qualname, target_names)
from timetabling_ga_tpu.analysis.project import Project

RULE_SYNC = "TT303"
RULE_DONATE = "TT304"
RULE_FENCE = "TT305"
RULE_RESIDENT = "TT306"

_METHOD_SINKS = {"item", "tolist"}
_BLOCKING_WAIT = {"jax.block_until_ready", "block_until_ready"}


def _sink_sets(config):
    """Partition the configured `taint_sinks` into bare conversion
    calls (`float`), dotted call names (`np.asarray`, tail-matched),
    and method sinks (`item`/`tolist`)."""
    converts, dotted, methods = set(), set(), set()
    for s in getattr(config, "taint_sinks",
                     ["float", "int", "bool", "np.asarray", "np.array",
                      "item", "tolist"]):
        if s in _METHOD_SINKS:
            methods.add(s)
        elif "." in s:
            dotted.add(s)
        else:
            converts.add(s)
    return converts, dotted, methods


def _is_dispatch_module(mod, config) -> bool:
    norm = mod.rel.replace("\\", "/")
    return any(norm.endswith(sfx) for sfx in config.dispatch_modules)


class _FuncFacts:
    """Cross-module bindings of one function body: dispatch programs,
    donating callables, and sanctioned-fetch classification."""

    def __init__(self, proj: Project, fi):
        self.proj = proj
        self.fi = fi
        self.sync_helpers = set(proj.config.sync_helpers)
        # names bound to a dispatch program built by a factory resolved
        # in ANOTHER module, name -> factory qname
        self.cross_progs: dict[str, str] = {}
        # names bound to a cross-module donating callable,
        # name -> (positions, origin qname)
        self.cross_donators: dict[str, tuple] = {}
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            callee = proj.resolve(fi.module, node.value.func)
            if callee is None \
                    or not proj.is_cross_module(fi.module, callee):
                continue
            spec = proj.donators.get(callee.qname)
            is_factory = callee.qname in proj.program_factories
            for tgt in node.targets:
                head = None
                tup = False
                if isinstance(tgt, ast.Name):
                    head = tgt.id
                elif isinstance(tgt, (ast.Tuple, ast.List)) and tgt.elts \
                        and isinstance(tgt.elts[0], ast.Name):
                    head, tup = tgt.elts[0].id, True
                if head is None:
                    continue
                if is_factory:
                    self.cross_progs[head] = callee.qname
                if spec is not None and tup == spec.tuple_result:
                    self.cross_donators[head] = (spec.positions,
                                                 spec.origin)

    def is_sanctioned(self, call: ast.Call) -> bool:
        qn = qualname(call.func)
        if qn is not None \
                and qn.rsplit(".", 1)[-1] in self.sync_helpers:
            return True
        callee = self.proj.resolve(self.fi.module, call.func)
        return callee is not None and callee.name in self.sync_helpers

    def device_call_origin(self, call: ast.Call) -> str | None:
        """Factory/function qname when `call` produces a device value
        whose producer lives in another module, else None."""
        f = call.func
        if isinstance(f, ast.Name) and f.id in self.cross_progs:
            return self.cross_progs[f.id]
        callee = self.proj.resolve(self.fi.module, f)
        if (callee is not None
                and self.proj.is_cross_module(self.fi.module, callee)
                and callee.qname in self.proj.device_returning):
            return callee.qname
        return None


class _TaintChecker:
    """TT303: linear statement walk tracking cross-module device taint
    into host-forcing sinks inside loops."""

    def __init__(self, facts: _FuncFacts, path, findings):
        self.facts = facts
        self.path = path
        self.findings = findings
        self.device: dict[str, str] = {}   # tainted name -> origin
        (self._converts, self._dotted,
         self._methods) = _sink_sets(facts.proj.config)

    def _flag(self, node, what, origin):
        self.findings.append(Finding(
            RULE_SYNC, self.path, node.lineno, node.col_offset,
            f"hidden host-device sync: {what} on a value produced by "
            f"`{origin}` (another module's dispatch program) inside a "
            f"dispatch loop — route the readback through a sanctioned "
            f"fetch helper"))

    def _origin(self, node: ast.AST) -> str | None:
        """Origin qname when the expression carries cross-module device
        taint, else None."""
        if isinstance(node, ast.Name):
            return self.device.get(node.id)
        if isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
            return self._origin(node.value)
        if isinstance(node, ast.Call):
            if self.facts.is_sanctioned(node):
                return None
            origin = self.facts.device_call_origin(node)
            if origin is not None:
                return origin
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                o = self._origin(a)
                if o is not None:
                    return o
            return None
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                o = self._origin(child)
                if o is not None:
                    return o
        return None

    def _check_sinks(self, node: ast.AST, in_loop: bool):
        if not in_loop:
            return
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            qn = qualname(sub.func)
            if (isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in self._methods):
                o = self._origin(sub.func.value)
                if o is not None:
                    self._flag(sub, f"`.{sub.func.attr}()`", o)
            elif qn in self._converts and sub.args:
                o = self._origin(sub.args[0])
                if o is not None:
                    self._flag(sub, f"`{qn}()`", o)
            elif qual_matches(qn, self._dotted) and sub.args:
                o = self._origin(sub.args[0])
                if o is not None:
                    self._flag(sub, f"`{qn}()`", o)

    def _check_test(self, test: ast.AST, in_loop: bool):
        """Control-flow-steering comparison on a tainted value."""
        if not in_loop:
            return
        for sub in ast.walk(test):
            if isinstance(sub, ast.Compare):
                for opnd in [sub.left] + list(sub.comparators):
                    o = self._origin(opnd)
                    if o is not None:
                        self._flag(
                            sub, "control-flow-steering comparison", o)
                        return

    def _bind(self, targets, value):
        origin = self._origin(value)
        for tgt in targets:
            for name in target_names(tgt):
                if origin is not None:
                    self.device[name] = origin
                else:
                    self.device.pop(name, None)

    def run(self):
        self._stmts(self.facts.fi.node.body, in_loop=False)

    def _stmts(self, stmts, in_loop):
        for st in stmts:
            self._stmt(st, in_loop)

    def _stmt(self, st, in_loop):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return
        if isinstance(st, ast.Assign):
            self._check_sinks(st.value, in_loop)
            self._bind(st.targets, st.value)
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign, ast.Expr,
                             ast.Return)):
            if getattr(st, "value", None) is not None:
                self._check_sinks(st.value, in_loop)
        elif isinstance(st, (ast.If, ast.While)):
            self._check_test(st.test, in_loop)
            self._check_sinks(st.test, in_loop)
            inner = in_loop or isinstance(st, ast.While)
            self._stmts(st.body, inner)
            self._stmts(st.orelse, inner)
        elif isinstance(st, ast.For):
            self._check_sinks(st.iter, in_loop)
            self._stmts(st.body, True)
            self._stmts(st.orelse, in_loop)
        elif isinstance(st, ast.With):
            self._stmts(st.body, in_loop)
        elif isinstance(st, ast.Try):
            self._stmts(st.body, in_loop)
            for h in st.handlers:
                self._stmts(h.body, in_loop)
            self._stmts(st.orelse, in_loop)
            self._stmts(st.finalbody, in_loop)


class _DonationChecker:
    """TT304: donated-slot arguments of cross-module donating callables
    die at the call; later reads flag until a rebind."""

    def __init__(self, facts: _FuncFacts, path, findings):
        self.facts = facts
        self.path = path
        self.findings = findings
        self.dead: dict[str, tuple] = {}   # name -> (lineno, origin)

    def _flag(self, node, name):
        lineno, origin = self.dead.pop(name)
        self.findings.append(Finding(
            RULE_DONATE, self.path, node.lineno, node.col_offset,
            f"`{name}` was donated on line {lineno} to a dispatch "
            f"program whose factory `{origin}` declares donate_argnums "
            f"in another module — the buffer is deleted at dispatch; "
            f"use the call's output or clone before donating"))

    def _check_reads(self, node: ast.AST):
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Name)
                    and isinstance(sub.ctx, ast.Load)
                    and sub.id in self.dead):
                self._flag(sub, sub.id)

    def _handle_calls(self, node: ast.AST):
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call) \
                    or not isinstance(sub.func, ast.Name):
                continue
            entry = self.facts.cross_donators.get(sub.func.id)
            if entry is None:
                continue
            positions, origin = entry
            for pos in positions:
                if pos < len(sub.args) \
                        and isinstance(sub.args[pos], ast.Name):
                    self.dead[sub.args[pos].id] = (sub.lineno, origin)

    def run(self):
        self._stmts(self.facts.fi.node.body)

    def _stmts(self, stmts):
        for st in stmts:
            self._stmt(st)

    def _stmt(self, st):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return
        if isinstance(st, ast.Assign):
            self._check_reads(st.value)
            self._handle_calls(st.value)
            for tgt in st.targets:
                for name in target_names(tgt):
                    self.dead.pop(name, None)   # rebind revives
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign, ast.Expr,
                             ast.Return, ast.Raise, ast.Assert)):
            val = getattr(st, "value", None) or getattr(st, "test", None)
            if val is not None:
                self._check_reads(val)
                self._handle_calls(val)
        elif isinstance(st, (ast.If, ast.While)):
            self._check_reads(st.test)
            self._handle_calls(st.test)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.For):
            self._check_reads(st.iter)
            self._handle_calls(st.iter)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.With):
            for item in st.items:
                self._check_reads(item.context_expr)
                self._handle_calls(item.context_expr)
            self._stmts(st.body)
        elif isinstance(st, ast.Try):
            self._stmts(st.body)
            for h in st.handlers:
                self._stmts(h.body)
            self._stmts(st.orelse)
            self._stmts(st.finalbody)


class _FenceChecker:
    """TT305: telemetry fetches that fence the next dispatch, and
    control flow steered through block_until_ready."""

    def __init__(self, facts: _FuncFacts, path, findings):
        self.facts = facts
        self.path = path
        self.findings = findings
        # every name read by a control-flow test anywhere in the scope
        self.control_names: set[str] = set()
        for node in ast.walk(facts.fi.node):
            if isinstance(node, (ast.If, ast.While)):
                self.control_names |= {
                    n.id for n in ast.walk(node.test)
                    if isinstance(n, ast.Name)}

    def run(self):
        for node in ast.walk(self.facts.fi.node):
            if isinstance(node, (ast.For, ast.While)):
                self._check_loop(node)

    def _flat(self, stmts):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            yield st
            for attr in ("body", "orelse", "finalbody"):
                yield from self._flat(getattr(st, attr, []) or [])
            for h in getattr(st, "handlers", []) or []:
                yield from self._flat(h.body)

    def _is_dispatch(self, st) -> bool:
        for sub in ast.walk(st):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id in self.facts.cross_progs):
                return True
        return False

    def _fetch_binding(self, st):
        """(call, bound_names) when `st` assigns a sanctioned-fetch
        result; bare Expr fetches are deliberate fences (exempt)."""
        if not isinstance(st, ast.Assign) \
                or not isinstance(st.value, ast.Call):
            return None
        if not self.facts.is_sanctioned(st.value):
            return None
        names = {n for tgt in st.targets for n in target_names(tgt)}
        return (st.value, names) if names else None

    def _check_loop(self, loop):
        stmts = list(self._flat(loop.body))
        dispatch_at = [i for i, st in enumerate(stmts)
                       if self._is_dispatch(st)]
        if dispatch_at:
            last_dispatch = dispatch_at[-1]
            for i, st in enumerate(stmts[:last_dispatch]):
                hit = self._fetch_binding(st)
                if hit is None:
                    continue
                call, names = hit
                if names & self.control_names:
                    continue   # control read before dispatch: the rule
                self.findings.append(Finding(
                    RULE_FENCE, self.path, call.lineno,
                    call.col_offset,
                    f"telemetry host read "
                    f"`{qualname(call.func)}(...)` fences the next "
                    f"dispatch — only control reads may precede a "
                    f"dispatch; move telemetry after it (or drop the "
                    f"binding to make the fence explicit)"))
        for st in stmts:
            for sub in ast.walk(st):
                if (isinstance(sub, ast.Call)
                        and qual_matches(qualname(sub.func),
                                         _BLOCKING_WAIT)
                        and sub.args):
                    bound = set()
                    if isinstance(st, ast.Assign):
                        bound = {n for tgt in st.targets
                                 for n in target_names(tgt)}
                    arg = sub.args[0]
                    steered = bound & self.control_names or (
                        isinstance(arg, ast.Name)
                        and arg.id in self.control_names)
                    if steered:
                        self.findings.append(Finding(
                            RULE_FENCE, self.path, sub.lineno,
                            sub.col_offset,
                            "control flow steered through "
                            "`jax.block_until_ready` — a whole-buffer "
                            "blocking wait; control fences must use "
                            "the sanctioned packed fetch helper"))


class _ResidentChecker:
    """TT306: a host fetch rooted in a device-resident group store,
    outside a park-fence helper. Linear statement walk, like
    _TaintChecker, with its own (simpler) rootedness: store accesses
    and names assigned from them, cleared by a rebind from any plain
    call — a dispatch program's output is new state, and parking it
    is the legal fence."""

    def __init__(self, facts: _FuncFacts, path, findings):
        self.facts = facts
        self.path = path
        self.findings = findings
        cfg = facts.proj.config
        self.stores = set(getattr(cfg, "resident_stores",
                                  ["_resident"]))
        (self._converts, self._dotted,
         self._methods) = _sink_sets(cfg)
        self.rooted: set[str] = set()

    def _flag(self, node, what):
        self.findings.append(Finding(
            RULE_RESIDENT, self.path, node.lineno, node.col_offset,
            f"host fetch of device-resident group state ({what}) "
            f"outside a park-fence helper — resident population state "
            f"may only reach the host inside a `fence_helpers` flush "
            f"body, where the group's snapshot/ship units re-sync; "
            f"fetch the dispatch OUTPUT at the park fence, or move "
            f"this read into the flush path"))

    def _store_access(self, node: ast.AST) -> bool:
        return any(isinstance(sub, ast.Attribute)
                   and sub.attr in self.stores
                   for sub in ast.walk(node))

    def _rooted_expr(self, node: ast.AST) -> bool:
        """Store access, or a read of a rooted name. A Call with no
        store access in it is NOT rooted (its output is a new value),
        which is also what makes assignment from one a clearing
        rebind."""
        if self._store_access(node):
            return True
        if isinstance(node, ast.Call):
            return False
        return any(isinstance(sub, ast.Name)
                   and isinstance(sub.ctx, ast.Load)
                   and sub.id in self.rooted
                   for sub in ast.walk(node))

    def _check_sinks(self, node: ast.AST):
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            qn = qualname(sub.func)
            args = list(sub.args) + [kw.value for kw in sub.keywords]
            if self.facts.is_sanctioned(sub):
                if any(self._rooted_expr(a) for a in args):
                    self._flag(sub, f"`{qn}(...)`")
            elif (isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in self._methods):
                if self._rooted_expr(sub.func.value):
                    self._flag(sub, f"`.{sub.func.attr}()`")
            elif ((qn in self._converts
                   or qual_matches(qn, self._dotted)) and sub.args):
                if self._rooted_expr(sub.args[0]):
                    self._flag(sub, f"`{qn}(...)`")

    def _bind(self, targets, value):
        rooted = self._rooted_expr(value)
        for tgt in targets:
            for name in target_names(tgt):
                if rooted:
                    self.rooted.add(name)
                else:
                    self.rooted.discard(name)

    def run(self):
        self._stmts(self.facts.fi.node.body)

    def _stmts(self, stmts):
        for st in stmts:
            self._stmt(st)

    def _stmt(self, st):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return
        if isinstance(st, ast.Assign):
            self._check_sinks(st.value)
            self._bind(st.targets, st.value)
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign, ast.Expr,
                             ast.Return, ast.Raise, ast.Assert)):
            val = getattr(st, "value", None) or getattr(st, "test",
                                                        None)
            if val is not None:
                self._check_sinks(val)
        elif isinstance(st, (ast.If, ast.While)):
            self._check_sinks(st.test)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.For):
            self._check_sinks(st.iter)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.With):
            for item in st.items:
                self._check_sinks(item.context_expr)
            self._stmts(st.body)
        elif isinstance(st, ast.Try):
            self._stmts(st.body)
            for h in st.handlers:
                self._stmts(h.body)
            self._stmts(st.orelse)
            self._stmts(st.finalbody)


def _analyze_project(proj: Project, ctx) -> dict[str, list[Finding]]:
    out: dict[str, list[Finding]] = {}
    rules = ctx.config.rules
    sync_helpers = set(ctx.config.sync_helpers)
    for fi in proj.functions.values():
        if fi.name in sync_helpers:
            continue   # the sanctioned sync points themselves
        facts = _FuncFacts(proj, fi)
        findings = out.setdefault(fi.module.rel, [])
        if "TT304" in rules and facts.cross_donators:
            _DonationChecker(facts, fi.module.rel, findings).run()
        if _is_dispatch_module(fi.module, ctx.config):
            if "TT303" in rules:
                _TaintChecker(facts, fi.module.rel, findings).run()
            if "TT305" in rules:
                _FenceChecker(facts, fi.module.rel, findings).run()
            if ("TT306" in rules
                    and fi.name not in set(getattr(
                        ctx.config, "fence_helpers", []))):
                _ResidentChecker(facts, fi.module.rel, findings).run()
    return out


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    cache = getattr(ctx, "interproc_findings", None)
    if cache is None:
        sources = getattr(ctx, "sources", None) \
            or [(path, path, tree, src)]
        proj = Project(sources, ctx.config)
        cache = _analyze_project(proj, ctx)
        ctx.interproc_findings = cache
    return list(cache.get(path, []))
