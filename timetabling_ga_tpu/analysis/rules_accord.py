"""TT307 — device collectives banned on the recovery/agreement path.

The tt-accord contract (runtime/control_channel.py): after a fault,
the collective program is poisoned on at least one process, so any
code that decides WHAT to do about the fault — the control side
channel itself, and the Supervisor's recovery policy — must be pure
host-side. A device collective (`lax.psum`/`ppermute`/`all_gather`
family) or any `multihost_utils.*` call (`broadcast_one_to_all`,
`process_allgather` — sugar over the same collectives) on that path
recreates the exact hang the channel exists to prevent: the faulted
or dead peer never reaches the rendezvous.

Two scopes:

  - ACCORD MODULES (`accord-modules` in pyproject, path suffix match —
    runtime/control_channel.py): the whole file is the side channel;
    importing `multihost_utils` there is already a finding, not just
    calling it.
  - `*Supervisor` CLASS BODIES in any analyzed file: the recovery
    policy surface (classify / agree_on_fault / snapshot / the
    ladder). dispatch_core.Supervisor is the instance; the rule keys
    on the class-name suffix so ports and test doubles inherit the
    discipline.

The run loop's HEALTHY-path collectives (dispatch_core.fetch's
allgather, guarded through the channel) are out of scope — they are
the program, not the recovery decision about the program.
"""

from __future__ import annotations

import ast

from timetabling_ga_tpu.analysis.core import (
    Finding, qual_matches, qualname)

RULE = "TT307"

# the jax collective family: launching any of these requires every
# process to arrive — the rendezvous a faulted peer never reaches
_COLLECTIVE_CALLEES = {
    "lax.psum", "lax.pmean", "lax.pmax", "lax.pmin", "lax.ppermute",
    "lax.pshuffle", "lax.all_gather", "lax.all_to_all",
    "lax.pbroadcast", "lax.psum_scatter",
    "psum", "pmean", "pmax", "pmin", "ppermute", "all_gather",
    "all_to_all", "pbroadcast", "psum_scatter",
}

# multihost_utils sugar over the same collectives
_MULTIHOST_CALLEES = {
    "broadcast_one_to_all", "process_allgather", "sync_global_devices",
}


def _accord_module(path: str, ctx) -> bool:
    rel = path.replace("\\", "/")
    modules = getattr(ctx.config, "accord_modules",
                      ["runtime/control_channel.py"])
    return any(m in rel for m in modules)


def _violation(node: ast.Call) -> str | None:
    """The banned callee's display name, or None."""
    qn = qualname(node.func)
    if qn is not None and "multihost_utils" in qn.split("."):
        return qn
    if qual_matches(qn, _MULTIHOST_CALLEES):
        return qn
    if qual_matches(qn, _COLLECTIVE_CALLEES):
        return qn
    return None


def _check_body(root: ast.AST, path: str, where: str,
                findings: list) -> None:
    for node in ast.walk(root):
        if not isinstance(node, ast.Call):
            continue
        name = _violation(node)
        if name is not None:
            findings.append(Finding(
                RULE, path, node.lineno, node.col_offset,
                f"device collective `{name}(...)` on the "
                f"recovery/agreement path ({where}) — after a fault "
                f"the collective program is poisoned on at least one "
                f"process, so a collective here hangs at the "
                f"rendezvous the faulted peer never reaches; recovery "
                f"must ride the host-side control channel "
                f"(runtime/control_channel.py, TT307)"))


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    findings: list[Finding] = []
    if _accord_module(path, ctx):
        # the whole file is the side channel: even IMPORTING the
        # collective sugar there signals the discipline is breaking
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                names = [a.name for a in node.names]
                if (node.module and "multihost_utils" in node.module) \
                        or "multihost_utils" in names:
                    findings.append(Finding(
                        RULE, path, node.lineno, node.col_offset,
                        "`multihost_utils` imported inside an accord "
                        "module — the control side channel must stay "
                        "host-side; device-collective sugar has no "
                        "business here (TT307)"))
            elif isinstance(node, ast.Import):
                if any("multihost_utils" in a.name for a in node.names):
                    findings.append(Finding(
                        RULE, path, node.lineno, node.col_offset,
                        "`multihost_utils` imported inside an accord "
                        "module — the control side channel must stay "
                        "host-side; device-collective sugar has no "
                        "business here (TT307)"))
        _check_body(tree, path, "accord module", findings)
        return findings
    # everywhere else: only *Supervisor class bodies (the recovery
    # policy surface) are audited
    for node in ast.walk(tree):
        if (isinstance(node, ast.ClassDef)
                and node.name.endswith("Supervisor")):
            _check_body(node, path,
                        f"`{node.name}` recovery policy", findings)
    return findings
