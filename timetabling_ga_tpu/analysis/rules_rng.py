"""TT401 — PRNG key reuse.

A JAX PRNG key passed to two consumers without an intervening
`jax.random.split` / `fold_in` gives both consumers IDENTICAL
randomness — island populations that mirror each other, mutation
streams that repeat — with no runtime error to catch it.

The analysis is a linear per-function scan. Key names are seeded from
`jax.random.key/PRNGKey/split/fold_in` results and key-looking
parameters. Consumption sites are call sites (a loop re-executing ONE
site with varying fold_in data is the sanctioned pattern and does not
flag). `x, key = jax.random.split(key)` consumes and rebinds
atomically. `fold_in(key, c)` derives rather than consumes, but two
fold_in sites folding the SAME literal constant collide and flag.
Subscripts of split-produced key arrays (`keys[3]`) are tracked per
literal index. Callees in `rng_exempt_callees` (checkpoint writers)
receive keys without consuming randomness.
"""

from __future__ import annotations

import ast
import re

from timetabling_ga_tpu.analysis.core import (
    Finding, func_params, qualname, target_names)

RULE = "TT401"

_KEY_MAKERS = {"key", "PRNGKey", "split", "fold_in", "wrap_key_data"}


def _rng_call_kind(call: ast.Call) -> str | None:
    """'split' | 'fold_in' | 'make' for jax.random.* calls, else None."""
    qn = qualname(call.func)
    if qn is None:
        return None
    parts = qn.split(".")
    tail = parts[-1]
    if tail not in _KEY_MAKERS:
        return None
    # accept jax.random.split / random.split / jr.split / bare PRNGKey
    if len(parts) >= 2 and parts[-2] not in ("random", "jax", "jr",
                                             "jrandom"):
        return None
    if tail in ("split", "fold_in"):
        return tail
    return "make"


class _Scan:
    def __init__(self, fn, path, ctx, findings):
        self.fn = fn
        self.path = path
        self.findings = findings
        self.exempt = set(ctx.config.rng_exempt_callees)
        param_re = re.compile(ctx.config.rng_param_pattern)
        params = func_params(fn) if not isinstance(fn, ast.Module) else []
        # name -> True once consumed since last (re)bind
        self.consumed: dict[str, bool] = {
            p: False for p in params if param_re.search(p)}
        # (name, fold literal) and (name, subscript literal) seen
        self.folds: set[tuple[str, object]] = set()
        self.subs: set[tuple[str, object]] = set()

    def is_key(self, name: str) -> bool:
        return name in self.consumed

    def _flag(self, node, name, why):
        self.findings.append(Finding(
            RULE, self.path, node.lineno, node.col_offset,
            f"PRNG key `{name}` {why} — split/fold_in a fresh subkey "
            f"per consumer (reused keys give identical randomness)"))

    def _bind(self, target_name: str):
        self.consumed[target_name] = False

    def _consume(self, node, name):
        if self.consumed.get(name):
            self._flag(node, name,
                       "passed to a second consumer without an "
                       "intervening jax.random.split/fold_in")
        self.consumed[name] = True

    def _handle_call(self, call: ast.Call, rebound: set[str]):
        kind = _rng_call_kind(call)
        qn = qualname(call.func) or ""
        callee_tail = qn.rsplit(".", 1)[-1]
        if kind is None and callee_tail in self.exempt:
            return
        args = list(call.args) + [kw.value for kw in call.keywords]
        for i, arg in enumerate(args):
            if isinstance(arg, ast.Name) and self.is_key(arg.id):
                if kind == "fold_in" and i == 0:
                    data = args[1] if len(args) > 1 else None
                    if isinstance(data, ast.Constant):
                        fk = (arg.id, repr(data.value))
                        if fk in self.folds:
                            self._flag(
                                call, arg.id,
                                f"folded with the same constant "
                                f"{data.value!r} at a second site")
                        self.folds.add(fk)
                    # non-constant fold data: derivation, assumed fresh
                elif kind == "split" and i == 0:
                    if arg.id in rebound:
                        # `k2, key = split(key)`: atomic consume+rebind
                        pass
                    else:
                        self._consume(call, arg.id)
                else:
                    self._consume(call, arg.id)
            elif (isinstance(arg, ast.Subscript)
                  and isinstance(arg.value, ast.Name)
                  and self.is_key(arg.value.id)
                  and isinstance(arg.slice, ast.Constant)):
                sk = (arg.value.id, repr(arg.slice.value))
                if sk in self.subs:
                    self._flag(call, arg.value.id,
                               f"element [{arg.slice.value!r}] consumed "
                               f"at a second site")
                self.subs.add(sk)

    def _visit_calls(self, node, rebound: set[str]):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._handle_call(sub, rebound)

    def run(self):
        body = self.fn.body if isinstance(self.fn.body, list) else []
        self._stmts(body)

    def _stmts(self, stmts):
        for st in stmts:
            self._stmt(st)

    def _stmt(self, st):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # each function is scanned in its own scope
        if isinstance(st, ast.Assign):
            rebound = set()
            for tgt in st.targets:
                rebound |= set(target_names(tgt))
            self._visit_calls(st.value, rebound & set(self.consumed))
            is_rng = (isinstance(st.value, ast.Call)
                      and _rng_call_kind(st.value) is not None)
            for name in rebound:
                if is_rng:
                    self._bind(name)
                elif name in self.consumed:
                    # rebound to a non-key value: stop tracking
                    del self.consumed[name]
                    self.folds = {f for f in self.folds if f[0] != name}
                    self.subs = {s for s in self.subs if s[0] != name}
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign, ast.Expr,
                             ast.Return, ast.Raise)):
            if getattr(st, "value", None) is not None:
                self._visit_calls(st.value, set())
        elif isinstance(st, ast.If):
            # mutually exclusive branches each get the pre-branch state;
            # afterwards a key counts consumed if EITHER branch consumed
            # it (so later reuse still flags, but one consumption per
            # exclusive branch does not)
            self._visit_calls(st.test, set())
            saved = (dict(self.consumed), set(self.folds), set(self.subs))
            self._stmts(st.body)
            after_body = (self.consumed, self.folds, self.subs)
            self.consumed, self.folds, self.subs = (
                dict(saved[0]), set(saved[1]), set(saved[2]))
            self._stmts(st.orelse)
            merged = {}
            for name in set(after_body[0]) & set(self.consumed):
                merged[name] = after_body[0][name] or self.consumed[name]
            self.consumed = merged
            self.folds |= after_body[1]
            self.subs |= after_body[2]
        elif isinstance(st, ast.While):
            self._visit_calls(st.test, set())
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.For):
            self._visit_calls(st.iter, set())
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.With):
            for item in st.items:
                self._visit_calls(item.context_expr, set())
            self._stmts(st.body)
        elif isinstance(st, ast.Try):
            self._stmts(st.body)
            for h in st.handlers:
                self._stmts(h.body)
            self._stmts(st.orelse)
            self._stmts(st.finalbody)
        elif isinstance(st, ast.Assert):
            self._visit_calls(st.test, set())


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    findings: list[Finding] = []
    scopes = [tree] + [n for n in ast.walk(tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
    for scope in scopes:
        _Scan(scope, path, ctx, findings).run()
    return findings
