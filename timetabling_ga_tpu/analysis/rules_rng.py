"""TT401 / TT402 — PRNG key discipline.

TT401 — key reuse. A JAX PRNG key passed to two consumers without an
intervening `jax.random.split` / `fold_in` gives both consumers
IDENTICAL randomness — island populations that mirror each other,
mutation streams that repeat — with no runtime error to catch it.

The analysis is a linear per-function scan. Key names are seeded from
`jax.random.key/PRNGKey/split/fold_in` results and key-looking
parameters. Consumption sites are call sites (a loop re-executing ONE
site with varying fold_in data is the sanctioned pattern and does not
flag). `x, key = jax.random.split(key)` consumes and rebinds
atomically. `fold_in(key, c)` derives rather than consumes, but two
fold_in sites folding the SAME literal constant collide and flag.
Subscripts of split-produced key arrays (`keys[3]`) are tracked per
literal index. Callees in `rng_exempt_callees` (checkpoint writers)
receive keys without consuming randomness.

TT402 — loop-carried key reuse: the blind spot TT401's per-site model
leaves open. ONE call site consuming the same key name across `for`
iterations executes many times, but is a single site, so TT401 never
fires — yet every iteration draws identical randomness (N "independent"
restarts that are all the same restart). Sanctioned forms: the key is
rebound inside the loop body by a split/fold_in chain (`key, k =
split(key)`), or the consumption is `fold_in(key, i)` with data that
depends on a loop variable. Only bare key NAMES are tracked — warm-up
code deliberately replaying a subkey array element (`wk[4]`) across
config variants is compile warm-up, not a randomness bug.
"""

from __future__ import annotations

import ast
import re

from timetabling_ga_tpu.analysis.core import (
    Finding, func_params, qualname, target_names)

RULE = "TT401"

_KEY_MAKERS = {"key", "PRNGKey", "split", "fold_in", "wrap_key_data"}


def _rng_call_kind(call: ast.Call) -> str | None:
    """'split' | 'fold_in' | 'make' for jax.random.* calls, else None."""
    qn = qualname(call.func)
    if qn is None:
        return None
    parts = qn.split(".")
    tail = parts[-1]
    if tail not in _KEY_MAKERS:
        return None
    # accept jax.random.split / random.split / jr.split / bare PRNGKey
    if len(parts) >= 2 and parts[-2] not in ("random", "jax", "jr",
                                             "jrandom"):
        return None
    if tail in ("split", "fold_in"):
        return tail
    return "make"


class _Scan:
    def __init__(self, fn, path, ctx, findings):
        self.fn = fn
        self.path = path
        self.findings = findings
        self.exempt = set(ctx.config.rng_exempt_callees)
        param_re = re.compile(ctx.config.rng_param_pattern)
        params = func_params(fn) if not isinstance(fn, ast.Module) else []
        # name -> True once consumed since last (re)bind
        self.consumed: dict[str, bool] = {
            p: False for p in params if param_re.search(p)}
        # (name, fold literal) and (name, subscript literal) seen
        self.folds: set[tuple[str, object]] = set()
        self.subs: set[tuple[str, object]] = set()

    def is_key(self, name: str) -> bool:
        return name in self.consumed

    def _flag(self, node, name, why):
        self.findings.append(Finding(
            RULE, self.path, node.lineno, node.col_offset,
            f"PRNG key `{name}` {why} — split/fold_in a fresh subkey "
            f"per consumer (reused keys give identical randomness)"))

    def _bind(self, target_name: str):
        self.consumed[target_name] = False

    def _consume(self, node, name):
        if self.consumed.get(name):
            self._flag(node, name,
                       "passed to a second consumer without an "
                       "intervening jax.random.split/fold_in")
        self.consumed[name] = True

    def _handle_call(self, call: ast.Call, rebound: set[str]):
        kind = _rng_call_kind(call)
        qn = qualname(call.func) or ""
        callee_tail = qn.rsplit(".", 1)[-1]
        if kind is None and callee_tail in self.exempt:
            return
        args = list(call.args) + [kw.value for kw in call.keywords]
        for i, arg in enumerate(args):
            if isinstance(arg, ast.Name) and self.is_key(arg.id):
                if kind == "fold_in" and i == 0:
                    data = args[1] if len(args) > 1 else None
                    if isinstance(data, ast.Constant):
                        fk = (arg.id, repr(data.value))
                        if fk in self.folds:
                            self._flag(
                                call, arg.id,
                                f"folded with the same constant "
                                f"{data.value!r} at a second site")
                        self.folds.add(fk)
                    # non-constant fold data: derivation, assumed fresh
                elif kind == "split" and i == 0:
                    if arg.id in rebound:
                        # `k2, key = split(key)`: atomic consume+rebind
                        pass
                    else:
                        self._consume(call, arg.id)
                else:
                    self._consume(call, arg.id)
            elif (isinstance(arg, ast.Subscript)
                  and isinstance(arg.value, ast.Name)
                  and self.is_key(arg.value.id)
                  and isinstance(arg.slice, ast.Constant)):
                sk = (arg.value.id, repr(arg.slice.value))
                if sk in self.subs:
                    self._flag(call, arg.value.id,
                               f"element [{arg.slice.value!r}] consumed "
                               f"at a second site")
                self.subs.add(sk)

    def _visit_calls(self, node, rebound: set[str]):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._handle_call(sub, rebound)

    def run(self):
        body = self.fn.body if isinstance(self.fn.body, list) else []
        self._stmts(body)

    def _stmts(self, stmts):
        for st in stmts:
            self._stmt(st)

    def _stmt(self, st):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # each function is scanned in its own scope
        if isinstance(st, ast.Assign):
            rebound = set()
            for tgt in st.targets:
                rebound |= set(target_names(tgt))
            self._visit_calls(st.value, rebound & set(self.consumed))
            is_rng = (isinstance(st.value, ast.Call)
                      and _rng_call_kind(st.value) is not None)
            for name in rebound:
                if is_rng:
                    self._bind(name)
                elif name in self.consumed:
                    # rebound to a non-key value: stop tracking
                    del self.consumed[name]
                    self.folds = {f for f in self.folds if f[0] != name}
                    self.subs = {s for s in self.subs if s[0] != name}
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign, ast.Expr,
                             ast.Return, ast.Raise)):
            if getattr(st, "value", None) is not None:
                self._visit_calls(st.value, set())
        elif isinstance(st, ast.If):
            # mutually exclusive branches each get the pre-branch state;
            # afterwards a key counts consumed if EITHER branch consumed
            # it (so later reuse still flags, but one consumption per
            # exclusive branch does not)
            self._visit_calls(st.test, set())
            saved = (dict(self.consumed), set(self.folds), set(self.subs))
            self._stmts(st.body)
            after_body = (self.consumed, self.folds, self.subs)
            self.consumed, self.folds, self.subs = (
                dict(saved[0]), set(saved[1]), set(saved[2]))
            self._stmts(st.orelse)
            merged = {}
            for name in set(after_body[0]) & set(self.consumed):
                merged[name] = after_body[0][name] or self.consumed[name]
            self.consumed = merged
            self.folds |= after_body[1]
            self.subs |= after_body[2]
        elif isinstance(st, ast.While):
            self._visit_calls(st.test, set())
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.For):
            self._visit_calls(st.iter, set())
            if (isinstance(st.iter, ast.Call)
                    and _rng_call_kind(st.iter) is not None):
                # `for key in jax.random.split(key, n):` — the target
                # is a fresh subkey every iteration; treat it as a
                # rebind so body consumption does not read as reuse
                for name in target_names(st.target):
                    self._bind(name)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.With):
            for item in st.items:
                self._visit_calls(item.context_expr, set())
            self._stmts(st.body)
        elif isinstance(st, ast.Try):
            self._stmts(st.body)
            for h in st.handlers:
                self._stmts(h.body)
            self._stmts(st.orelse)
            self._stmts(st.finalbody)
        elif isinstance(st, ast.Assert):
            self._visit_calls(st.test, set())


RULE_LOOP = "TT402"


def _scope_key_names(scope, ctx) -> set[str]:
    """Key-looking names in one scope: parameters matching the
    configured pattern plus names bound from rng make/split/fold_in
    calls (same seeding as TT401's scan, without the linear state)."""
    param_re = re.compile(ctx.config.rng_param_pattern)
    names = {p for p in (func_params(scope)
                         if not isinstance(scope, ast.Module) else [])
             if param_re.search(p)}
    for node in _scope_walk(scope):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _rng_call_kind(node.value) is not None):
            for tgt in node.targets:
                names |= set(target_names(tgt))
    return names


def _scope_walk(scope):
    """Walk a scope's nodes without descending into nested functions
    (they are their own scopes)."""
    todo = list(ast.iter_child_nodes(scope))
    while todo:
        node = todo.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            todo.extend(ast.iter_child_nodes(node))


def _names_under(node) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _check_loop_keys(scope, path, ctx, findings):
    keys = _scope_key_names(scope, ctx)
    if not keys:
        return
    exempt = set(ctx.config.rng_exempt_callees)
    for loop in _scope_walk(scope):
        if not isinstance(loop, ast.For):
            continue
        loop_vars = set(target_names(loop.target))
        # keys the body rebinds from an rng chain are sanctioned: every
        # iteration advances the stream before consuming it
        rebound: set[str] = set()
        for node in ast.walk(loop):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and _rng_call_kind(node.value) is not None):
                for tgt in node.targets:
                    rebound |= set(target_names(tgt))
        # names DERIVED from a loop variable (`step = i * 2 + 1`) vary
        # per iteration just like the loop variable itself: fold_in on
        # one is the sanctioned pattern too. Transitive closure over
        # the body's assignments, to a fixpoint.
        derived = set(loop_vars)
        changed = True
        while changed:
            changed = False
            for node in ast.walk(loop):
                if (isinstance(node, ast.Assign)
                        and _names_under(node.value) & derived):
                    for tgt in node.targets:
                        for nm in target_names(tgt):
                            if nm not in derived:
                                derived.add(nm)
                                changed = True
        flagged: set[str] = set()
        for call in ast.walk(loop):
            if not isinstance(call, ast.Call):
                continue
            kind = _rng_call_kind(call)
            if kind == "make":
                continue              # fresh key construction
            qn = qualname(call.func) or ""
            if kind is None and qn.rsplit(".", 1)[-1] in exempt:
                continue
            args = list(call.args) + [kw.value for kw in call.keywords]
            for i, arg in enumerate(args):
                if not (isinstance(arg, ast.Name) and arg.id in keys):
                    continue
                name = arg.id
                if name in rebound or name in flagged:
                    continue
                if name in loop_vars:
                    # `for key in jax.random.split(key, n):` — the loop
                    # target is a fresh value every iteration by
                    # construction
                    continue
                if kind == "fold_in" and i == 0:
                    data = args[1] if len(args) > 1 else None
                    if data is not None and (_names_under(data)
                                             & derived):
                        continue      # fold_in on the loop index (or a
                        #               value derived from it): THE
                        #               sanctioned pattern
                flagged.add(name)
                findings.append(Finding(
                    RULE_LOOP, path, call.lineno, call.col_offset,
                    f"PRNG key `{name}` consumed at this site on every "
                    f"iteration of the enclosing `for` loop without "
                    f"fold_in on the loop variable or a split rebind — "
                    f"each iteration draws identical randomness"))


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    findings: list[Finding] = []
    scopes = [tree] + [n for n in ast.walk(tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
    for scope in scopes:
        if "TT401" in ctx.config.rules:
            _Scan(scope, path, ctx, findings).run()
        if "TT402" in ctx.config.rules:
            _check_loop_keys(scope, path, ctx, findings)
    return findings
