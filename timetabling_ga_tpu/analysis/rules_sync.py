"""TT301/TT302 — hidden host-device syncs and hidden collectives.

TT301 — hidden host-device synchronization in dispatch loops.

Inside the host orchestration loops of the configured dispatch modules
(runtime/engine.py, parallel/islands.py by default), `.item()`,
`.tolist()`, `float()`, `int()`, `bool()`, `np.asarray()` / `np.array()`
on a device array each cost a full device round trip — multi-second on
tunneled devices — and serialize the dispatch pipeline. All readbacks
must route through the sanctioned fetch helpers (`_fetch` /
`_fetch_final`), which batch the round trip and are exempt.

Device-value taint is seeded from compiled-program producers (callees
matching `device_producers`, default `cached_*` / `jax.jit`): a name
bound from calling such a program is a device array; `_fetch(x)` clears
the taint (its result is host memory).

TT302 — hidden cross-device collectives from shuffle-by-sort random
ops. In code that runs inside `shard_map` bodies (the configured
`sharded_modules`, default ops/ and parallel/), `jax.random.
permutation` / `shuffle` / `choice` lower through a sort whose operand
XLA's SPMD partitioner replicates across the mesh with masked
all-reduces — collectives inside per-island programs that silently
merge the islands' random streams AND deadlock the CPU backend when a
surrounding data-dependent while_loop's trip counts diverge (one device
exits, the other waits at the rendezvous forever). Use elementwise
constructions instead: affine index permutations, `lax.top_k` over iid
uniforms, `jax.random.categorical`.
"""

from __future__ import annotations

import ast
import re

from timetabling_ga_tpu.analysis.core import (
    Finding, qual_matches, qualname, target_names)

RULE = "TT301"
RULE_COLLECTIVE = "TT302"

_COLLECTIVE_RANDOM_CALLS = {
    "jax.random.permutation", "random.permutation",
    "jax.random.shuffle", "random.shuffle",
    "jax.random.choice", "random.choice",
}

_CONVERT_CALLS = {"float", "int", "bool"}
_NUMPY_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                "jax.device_get"}
_SYNC_METHODS = {"item", "tolist"}


class _FuncChecker:
    def __init__(self, fn, path, ctx, findings):
        self.fn = fn
        self.path = path
        self.findings = findings
        self.sync_helpers = set(ctx.config.sync_helpers)
        self.producer_res = [re.compile(p)
                             for p in ctx.config.device_producers]
        self.programs: set[str] = set()   # names of compiled programs
        self.device: set[str] = set()     # names holding device arrays

    def _is_producer(self, call: ast.Call) -> bool:
        qn = qualname(call.func)
        if qn is not None and any(r.match(qn) for r in self.producer_res):
            return True
        # nested: cached_init(...)(args) — calling a producer's result
        if isinstance(call.func, ast.Call):
            return self._is_producer(call.func)
        return False

    def _is_sync_helper_call(self, call: ast.Call) -> bool:
        qn = qualname(call.func)
        return (qn is not None
                and qn.rsplit(".", 1)[-1] in self.sync_helpers)

    def value_kind(self, node: ast.AST) -> str:
        """'device' | 'host' for an expression."""
        if isinstance(node, ast.Name):
            return "device" if node.id in self.device else "host"
        if isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
            return self.value_kind(node.value)
        if isinstance(node, ast.Call):
            if self._is_sync_helper_call(node):
                return "host"
            qn = qualname(node.func)
            if (self._is_producer(node)
                    or (qn is not None
                        and qn.rsplit(".", 1)[-1] in self.programs)
                    or (isinstance(node.func, ast.Name)
                        and node.func.id in self.programs)):
                return "device"
            if any(self.value_kind(a) == "device"
                   for a in list(node.args)
                   + [kw.value for kw in node.keywords]):
                return "device"
            if (isinstance(node.func, ast.Attribute)
                    and self.value_kind(node.func.value) == "device"):
                return "device"   # method call on a device array
            return "host"
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self.value_kind(node.elt)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                if self.value_kind(child) == "device":
                    return "device"
        return "host"

    def _bind(self, target: ast.AST, kind_device: bool, program: bool):
        for name in target_names(target):
            if program:
                self.programs.add(name)
                self.device.discard(name)
            elif kind_device:
                self.device.add(name)
                self.programs.discard(name)
            else:
                self.device.discard(name)
                self.programs.discard(name)

    def _flag(self, node, what):
        self.findings.append(Finding(
            RULE, self.path, node.lineno, node.col_offset,
            f"hidden host-device sync: {what} on a device array inside a "
            f"dispatch loop — batch the readback through the sanctioned "
            f"fetch helper instead"))

    def _check_expr_for_syncs(self, node: ast.AST, in_loop: bool):
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            qn = qualname(sub.func)
            if (isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _SYNC_METHODS
                    and self.value_kind(sub.func.value) == "device"):
                self._flag(sub, f"`.{sub.func.attr}()`")
            elif (qn in _CONVERT_CALLS and in_loop and sub.args
                    and self.value_kind(sub.args[0]) == "device"):
                self._flag(sub, f"`{qn}()`")
            elif (qual_matches(qn, _NUMPY_CALLS) and in_loop and sub.args
                    and self.value_kind(sub.args[0]) == "device"):
                self._flag(sub, f"`{qn}()`")

    def run(self):
        self._stmts(self.fn.body, in_loop=False)

    def _stmts(self, stmts, in_loop):
        for st in stmts:
            self._stmt(st, in_loop)

    def _stmt(self, st, in_loop):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return
        if isinstance(st, ast.Assign):
            self._check_expr_for_syncs(st.value, in_loop)
            if isinstance(st.value, ast.Call) and self._is_producer(
                    st.value) and not isinstance(st.value.func, ast.Call):
                for tgt in st.targets:
                    self._bind(tgt, False, program=True)
            else:
                kind = self.value_kind(st.value)
                for tgt in st.targets:
                    self._bind(tgt, kind == "device", program=False)
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            if st.value is not None:
                self._check_expr_for_syncs(st.value, in_loop)
        elif isinstance(st, (ast.Expr, ast.Return)):
            if st.value is not None:
                self._check_expr_for_syncs(st.value, in_loop)
        elif isinstance(st, (ast.If, ast.While)):
            self._check_expr_for_syncs(st.test, in_loop)
            inner = in_loop or isinstance(st, ast.While)
            self._stmts(st.body, inner)
            self._stmts(st.orelse, inner)
        elif isinstance(st, ast.For):
            self._check_expr_for_syncs(st.iter, in_loop)
            self._stmts(st.body, True)
            self._stmts(st.orelse, in_loop)
        elif isinstance(st, ast.With):
            self._stmts(st.body, in_loop)
        elif isinstance(st, ast.Try):
            self._stmts(st.body, in_loop)
            for h in st.handlers:
                self._stmts(h.body, in_loop)
            self._stmts(st.orelse, in_loop)
            self._stmts(st.finalbody, in_loop)
        elif isinstance(st, (ast.Raise, ast.Assert)):
            pass


def _check_collective_randoms(tree: ast.Module, path: str, ctx
                              ) -> list[Finding]:
    norm = path.replace("\\", "/")
    if not any(part in norm for part in ctx.config.sharded_modules):
        return []
    findings = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and qual_matches(qualname(node.func),
                                 _COLLECTIVE_RANDOM_CALLS)):
            name = qualname(node.func)
            findings.append(Finding(
                RULE_COLLECTIVE, path, node.lineno, node.col_offset,
                f"`{name}` in shard_map-executed code lowers through a "
                f"sort the SPMD partitioner replicates with cross-device "
                f"all-reduces — merged island RNG streams and a CPU-"
                f"backend deadlock under varying while_loop trip counts; "
                f"use an affine permutation / lax.top_k of uniforms / "
                f"jax.random.categorical instead"))
    return findings


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    findings: list[Finding] = []
    if "TT302" in ctx.config.rules:
        findings += _check_collective_randoms(tree, path, ctx)
    norm = path.replace("\\", "/")
    if "TT301" in ctx.config.rules and any(
            norm.endswith(suffix)
            for suffix in ctx.config.dispatch_modules):
        sync_helpers = set(ctx.config.sync_helpers)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name in sync_helpers:
                    continue  # the sanctioned sync points themselves
                _FuncChecker(node, path, ctx, findings).run()
    return findings
