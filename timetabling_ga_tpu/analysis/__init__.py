"""tt-analyze — JAX-aware static analysis for this codebase.

Usage:
    python -m timetabling_ga_tpu.analysis [--strict] [--json] [--sarif]
        [--warn-unused-ignores] [paths...]

Rules (see README "Static analysis & sanitizers"):

  TT101  tracer-unsafe control flow in jit/vmap/shard_map/scan targets
  TT102  `and`/`or` short-circuit on traced values in the same targets
         (bool() on a tracer hidden in expression position)
  TT201  jax.jit static arguments receiving unhashable/run-varying values
  TT202  compile-cache dict keys omitting a value the program closes over
  TT203  donated-buffer reuse (donate_argnums args read after the
         jitted call — the buffer is deleted at dispatch)
  TT301  hidden host-device syncs inside dispatch loops
  TT302  collective-bearing random ops (permutation/shuffle/choice) in
         shard_map-executed code — replicated-sort all-reduces that
         merge island RNG streams and deadlock varying while_loops
  TT303  WHOLE-PROGRAM device taint (analysis/project.py): values a
         dispatch program produced in another module hitting a
         host-forcing sink — float()/int()/bool(), np.asarray,
         .item()/.tolist(), control-flow-steering comparisons — inside
         a dispatch loop; the sanctioned fetch helpers clear taint
  TT304  interprocedurally-donated buffer read after the donating
         dispatch — the cross-module upgrade of TT203: the factory
         declaring donate_argnums and the call site reading the dead
         buffer may live in different modules
  TT305  dispatch-fence discipline: a control host read must precede
         the next dispatch, telemetry must not — telemetry fetches
         fencing a later dispatch in the same loop iteration, and
         control flow steered through jax.block_until_ready instead
         of the sanctioned packed fetch
  TT306  host fetch of device-RESIDENT group state outside a park
         fence: a value rooted in a `resident_stores` attribute
         (serve/scheduler.py `_resident`) reaching a fetch helper or
         conversion sink anywhere but a `fence_helpers` flush body —
         bytes moving without the snapshot/ship units re-syncing
  TT401  PRNG key reuse (two consumers, no split/fold_in between)
  TT402  loop-carried key reuse (one call site consuming the same key
         across `for` iterations without fold_in on the loop index)
  TT501  JAX imports outside the pinned compatibility table (compat.py)
  TT502  jax.* ATTRIBUTE access outside the pinned table — the
         `jax.profiler.*` / `jax.distributed.*` uses TT501's import
         scanner cannot see
  TT601  wall-clock reads (time.time/monotonic/perf_counter) and span
         tracer calls inside trace targets — they execute at TRACE
         time and bake the compile's clock into the program; timing is
         host-side by design (tt-obs, README "Observability")
  TT602  blocking I/O and MetricsRegistry mutation reachable from HTTP
         handler code paths — the pull front's handlers (obs/http.py)
         must only READ registry snapshots and only write their own
         response socket; a scrape is a pure observer
  TT603  cost_analysis / memory_analysis / memory_stats calls inside
         trace targets or dispatch loops — host-sync (and recompile)
         hazards that belong in the obs paths only: the cost
         observatory (obs/cost.py) extracts analyses at compile time
         and polls memory_stats from its own thread
  TT604  quality accounting off device — population-evaluation calls
         (batch_penalty/evaluate/event_heat) inside dispatch-loop
         bodies, and collectives or collective-bearing random ops
         introduced in quality-reduction helpers (TT302-adjacent);
         the search-quality observatory ships packed on-device rows
         instead (obs/quality.py, parallel/islands.py)
  TT606  incident-bundle serialization / file I/O inside trace targets
         or dispatch loops, and flight-recorder dump triggers on HTTP
         handler paths — dumps belong on the recorder's own thread;
         handlers serve the in-memory `latest()`/history `window()`
         only (obs/flight.py, obs/history.py)
  TT607  usage-ledger mutation inside trace targets or on HTTP handler
         paths, and wall-clock reads on handler paths — the tt-meter
         ledger is fed from the scheduler's park fence and folded on
         its own thread; handlers READ the meter (`totals()`), and
         metering timestamps belong to the drive loop's fence
         brackets, never a scrape's (obs/usage.py)
  TT608  fleet actuator calls (spawn / preempt / adopt / process+port
         mutation) on HTTP handler paths or inside dispatcher-tick
         bodies — the tt-scale autoscaler thread is the only legal
         actuation site: handlers enqueue, the dispatcher executes
         enqueued commands, and replica-count decisions carry the
         policy's sustained-window evidence, cooldown, and warmth
         guard (fleet/autoscaler.py)

Suppress one finding inline with `# tt-analyze: ignore[TT301]` (on the
line, or on a comment line directly above); `--warn-unused-ignores`
reports markers that suppress nothing (TT901) so stale suppressions
cannot rot in place. Configure via `[tool.tt-analyze]` in
pyproject.toml. Exit status: 0, or 1 under --strict when findings
remain.

Every file is parsed exactly once per run; the parsed trees are shared
across all rules AND the whole-program layer (analysis/project.py), and
`--json` reports per-rule and total wall time so analyzer cost is
tracked like a bench leg.

Stdlib-only by design: linting must not require JAX or a device.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
import time

from timetabling_ga_tpu.analysis.config import (
    ALL_RULES, AnalyzerConfig, load_compat_table, load_config)
from timetabling_ga_tpu.analysis.core import (
    Finding, filter_suppressed, unused_suppressions)

__all__ = ["Finding", "AnalyzerConfig", "run_analysis", "main",
           "ALL_RULES"]


class _Context:
    """Per-run state shared by the rules: config, the pinned API
    table, and — set by run_analysis — the shared parsed sources the
    whole-program rules (TT303/TT304/TT305) build their Project
    from."""

    def __init__(self, config: AnalyzerConfig):
        self.config = config
        self.compat_table = load_compat_table(config)
        self.sources: list[tuple] = []        # (path, rel, tree, src)
        self.interproc_findings = None        # rules_interproc cache


def _rule_modules():
    from timetabling_ga_tpu.analysis import (
        rules_accord, rules_api, rules_cost, rules_donate,
        rules_edit, rules_fleet, rules_flight, rules_http,
        rules_interproc, rules_obs, rules_prof, rules_quality,
        rules_recompile, rules_rng, rules_scale, rules_sync,
        rules_trace, rules_usage)
    return {
        "TT101": rules_trace,
        "TT102": rules_trace,
        "TT201": rules_recompile,
        "TT202": rules_recompile,
        "TT203": rules_donate,
        "TT301": rules_sync,
        "TT302": rules_sync,
        "TT303": rules_interproc,
        "TT304": rules_interproc,
        "TT305": rules_interproc,
        "TT306": rules_interproc,
        "TT307": rules_accord,
        "TT309": rules_edit,
        "TT310": rules_prof,
        "TT401": rules_rng,
        "TT402": rules_rng,
        "TT501": rules_api,
        "TT502": rules_api,
        "TT601": rules_obs,
        "TT602": rules_http,
        "TT603": rules_cost,
        "TT604": rules_quality,
        "TT605": rules_fleet,
        "TT606": rules_flight,
        "TT607": rules_usage,
        "TT608": rules_scale,
    }


def _rule_docs() -> dict[str, str]:
    docs = {rule: (mod.__doc__ or rule).strip().splitlines()[0]
            for rule, mod in _rule_modules().items()}
    docs["TT000"] = "syntax error"
    docs["TT901"] = "unused `# tt-analyze: ignore` suppression marker"
    return docs


def _iter_py_files(paths, root):
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            yield full
        else:
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = sorted(d for d in dirnames
                                     if not d.startswith((".", "__pycache")))
                for fname in sorted(filenames):
                    if fname.endswith(".py"):
                        yield os.path.join(dirpath, fname)


def _rule_groups(config):
    """(label, module) pairs for the enabled rules, one entry per rule
    module, labels joining the rule ids the module implements."""
    mods = _rule_modules()
    groups: list[tuple[list[str], object]] = []
    for rule in config.rules:
        mod = mods.get(rule)
        if mod is None:
            continue
        for rules, m in groups:
            if m is mod:
                rules.append(rule)
                break
        else:
            groups.append(([rule], mod))
    return [("+".join(rules), mod) for rules, mod in groups]


def run_analysis(paths=None, config: AnalyzerConfig | None = None,
                 timings: dict | None = None) -> list[Finding]:
    """Analyze `paths` (files or directories); returns all findings.

    Single-parse: every file is read and parsed exactly once, and the
    trees are shared by all per-file rules and the whole-program layer.
    Pass a dict as `timings` to receive {"total_s", "per_rule_s"}.
    """
    if config is None:
        config = load_config(".")
    ctx = _Context(config)
    t_total = time.perf_counter()

    order: list[str] = []             # rel paths in walk order
    srcs: dict[str, str] = {}
    syntax_errors: dict[str, Finding] = {}
    for path in _iter_py_files(paths or config.paths, config.root):
        with open(path, encoding="utf-8") as f:
            src = f.read()
        rel = os.path.relpath(path, config.root)
        if rel.startswith(".."):
            rel = path
        order.append(rel)
        srcs[rel] = src
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            syntax_errors[rel] = Finding(
                "TT000", rel, e.lineno or 0, e.offset or 0,
                f"syntax error: {e.msg}")
            continue
        ctx.sources.append((path, rel, tree, src))

    per_file: dict[str, list[Finding]] = {rel: [] for rel in order}
    per_rule_s: dict[str, float] = {}
    for label, mod in _rule_groups(config):
        t0 = time.perf_counter()
        for _, rel, tree, src in ctx.sources:
            per_file[rel].extend(mod.check(tree, src, rel, ctx))
        per_rule_s[label] = round(time.perf_counter() - t0, 6)

    findings: list[Finding] = []
    enabled = set(config.rules)
    for rel in order:
        if rel in syntax_errors:
            findings.append(syntax_errors[rel])
            continue
        # rules sharing a module (TT201/TT202) can duplicate; dedupe
        # exactly, then keep only the enabled ids
        fs = sorted({f for f in per_file[rel] if f.rule in enabled},
                    key=lambda f: (f.line, f.col, f.rule))
        kept = filter_suppressed(fs, srcs[rel])
        if config.warn_unused_ignores:
            kept += unused_suppressions(fs, srcs[rel], rel)
        findings.extend(kept)

    if timings is not None:
        timings["per_rule_s"] = per_rule_s
        timings["total_s"] = round(time.perf_counter() - t_total, 6)
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tt-analyze",
        description="JAX-aware static analysis (tracer safety, recompile "
                    "hazards, host syncs, whole-program device taint, "
                    "RNG discipline, pinned API)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: [tool.tt-analyze] "
                         "paths)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 if any finding remains")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable JSON report on stdout "
                         "(includes per-rule wall time)")
    ap.add_argument("--sarif", action="store_true", dest="as_sarif",
                    help="SARIF 2.1.0 report on stdout (CI annotations)")
    ap.add_argument("--warn-unused-ignores", action="store_true",
                    help="report stale `# tt-analyze: ignore` markers "
                         "(TT901)")
    ap.add_argument("--root", default=".",
                    help="project root holding pyproject.toml")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to enable "
                         f"(default: all of {','.join(ALL_RULES)})")
    ap.add_argument("--list-rules", action="store_true",
                    help="print rule ids and one-line summaries")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, mod in sorted(_rule_modules().items()):
            doc = (mod.__doc__ or "").strip().splitlines()[0]
            print(f"{rule}  {doc}")
        return 0

    config = load_config(args.root)
    if args.rules:
        config.rules = [r.strip() for r in args.rules.split(",")]
    if args.warn_unused_ignores:
        config.warn_unused_ignores = True
    timings: dict = {}
    findings = run_analysis(args.paths or None, config, timings=timings)

    if args.as_sarif:
        from timetabling_ga_tpu.analysis.sarif import to_sarif
        print(json.dumps(to_sarif(findings, _rule_docs()), indent=2,
                         sort_keys=True))
    elif args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in findings],
            "count": len(findings),
            "strict": args.strict,
            "timing": timings,
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(f"tt-analyze: {n} finding{'s' if n != 1 else ''}",
              file=sys.stderr)
    return 1 if (args.strict and findings) else 0
