"""TT606 — incident-bundle serialization off the recorder thread.

The flight recorder's contract (obs/flight.py) has two sides:

  - DUMPS BELONG ON THE RECORDER THREAD. Bundle serialization and the
    file I/O around it (`json.dump`/`json.dumps` of bundle-sized
    payloads, `open`, `os.replace`/`os.rename`/`os.fsync`) are
    milliseconds-to-seconds of host work; inside a TRACE TARGET they
    execute at trace time (and bake a handle into the program), and
    inside a DISPATCH LOOP they serialize the pipeline the loops exist
    to keep full — the exact stall class TT301/TT603 ban for readbacks
    and introspection. The tee feeding the rings is O(1) appends on
    the writer thread; everything heavier runs where a hang is
    harmless.
  - HANDLERS ONLY READ. `GET /metrics/history` and `GET /v1/incident`
    serve lock-guarded in-memory state (`HistoryRing.window()`,
    `FlightRecorder.latest()`); a handler that TRIGGERS or PERFORMS a
    dump (`recorder.trigger(...)`, `flight.dump(...)`, `json.dump` to
    a file) turns a scrape storm into a disk storm and couples the
    observer to the observed — the TT602 discipline, extended to the
    flight surface (audited with the same `_reachable` walk over
    handler classes and `*Api` roots).

Scope: half 1 scans trace targets module-wide (TT601's collection)
plus For/While bodies in the configured dispatch modules (TT301's
scope); half 2 scans handler-reachable code everywhere. obs/flight.py
itself is exempt — it IS the sanctioned recorder-thread home.
"""

from __future__ import annotations

import ast
import re

from timetabling_ga_tpu.analysis.core import Finding, qual_matches, qualname
from timetabling_ga_tpu.analysis.rules_http import _reachable
from timetabling_ga_tpu.analysis.rules_trace import _collect_targets

RULE = "TT606"

# serialization / file-I/O callees that mean "a bundle is being built
# or written here" (tail-matched like TT602's blocking list)
_SERIALIZE_CALLEES = {"json.dump", "json.dumps",
                      "os.replace", "os.rename", "os.fsync"}

# handler-path receivers that ARE the flight recorder (a handler may
# read `latest()`; it must never trigger or perform a dump)
_RECORDER_RECV = re.compile(r"(^|\.)_?(flight|recorder)$", re.IGNORECASE)
_RECORDER_MUTATORS = {"trigger", "dump", "dump_now", "note_record",
                      "poll_once", "close"}

# modules whose own bodies are the sanctioned recorder/sampler home
_EXEMPT_SUFFIXES = ("obs/flight.py", "obs/history.py")


def _is_serialize_call(node: ast.Call) -> bool:
    qn = qualname(node.func)
    if qual_matches(qn, _SERIALIZE_CALLEES):
        return True
    return isinstance(node.func, ast.Name) and node.func.id == "open"


def _flag_hot(findings, path, node, where: str) -> None:
    qn = qualname(node.func) or "open"
    findings.append(Finding(
        RULE, path, node.lineno, node.col_offset,
        f"bundle serialization / file I/O `{qn}(...)` {where} — dumps "
        f"belong on the flight recorder's own thread (obs/flight.py): "
        f"serializing or writing on the dispatch stream stalls the "
        f"pipeline exactly like the readbacks TT301/TT603 ban"))


class _LoopScanner:
    """Flag serialization calls inside For/While bodies of a host
    function — the dispatch-loop half, scoped to the configured
    dispatch modules (the TT603 scanner's shape)."""

    def __init__(self, path, findings):
        self.path = path
        self.findings = findings

    def scan(self, fn: ast.AST) -> None:
        self._stmts(getattr(fn, "body", []), in_loop=False)

    def _check(self, node: ast.AST, in_loop: bool) -> None:
        if not in_loop:
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and _is_serialize_call(sub):
                _flag_hot(self.findings, self.path, sub,
                          "inside a dispatch loop")

    def _stmts(self, stmts, in_loop: bool) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.For, ast.While)):
                if isinstance(st, ast.While):
                    self._check(st.test, in_loop)
                else:
                    self._check(st.iter, in_loop)
                self._stmts(st.body, True)
                self._stmts(st.orelse, True)
                continue
            for field in ("value", "test", "iter"):
                v = getattr(st, field, None)
                if isinstance(v, ast.expr):
                    self._check(v, in_loop)
            for item in getattr(st, "items", []) or []:
                # `with open(...) as fh:` — the context expression is
                # where the file I/O call sits
                self._check(item.context_expr, in_loop)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(st, field, None)
                if isinstance(sub, list):
                    self._stmts(sub, in_loop)
            for h in getattr(st, "handlers", []) or []:
                self._stmts(h.body, in_loop)


def check(tree: ast.Module, src: str, path: str, ctx) -> list[Finding]:
    norm = path.replace("\\", "/")
    if norm.endswith(_EXEMPT_SUFFIXES):
        return []
    findings: list[Finding] = []
    # half 1a: trace targets, module-wide (anything lexically inside
    # traced code executes at trace time)
    for fn in _collect_targets(tree):
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and _is_serialize_call(node):
                _flag_hot(findings, path, node,
                          "inside a jit/vmap/shard_map target")
    # half 1b: dispatch loops, in the configured dispatch modules only
    if any(norm.endswith(suffix)
           for suffix in ctx.config.dispatch_modules):
        scanner = _LoopScanner(path, findings)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                scanner.scan(node)
    # half 2: handler paths (the TT602 reachability walk, including
    # the configured *Api roots) — a handler may only READ the flight
    # surface (`latest()`, `window()`), never trigger or perform a
    # dump, and never serialize a bundle to a file itself
    suffixes = tuple(getattr(ctx.config, "handler_api_suffixes",
                             ("Api",)))
    for where, fn in _reachable(tree, suffixes):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and f.attr in _RECORDER_MUTATORS
                    and (qn_recv := qualname(f.value)) is not None
                    and _RECORDER_RECV.search(qn_recv)):
                findings.append(Finding(
                    RULE, path, node.lineno, node.col_offset,
                    f"flight-recorder mutation `{qn_recv}.{f.attr}"
                    f"(...)` on the HTTP handler path `{where}` — "
                    f"handlers serve `latest()`/`window()` from "
                    f"memory; triggering or performing dumps from a "
                    f"handler couples scrapes to disk writes "
                    f"(obs/flight.py design rules)"))
                continue
            if qual_matches(qualname(f), {"json.dump"}):
                findings.append(Finding(
                    RULE, path, node.lineno, node.col_offset,
                    f"file serialization `json.dump(...)` on the HTTP "
                    f"handler path `{where}` — bundle writes belong "
                    f"on the recorder thread; handlers reply from the "
                    f"in-memory `latest()` copy (obs/flight.py)"))
    # a call can sit both in a loop and in a traced fn at one line;
    # dedupe by (line, col) like TT603
    seen: set = set()
    out = []
    for f in findings:
        k = (f.line, f.col)
        if k in seen:
            continue
        seen.add(k)
        out.append(f)
    return out
