"""ctypes bindings for the native C++ components (native/*.cpp).

Loads `libtimetabling_native.so` (built by `make -C native`; an
auto-build is attempted on first use). Exposes:

  - `eval_batch(problem, slots, rooms, threads)` — the C++ scalar
    evaluator over a population; an independent third implementation of
    the fitness semantics (JAX kernels, Python oracle, C++), used for
    cross-checking and as the CPU-side baseline in benchmarks.
  - `assign_rooms_batch(problem, slots)` — the C++ greedy matcher.

No pybind11 in this image, so the surface is a C ABI + ctypes
(per-project constraint); arrays cross as dense int32/int8 buffers.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libtimetabling_native.so")

_lib = None
_load_error: Optional[str] = None


def _try_load():
    global _lib, _load_error
    if _lib is not None or _load_error is not None:
        return
    # Always run make (a fresh build is a no-op): loading a stale .so
    # after editing the .cpp would silently validate old semantics.
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR],
                       capture_output=True, check=True, timeout=300)
    except Exception as e:
        if not os.path.exists(_LIB_PATH):
            _load_error = f"native build failed: {e}"
            return
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as e:
        _load_error = f"cannot load {_LIB_PATH}: {e}"
        return

    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    i8p = np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.tt_problem_create.restype = ctypes.c_void_p
    lib.tt_problem_create.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, i32p, i8p, i8p, i8p]
    lib.tt_problem_free.restype = None
    lib.tt_problem_free.argtypes = [ctypes.c_void_p]
    lib.tt_eval_batch.restype = ctypes.c_int
    lib.tt_eval_batch.argtypes = [
        ctypes.c_void_p, i32p, i32p, ctypes.c_int,
        i64p, i32p, i32p, ctypes.c_int]
    lib.tt_assign_rooms.restype = ctypes.c_int
    lib.tt_assign_rooms.argtypes = [
        ctypes.c_void_p, i32p, ctypes.c_int, i32p]
    _lib = lib


def is_available() -> bool:
    _try_load()
    return _lib is not None


def load_error() -> Optional[str]:
    _try_load()
    return _load_error


# Problem handles: parse+derive once per Problem object, freed with it.
_handles: dict = {}


def _handle(problem) -> int:
    key = id(problem)
    cached = _handles.get(key)
    if cached is not None:
        return cached
    h = _lib.tt_problem_create(
        problem.n_events, problem.n_rooms, problem.n_features,
        problem.n_students, problem.n_days, problem.slots_per_day,
        np.ascontiguousarray(problem.room_size, np.int32),
        np.ascontiguousarray(problem.attends, np.int8),
        np.ascontiguousarray(problem.room_features, np.int8),
        np.ascontiguousarray(problem.event_features, np.int8))
    if not h:
        # same bound the JAX matcher asserts (ops/rooms.py): the packed
        # room-preference key holds occupancy/cap_rank in 12-bit fields
        raise ValueError(
            f"native matcher requires E < 4096 and R < 4096, got "
            f"E={problem.n_events} R={problem.n_rooms}")
    _handles[key] = h
    import weakref
    weakref.finalize(problem, _free_handle, key, h)
    return h


def _free_handle(key, h):
    _handles.pop(key, None)
    if _lib is not None:
        _lib.tt_problem_free(h)


def eval_batch(problem, slots, rooms, threads: int = 1):
    """(P, E) int32 arrays -> (penalty int64, hcv int32, scv int32)."""
    _try_load()
    if _lib is None:
        raise RuntimeError(_load_error)
    slots = np.ascontiguousarray(slots, np.int32)
    rooms = np.ascontiguousarray(rooms, np.int32)
    P = slots.shape[0]
    pen = np.empty(P, np.int64)
    hcv = np.empty(P, np.int32)
    scv = np.empty(P, np.int32)
    rc = _lib.tt_eval_batch(_handle(problem), slots, rooms, P,
                            pen, hcv, scv, threads)
    if rc != 0:
        raise RuntimeError(f"tt_eval_batch failed: {rc}")
    return pen, hcv, scv


def assign_rooms_batch(problem, slots):
    """(P, E) slots -> (P, E) rooms via the C++ greedy matcher."""
    _try_load()
    if _lib is None:
        raise RuntimeError(_load_error)
    slots = np.ascontiguousarray(slots, np.int32)
    P = slots.shape[0]
    rooms = np.empty_like(slots)
    rc = _lib.tt_assign_rooms(_handle(problem), slots, P, rooms)
    if rc != 0:
        raise RuntimeError(f"tt_assign_rooms failed: {rc}")
    return rooms
