"""`tt stats` — human-readable summary of a JSONL record stream.

    tt stats run.jsonl

Answers the questions people were answering with jq one-liners: what
did each island/job converge to and how fast (best-so-far curve,
time-to-feasible), did the run recover from faults (sites, actions,
degradation levels), how long did serve jobs take (per-job latency from
their solution records), and what did the last metrics snapshot say.

For serve logs recorded with `--obs`, the jobEntry lifecycle and the
job-tagged spanEntry records additionally yield a per-job WALL-TIME
BREAKDOWN — where each job's latency went:

  queued      admission to its first pack (waiting for a lane)
  routed      the fleet gateway's placement leg (admit-at-gateway →
              accepted-by-replica: the `routed` span a gateway log
              carries per placed job — fleet/gateway.py, tt-obs v5)
  recovered   warm-start snapshot admission on a RESUMED job (the
              fleet-resume seam, serve/scheduler._admit_resumed):
              what a failed-over or preempted job paid to not replay
              — only present for resumed jobs
  packed      pack / resume / park spans it rode (the per-quantum
              host-side cost of the park/resume serving model)
  executing   its quantum spans (device time advancing the job)
  parked      everything else between admit and finalize — sitting as
              a host snapshot while co-tenants ran

with p50/p99 across jobs per component — the numbers that say whether
a slow service needs more lanes (queued), a faster gateway (routed),
bigger quanta (packed), or faster kernels (executing). Several inputs
concatenate (`tt stats gateway.jsonl replica*.jsonl` summarizes a
fleet's whole log set); each log's timestamps live in its OWN tracer
epoch, so the breakdown windows a job over its replica-side spans
only and adds the gateway leg as the clock-safe `routed` duration sum
(see `_job_breakdown`) — timestamps from different logs are never
differenced.

Gateway logs additionally yield a per-replica PLACEMENT summary from
the routeEntry records (tt-obs v5): placements per replica with the
router's hit/warm/miss affinity outcomes — `tt stats` answers "where
did my bucket land and was it warm" without a Perfetto round trip.

Stdlib-only and device-free, like the trace exporter.
"""

from __future__ import annotations

import json

from timetabling_ga_tpu.obs.trace_export import read_jsonl

FEASIBLE_LIMIT = 1_000_000


def _key(proc_id, job):
    return f"job {job}" if job is not None else f"island {proc_id}"


# span taxonomy feeding the per-job breakdown (scheduler.py span names
# + the gateway's placement leg, fleet/gateway.py)
_EXEC_SPANS = ("quantum",)
_PACKED_SPANS = ("pack", "resume", "park")   # init nests inside pack
_ROUTED_SPANS = ("routed",)                  # gateway admit→placed
_RECOVERED_SPANS = ("recover",)              # warm-start snapshot
#                                              admission on a resumed
#                                              job (the fleet-resume
#                                              seam, serve/scheduler
#                                              _admit_resumed)


def _pctl(vals, q):
    """Nearest-rank percentile over a sorted list (the same estimator
    the legacy latency line uses)."""
    return vals[min(len(vals) - 1, int(q * len(vals)))]


def _job_breakdown(spans) -> dict:
    """Per-job wall-time decomposition from job-tagged spans.

    A span tagged with a job LIST (a packed dispatch advancing many
    lanes) counts fully toward every listed job: each job really did
    spend that wall time inside the span — concurrency, not
    attribution error. `parked` is the remainder between admission and
    the job's last span: time spent as a host snapshot while
    co-tenants held the lanes.

    Clock discipline for fleet log sets: each log's `ts` is seconds
    since ITS tracer epoch, so gateway and replica timestamps must
    never be differenced. The time WINDOW (t0/end → total, queued,
    parked) is therefore computed from the replica-side spans alone
    (everything not `cat="fleet"`); the gateway leg enters as the
    `routed` component — a span-duration SUM, clock-safe by
    construction — added on top of the window, so `total ≈ e2e` and
    the printed identity `total = queued + routed + packed +
    executing + parked` holds (modulo the unprinted finalize sliver).
    A gateway-only log (no replica spans for the job) falls back to
    its own window, where the routed span IS inside and is subtracted
    from the remainder instead."""
    per: dict = {}
    for s in spans:
        j = s.get("job")
        ids = ([str(x) for x in j] if isinstance(j, list)
               else [str(j)] if j is not None else [])
        for jid in ids:
            per.setdefault(jid, []).append(s)
    out: dict = {}
    for jid, ss in sorted(per.items()):
        base = [s for s in ss if s.get("cat") != "fleet"] or ss
        in_window = base is ss       # gateway-only: routed inside
        # one SOURCE log for the window: a failed-over job has replica
        # spans in TWO logs with unrelated epochs (`_src` is stamped
        # by main_stats per input file). The authoritative leg is the
        # one that finalized — the dead replica's partial leg is the
        # copy the gateway's failover discarded; fall back to the
        # largest leg when no finalize survived. The replica-side
        # tallies (executing/packed/finalize) come from the same leg,
        # so the components describe the run the job's record stream
        # actually is; only `routed` sums across sources (the gateway
        # leg lives in its own log by construction).
        by_src: dict = {}
        for s in base:
            by_src.setdefault(s.get("_src", 0), []).append(s)
        if len(by_src) > 1:
            base = next(
                (grp for grp in by_src.values()
                 if any(s.get("name") == "finalize" for s in grp)),
                max(by_src.values(), key=len))
        t0 = min(float(s.get("ts", 0.0)) for s in base)
        end = max(float(s.get("ts", 0.0))
                  + max(0.0, float(s.get("dur", 0.0))) for s in base)
        base_total = max(0.0, end - t0)

        def tally(names, ss=base):
            return sum(max(0.0, float(s.get("dur", 0.0))) for s in ss
                       if s.get("name") in names)

        executing = tally(_EXEC_SPANS)
        packed = tally(_PACKED_SPANS)
        routed = tally(_ROUTED_SPANS, ss)   # the gateway leg: every
        #                                     placement round, summed
        recovered = tally(_RECOVERED_SPANS)  # snapshot unpack +
        #                                      rehydrate on resume —
        #                                      what a failed-over job
        #                                      paid to NOT replay
        work = _EXEC_SPANS + _PACKED_SPANS + _RECOVERED_SPANS \
            + (_ROUTED_SPANS if in_window else ())
        first_work = min(
            (float(s.get("ts", 0.0)) for s in base
             if s.get("name") in work), default=end)
        queued = max(0.0, first_work - t0)
        fin = tally(("finalize",))
        rest = max(0.0, base_total - queued - packed - executing
                   - recovered - fin
                   - (routed if in_window else 0.0))
        total = base_total if in_window else base_total + routed
        out[jid] = {"total": total, "queued": queued,
                    "routed": routed, "recovered": recovered,
                    "packed": packed, "executing": executing,
                    "parked": rest}
    return out


def summarize(records) -> str:
    """The `tt stats` report text for a list of record dicts."""
    curves: dict = {}       # stream key -> list of (best, time)
    solutions: dict = {}    # stream key -> solution record
    runs = []
    faults: list = []
    jobs: dict = {}         # job id -> lifecycle events
    spans: list = []        # spanEntry bodies (per-job breakdown)
    flight_spans: list = []  # flight_dump spans (incident section)
    routes: list = []       # routeEntry bodies (placement summary)
    compiles: list = []     # costEntry bodies (compile accounting)
    usage_recs: list = []   # whole records (obs/usage.py summarize)
    scale_recs: list = []   # whole records (fleet/autoscaler.py
    #                         summarize_entries — the tt-scale
    #                         decision log)
    quality_recs: list = []  # whole records (obs/quality.py summarize)
    prof_recs: list = []    # profEntry bodies (tt-prof attribution)
    counts: dict = {}
    last_metrics = None
    for rec in records:
        kind = next(iter(rec), None)
        counts[kind] = counts.get(kind, 0) + 1
        body = rec.get(kind)
        if kind == "logEntry":
            k = _key(body.get("procID"), body.get("job"))
            curves.setdefault(k, []).append(
                (body.get("best"), body.get("time", 0.0)))
        elif kind == "solution":
            solutions[_key(body.get("procID"), body.get("job"))] = body
        elif kind == "runEntry":
            runs.append(body)
        elif kind == "faultEntry":
            faults.append(body)
        elif kind == "jobEntry":
            jobs.setdefault(body.get("job"), []).append(body)
        elif kind == "spanEntry":
            if body.get("job") is not None:
                spans.append(body)
            if body.get("name") == "flight_dump":
                flight_spans.append(body)
        elif kind == "routeEntry":
            routes.append(body)
        elif kind == "costEntry":
            compiles.append(body)
        elif kind == "usageEntry":
            usage_recs.append(rec)
        elif kind == "scaleEntry":
            scale_recs.append(rec)
        elif kind == "qualityEntry":
            quality_recs.append(rec)
        elif kind == "profEntry":
            prof_recs.append(body)
        elif kind == "metricsEntry":
            last_metrics = body

    lines = ["== record stream"]
    lines.append("  " + "  ".join(f"{k}:{v}" for k, v in
                                  sorted(counts.items())))

    if curves or solutions:
        lines.append("== best-so-far")
        for k in sorted(set(curves) | set(solutions)):
            pts = curves.get(k, [])
            sol = solutions.get(k)
            parts = [f"  {k}:"]
            if pts:
                first_b, first_t = pts[0]
                last_b, last_t = pts[-1]
                parts.append(f"{first_b} @ {first_t:.1f}s -> "
                             f"{last_b} @ {last_t:.1f}s "
                             f"({len(pts)} improvements)")
                feas = next((t for b, t in pts if b < FEASIBLE_LIMIT),
                            None)
                if feas is not None:
                    parts.append(f"feasible @ {feas:.1f}s")
            if sol is not None:
                feas_s = ("feasible" if sol.get("feasible")
                          else "INFEASIBLE")
                parts.append(f"final {sol.get('totalBest')} ({feas_s}, "
                             f"{sol.get('totalTime', 0.0):.1f}s)")
            lines.append(" ".join(parts))

    if runs:
        final = runs[-1]
        lines.append(f"== run: totalBest {final.get('totalBest')} "
                     f"feasible={final.get('feasible')}"
                     + (f" totalTime {final['totalTime']:.1f}s"
                        if "totalTime" in final else ""))

    if faults:
        lines.append(f"== faults ({len(faults)} records)")
        by_site: dict = {}
        for f in faults:
            by_site.setdefault((f.get("site"), f.get("action")), []
                               ).append(f)
        for (site, action), fs in sorted(by_site.items()):
            worst = max(f.get("level", 0) for f in fs)
            lines.append(f"  {site}/{action}: {len(fs)}x "
                         f"(max level {worst}); last: "
                         f"{str(fs[-1].get('error', ''))[:80]}")
    else:
        lines.append("== faults: none")

    if jobs:
        lines.append(f"== jobs ({len(jobs)})")
        lats = []
        edit_lats = []          # mode=edit jobs, split out (tt-edit)
        edit_demoted = 0
        edit_dists = []
        for jid, evs in sorted(jobs.items()):
            events = [e.get("event") for e in evs]
            sol = solutions.get(f"job {jid}")
            lat = sol.get("totalTime") if sol else None
            if lat is not None:
                lats.append(lat)
            done = next((e for e in evs if e.get("event") == "done"),
                        None)
            mode = next((e.get("mode") for e in evs
                         if e.get("mode")), None)
            tag = ""
            if mode:
                tag = f" [{mode}]"
                if mode == "edit":
                    if lat is not None:
                        edit_lats.append(lat)
                    if any(e.get("demoted") for e in evs):
                        edit_demoted += 1
                        tag = " [edit, demoted]"
                    if done and done.get("edit_distance") is not None:
                        edit_dists.append(int(done["edit_distance"]))
            lines.append(
                f"  {jid}{tag}: {'->'.join(events)}"
                + (f" best {done.get('best')} gens {done.get('gens')}"
                   if done else "")
                + (f" latency {lat:.2f}s" if lat is not None else ""))
        if lats:
            lats.sort()
            p = (lambda q: lats[min(len(lats) - 1,
                                    int(q * len(lats)))])
            lines.append(f"  latency p50 {p(0.5):.2f}s "
                         f"p95 {p(0.95):.2f}s max {lats[-1]:.2f}s")
        if edit_lats or edit_demoted:
            # incremental re-solves get their own latency row: warm
            # edits are the latency story tt-edit exists to improve,
            # so averaging them into cold solves would hide it
            edit_lats.sort()
            parts = [f"  edit: {len(edit_lats)} jobs"
                     + (f" ({edit_demoted} demoted)"
                        if edit_demoted else "")]
            if edit_lats:
                parts.append(
                    f"latency p50 {_pctl(edit_lats, 0.5):.2f}s "
                    f"p95 {_pctl(edit_lats, 0.95):.2f}s")
            if edit_dists:
                ds = sorted(edit_dists)
                parts.append(f"edit_distance p50 {_pctl(ds, 0.5)} "
                             f"max {ds[-1]}")
            lines.append(" ".join(parts))

    breakdown = _job_breakdown(spans)
    if breakdown:
        # the `routed` column only appears when some job actually has
        # a gateway placement span — plain serve logs keep the old shape
        with_routed = any(b["routed"] > 0 for b in breakdown.values())
        # likewise `recovered`: only resumed jobs (fleet failover /
        # preemption) carry the snapshot-admission span
        with_rec = any(b["recovered"] > 0 for b in breakdown.values())
        lines.append(f"== job latency breakdown ({len(breakdown)} "
                     f"jobs, from spans)")
        for jid, b in breakdown.items():
            routed_s = (f"routed {b['routed']:.2f} + "
                        if with_routed else "")
            rec_s = (f"recovered {b['recovered']:.2f} + "
                     if with_rec else "")
            lines.append(
                f"  {jid}: total {b['total']:.2f}s = "
                f"queued {b['queued']:.2f} + {routed_s}{rec_s}"
                f"packed {b['packed']:.2f} "
                f"+ executing {b['executing']:.2f} "
                f"+ parked {b['parked']:.2f}")
        comps = ("total", "queued") \
            + (("routed",) if with_routed else ()) \
            + (("recovered",) if with_rec else ()) \
            + ("packed", "executing", "parked")
        for comp in comps:
            vals = sorted(b[comp] for b in breakdown.values())
            lines.append(f"  {comp}: p50 {_pctl(vals, 0.5):.2f}s "
                         f"p99 {_pctl(vals, 0.99):.2f}s "
                         f"max {vals[-1]:.2f}s")

    if routes:
        # gateway placement summary (routeEntry, tt-obs v5): per
        # replica, how many placements landed there and how warm —
        # the affinity story per replica, straight off the log
        lines.append(f"== placements ({len(routes)} routeEntry "
                     f"records)")
        by_rep: dict = {}
        for r in routes:
            by_rep.setdefault(r.get("replica", "?"), []).append(r)
        for rep, rs in sorted(by_rep.items()):
            outcomes: dict = {}
            buckets = set()
            for r in rs:
                o = r.get("outcome", "?")
                outcomes[o] = outcomes.get(o, 0) + 1
                if r.get("bucket") is not None:
                    buckets.add(tuple(r["bucket"]))
            ostr = " ".join(f"{k}:{v}" for k, v in
                            sorted(outcomes.items()))
            lines.append(f"  {rep}: {len(rs)} placements "
                         f"({ostr}) over {len(buckets)} "
                         f"bucket{'s' if len(buckets) != 1 else ''}")

    if flight_spans:
        # tt-flight (obs/flight.py): every `flight_dump` span is one
        # incident bundle written — its duration is the TIME-TO-DUMP
        # (trigger instant -> bundle on disk), the latency of the
        # black box itself
        lines.append(f"== incidents ({len(flight_spans)} dumps)")
        by_trig: dict = {}
        for s in flight_spans:
            by_trig.setdefault(s.get("trigger", "?"), []).append(
                max(0.0, float(s.get("dur", 0.0))))
        for trig, durs in sorted(by_trig.items()):
            durs.sort()
            lines.append(
                f"  {trig}: {len(durs)}x, time-to-dump "
                f"p50 {_pctl(durs, 0.5):.3f}s "
                f"p99 {_pctl(durs, 0.99):.3f}s")

    if prof_recs:
        # tt-prof (obs/prof.py): per-phase share of attributed device
        # time across this log's profiler captures — p50/p95 of each
        # phase's fraction over the profEntry records, so a phase whose
        # share GREW between captures shows as a spread, not an average
        lines.append(f"== phases ({len(prof_recs)} profEntry records)")
        shares: dict = {}
        secs: dict = {}
        for b in prof_recs:
            for name, ph in (b.get("phases") or {}).items():
                shares.setdefault(name, []).append(
                    float(ph.get("frac", 0.0)))
                secs.setdefault(name, []).append(
                    float(ph.get("s", 0.0)))
            shares.setdefault("unattributed", []).append(
                float(b.get("unattributedFrac", 0.0)))
            secs.setdefault("unattributed", []).append(
                float(b.get("unattributedSeconds", 0.0)))
        order = sorted(shares, key=lambda n: -sorted(shares[n])[
            min(len(shares[n]) - 1, len(shares[n]) // 2)])
        for name in order:
            fr = sorted(shares[name])
            lines.append(
                f"  {name}: share p50 {_pctl(fr, 0.5):.1%} "
                f"p95 {_pctl(fr, 0.95):.1%} "
                f"({sum(secs[name]):.3f}s over "
                f"{len(fr)} capture{'s' if len(fr) != 1 else ''})")

    if compiles:
        # cost observatory (obs/cost.py): per-program compile count,
        # total lower+compile seconds, and the latest roofline numbers
        lines.append(f"== compiles ({len(compiles)} costEntry records)")
        by_prog: dict = {}
        for c in compiles:
            by_prog.setdefault(c.get("program", "?"), []).append(c)
        for prog, cs in sorted(by_prog.items()):
            total = sum(float(c.get("lowerSeconds", 0.0))
                        + float(c.get("compileSeconds", 0.0))
                        for c in cs)
            # latest entry CARRYING roofline numbers (a backend may
            # omit flops on some compiles)
            last = next((c for c in reversed(cs)
                         if c.get("flops") is not None), cs[-1])
            tail = ""
            if last.get("flops") is not None:
                tail = f" flops {last['flops']:.3g}"
                if last.get("intensity") is not None:
                    tail += f" AI {last['intensity']:.1f}"
            lines.append(f"  {prog}: {len(cs)}x, {total:.2f}s "
                         f"lower+compile{tail}")

    if usage_recs:
        # tt-meter (obs/usage.py owns the report): who consumed the
        # capacity — per-tenant and per-job device seconds, FLOPs,
        # queue/park wall, compile amortization
        from timetabling_ga_tpu.obs import usage as obs_usage
        lines.append(obs_usage.summarize_entries(usage_recs))

    if scale_recs:
        # tt-scale (fleet/autoscaler.py owns the report): the
        # autoscaler decision log with its sustained-window evidence
        from timetabling_ga_tpu.fleet.autoscaler import (
            summarize_entries as scale_summary)
        lines.append(scale_summary(scale_recs))

    if quality_recs:
        # search-quality observatory (obs/quality.py owns the report):
        # diversity trend, operator hit rates, migration gain, and the
        # stall/kick event log (faultEntry site `quality`)
        from timetabling_ga_tpu.obs import quality as obs_quality
        lines.append(obs_quality.summarize(
            quality_recs + [{"faultEntry": f} for f in faults
                            if f.get("site") == "quality"]))

    if last_metrics is not None:
        lines.append("== last metrics snapshot")
        for kind in ("counters", "gauges"):
            for name, v in sorted((last_metrics.get(kind) or {}).items()):
                lines.append(f"  {name}: {v}")
        for name, h in sorted((last_metrics.get("histograms")
                               or {}).items()):
            if h.get("count"):
                lines.append(f"  {name}: n={h['count']} "
                             f"p50={h.get('p50')} p95={h.get('p95')} "
                             f"max={h.get('max')}")
    return "\n".join(lines)


def main_stats(argv) -> int:
    """`tt stats <log.jsonl> [more.jsonl ...]` entry point."""
    inputs: list = []
    for a in argv:
        if a in ("-h", "--help"):
            print("usage: tt stats <log.jsonl> [more.jsonl ...]\n\n"
                  "summarize a JSONL record stream: best-so-far curves, "
                  "time-to-feasible, recoveries and fault sites, per-job "
                  "latency (serve+obs logs: queued/routed/packed/"
                  "executing/parked breakdown, p50/p99 across jobs), "
                  "gateway placement summary (routeEntry), last metrics "
                  "snapshot. Several inputs concatenate — `tt stats "
                  "gateway.jsonl replica*.jsonl` reads a fleet's whole "
                  "log set")
            return 0
        if a.startswith("-"):
            raise SystemExit(f"unknown argument: {a}")
        inputs.append(a)
    if not inputs:
        raise SystemExit("usage: tt stats <log.jsonl> [more.jsonl ...]")
    records: list = []
    for idx, path in enumerate(inputs):
        batch = read_jsonl(path)
        if len(inputs) > 1:
            # stamp span provenance: each log's timestamps live in
            # its own tracer epoch, and _job_breakdown must window a
            # job inside ONE log (a failed-over job has spans in two
            # replica logs whose epochs are unrelated)
            for rec in batch:
                body = rec.get("spanEntry")
                if isinstance(body, dict):
                    body["_src"] = idx
        records.extend(batch)
    print(summarize(records))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main_stats(sys.argv[1:]))
