"""tt-obs metrics: one registry for every counter the stack grew.

Before this module the engine counted recoveries in a module global
(`engine._RECOVERIES_TOTAL`), the serve bench leg computed latency
percentiles ad hoc, and the writer's queue depth was invisible. The
registry absorbs them all into one namespace so every consumer — the
`metricsEntry` JSONL snapshots, the `stats` line-JSON command on
`tt serve`, the Prometheus text exposition, and the back-compat
`engine.run_counters()` dict — reads the same numbers.

Three instrument kinds (the Prometheus trinity):

  Counter    monotone float/int (`engine.recoveries`, `serve.jobs_done`)
  Gauge      last-set value, or a PULL function sampled at snapshot
             time (`writer.queue_depth` bound to Queue.qsize — the
             occupancy is only meaningful at read time)
  Histogram  fixed log-spaced buckets + count/sum/min/max, with
             bucket-interpolated percentile estimates (`p50`/`p95`/
             `p99`) — per-job latency lives here

Naming: dotted lowercase (`engine.gens_per_sec`); the Prometheus
exposition maps dots to underscores (`tt_engine_gens_per_sec`).

Thread-safe behind one registry lock: the AsyncWriter worker, the serve
loop, and the engine's main thread all touch it. Updates are a dict
lookup + an add under a lock — cheap enough to leave on even when no
`--obs` flag is emitting snapshots (the bench observability leg
measures exactly this overhead).

Stdlib-only by design: the CLI subcommands (`tt trace`, `tt stats`)
and the analyzer must import obs without JAX or a device.
"""

from __future__ import annotations

import math
import re
import threading

# log-spaced latency buckets (seconds): 1 ms .. 10 min, the range one
# dispatch (~100 ms), one quantum (~1 s) and one solve job (~minutes)
# all land in with resolution proportional to magnitude
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                   600.0)


class Counter:
    """Monotone accumulator. `inc` with a negative delta raises — a
    decreasing 'counter' is a gauge wearing the wrong type, and the
    Prometheus scrape semantics (rate() over resets) depend on
    monotonicity."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._value = 0.0
        self._lock = lock

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: negative inc {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-set value, or a pull function sampled at read time."""

    __slots__ = ("name", "_value", "_fn", "_lock")

    def __init__(self, name: str, lock: threading.Lock, fn=None):
        self.name = name
        self._value = 0.0
        self._fn = fn
        self._lock = lock

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def bind(self, fn) -> None:
        """Re-point a pull gauge at a new source (each engine.run binds
        `writer.queue_depth` to ITS writer; the old writer is gone).
        `bind(None)` unbinds: the gauge freezes at its last `set()`
        value and stops holding the old source (and everything its
        closure reaches — a finished run's writer and output stream)
        alive through the process-global registry."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        fn = self._fn
        if fn is not None:
            try:
                return float(fn())
            except Exception:
                # a pull source may outlive its object (a closed writer's
                # queue); a snapshot must degrade, never raise
                return float("nan")
        return self._value


class Histogram:
    """Fixed-bucket histogram with count/sum/min/max and interpolated
    percentile estimates.

    Buckets are cumulative-less-or-equal boundaries (Prometheus `le`
    semantics) plus an implicit +Inf bucket. `percentile(q)` linearly
    interpolates within the target bucket's bounds — exact enough for
    p50/p95 dashboards at log-spaced resolution, with O(1) memory
    (no reservoir: serve streams are unbounded).

    Exemplars (OpenMetrics): `observe(v, exemplar={"job": "j42"})`
    remembers the LAST exemplar landing in each bucket — one
    (labels, value) pair per bucket, O(buckets) memory. A p99 spike on
    the scrape dashboard then joins back to the concrete job/dispatch
    that caused it (its jobEntry lifecycle is on the record stream
    under the same id); `to_openmetrics` renders them, the 0.0.4 text
    exposition ignores them (no exemplar syntax there)."""

    __slots__ = ("name", "buckets", "_counts", "count", "sum",
                 "_min", "_max", "_exemplars", "_lock")

    def __init__(self, name: str, lock: threading.Lock, buckets=None):
        self.name = name
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._counts = [0] * (len(self.buckets) + 1)
        self._exemplars: list = [None] * (len(self.buckets) + 1)
        self.count = 0
        self.sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._lock = lock

    def observe(self, v: float, exemplar: dict | None = None) -> None:
        v = float(v)
        with self._lock:
            i = 0
            for i, b in enumerate(self.buckets):
                if v <= b:
                    break
            else:
                i = len(self.buckets)
            self._counts[i] += 1
            self.count += 1
            self.sum += v
            self._min = min(self._min, v)
            self._max = max(self._max, v)
            if exemplar:
                self._exemplars[i] = (
                    {str(k): str(w) for k, w in exemplar.items()}, v)

    def percentile(self, q: float) -> float:
        """Estimated q-quantile (q in [0, 1]); nan when empty."""
        with self._lock:
            if self.count == 0:
                return float("nan")
            target = q * self.count
            seen = 0
            for i, c in enumerate(self._counts):
                if c == 0:
                    continue
                lo = self.buckets[i - 1] if i > 0 else min(self._min, 0.0)
                hi = (self.buckets[i] if i < len(self.buckets)
                      else self._max)
                if seen + c >= target:
                    frac = (target - seen) / c
                    est = lo + frac * (hi - lo)
                    # clamp into the observed range (interpolation can
                    # undershoot the true min in the first bucket)
                    return min(max(est, self._min), self._max)
                seen += c
            return self._max

    def summary(self) -> dict:
        with self._lock:
            count, total = self.count, self.sum
        if count == 0:
            return {"count": 0, "sum": 0.0}
        return {"count": count, "sum": round(total, 6),
                "min": round(self._min, 6), "max": round(self._max, 6),
                "mean": round(total / count, 6),
                "p50": round(self.percentile(0.50), 6),
                "p95": round(self.percentile(0.95), 6),
                "p99": round(self.percentile(0.99), 6)}


class MetricsRegistry:
    """Name -> instrument map. get-or-create accessors: callers never
    pre-register, so an instrument exists from its first touch and a
    snapshot sees every name ever used this process."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict = {}

    def _get(self, name: str, kind, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = kind(name, self._lock, **kw)
                self._metrics[name] = m
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {kind.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def gauge_fn(self, name: str, fn) -> Gauge:
        """Pull gauge: `fn()` is sampled at snapshot time. Re-binding an
        existing name re-points it (per-run sources like a writer's
        queue)."""
        g = self._get(name, Gauge)
        g.bind(fn)
        return g

    def freeze(self, name: str, value: float) -> None:
        """Freeze a pull gauge at `value` and unbind its source (see
        Gauge.bind): run/service teardown must not leave the
        process-global registry holding closures over a finished
        writer or queue."""
        g = self.gauge(name)
        g.set(value)
        g.bind(None)

    def histogram(self, name: str, buckets=None) -> Histogram:
        return self._get(name, Histogram, buckets=buckets)

    def snapshot(self) -> dict:
        """The metricsEntry payload: {"counters": {...}, "gauges":
        {...}, "histograms": {name: {count, sum, p50, p95, ...}}}."""
        with self._lock:
            items = list(self._metrics.items())
        counters, gauges, hists = {}, {}, {}
        for name, m in sorted(items):
            if isinstance(m, Counter):
                v = m.value
                counters[name] = int(v) if v == int(v) else round(v, 6)
            elif isinstance(m, Gauge):
                v = m.value
                gauges[name] = (None if v != v          # nan -> null
                                else round(v, 6))
            else:
                hists[name] = m.summary()
        out: dict = {}
        if counters:
            out["counters"] = counters
        if gauges:
            out["gauges"] = gauges
        if hists:
            out["histograms"] = hists
        return out

    def to_prometheus(self, prefix: str = "tt") -> str:
        """Prometheus text exposition (format 0.0.4): counters as
        `<prefix>_<name>_total`, gauges plain, histograms as the
        standard `_bucket{le=...}` / `_sum` / `_count` triplet.

        Rendered UNDER the registry lock (one lock shared by every
        instrument): the pull front scrapes from its own handler
        threads, and a histogram read racing observe() could otherwise
        emit `x_count` != its `+Inf` bucket — invalid exposition a
        strict parser rejects. Render cost is O(metrics) string ops;
        pull-gauge sources must not touch the registry (none do — they
        read queue sizes)."""
        lines: list[str] = []
        with self._lock:
            for name, m in sorted(self._metrics.items()):
                pn = _prom_name(f"{prefix}.{name}")
                if isinstance(m, Counter):
                    lines.append(f"# TYPE {pn}_total counter")
                    lines.append(f"{pn}_total {_prom_num(m.value)}")
                elif isinstance(m, Gauge):
                    lines.append(f"# TYPE {pn} gauge")
                    lines.append(f"{pn} {_prom_num(m.value)}")
                else:
                    lines.append(f"# TYPE {pn} histogram")
                    cum = 0
                    for i, b in enumerate(m.buckets):
                        cum += m._counts[i]
                        lines.append(
                            f'{pn}_bucket{{le="{_prom_num(b)}"}} {cum}')
                    lines.append(f'{pn}_bucket{{le="+Inf"}} {m.count}')
                    lines.append(f"{pn}_sum {_prom_num(m.sum)}")
                    lines.append(f"{pn}_count {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")

    def to_openmetrics(self, prefix: str = "tt") -> str:
        """OpenMetrics 1.0 text exposition — what the pull front's
        `/metrics` endpoint serves (obs/http.py). Same sample names as
        `to_prometheus` plus histogram bucket EXEMPLARS
        (`... # {job="j42"} 0.93`) and the mandatory `# EOF` trailer.
        Counters drop the `_total` suffix from the metric NAME line
        (OpenMetrics: the family is `x`, the sample `x_total`).

        Rendered under the registry lock, like `to_prometheus` (and
        more urgently: this IS the scrape endpoint's payload, read
        from handler threads while the dispatch path observes)."""
        lines: list[str] = []
        with self._lock:
            for name, m in sorted(self._metrics.items()):
                pn = _prom_name(f"{prefix}.{name}")
                if isinstance(m, Counter):
                    lines.append(f"# TYPE {pn} counter")
                    lines.append(f"{pn}_total {_prom_num(m.value)}")
                elif isinstance(m, Gauge):
                    lines.append(f"# TYPE {pn} gauge")
                    lines.append(f"{pn} {_prom_num(m.value)}")
                else:
                    lines.append(f"# TYPE {pn} histogram")
                    cum = 0
                    bounds = ([_prom_num(b) for b in m.buckets]
                              + ["+Inf"])
                    for i, le in enumerate(bounds):
                        cum += m._counts[i]
                        line = f'{pn}_bucket{{le="{le}"}} {cum}'
                        ex = m._exemplars[i]
                        if ex is not None:
                            labels, v = ex
                            lbl = ",".join(
                                f'{k}="{_escape_label(w)}"'
                                for k, w in sorted(labels.items()))
                            line += f" # {{{lbl}}} {_prom_num(v)}"
                        lines.append(line)
                    lines.append(f"{pn}_sum {_prom_num(m.sum)}")
                    lines.append(f"{pn}_count {m.count}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop every instrument (tests only — production code keeps
        process-lifetime counters, the bench legs diff them)."""
        with self._lock:
            self._metrics.clear()


_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return _NAME_RE.sub("_", name)


def _escape_label(v: str) -> str:
    """Label-value escaping per the exposition formats (backslash,
    double quote, newline)."""
    return (v.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_num(v: float) -> str:
    if v != v:
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


# THE process registry: engine, serve, writer and bench all meet here.
REGISTRY = MetricsRegistry()
