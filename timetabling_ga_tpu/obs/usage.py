"""tt-meter — per-job / per-tenant usage metering and capacity
attribution (README "Usage metering").

Every observability layer so far answers "what is the process doing";
this module answers "WHO is consuming the fleet". The serve scheduler
meters each packed dispatch at its park fence and attributes the
dispatch's totals to the individual jobs that rode it — and through
each job's `tenant` tag to the tenant that submitted it:

  device_seconds   the quantum's measured device wall time (minus any
                   compile the same call paid — that goes to
                   compile_seconds under its own name)
  flops            the lane program's compile-time FLOP count
                   (obs/cost.py `CostProgram.last_cost`) — the
                   DETERMINISTIC capacity unit premium tiers can bill
                   against (wall seconds vary run to run; FLOPs per
                   executed program do not)
  compile_seconds  compile amortization: the lower+compile wall a cold
                   dispatch paid, split like the work it enabled
  queue_seconds    admission -> first dispatch (per job, once)
  park_seconds     time spent parked as a host snapshot between quanta
  gens/dispatches  executed generations / dispatches ridden

ATTRIBUTION RULE — packed dispatches split every dispatch total across
their co-tenant lanes proportionally to the generations each lane
actually ran, with a pinned CONSERVATION invariant: `split(total,
weights)` quantizes the total onto a power-of-two grid (~ns for the
seconds components, integer for FLOPs) and apportions the integer
quanta largest-remainder-first, so the per-lane shares sum to the
recorded total BIT-EXACTLY — in float, and through JSON round trips —
and summing any set of tenants' meters never under- or over-counts
the fleet (tests/test_usage.py pins it; bench `extra.usage` asserts
it on a live stream).

THE LEDGER runs off the dispatch path (the MemPoller/flight
discipline): the scheduler appends one settlement event per dispatch
to a bounded deque and moves on; the `tt-usage` daemon thread drains
it, folds per-tenant totals, bumps the live
`usage.tenant.<t>.{device_seconds,flops,jobs,queue_seconds,...}`
registry counters (which obs/history.py samples automatically — so
`HistoryRing.rate("usage.tenant.acme.flops", 60)` is a per-tenant
demand curve the autoscaler's `sustained()` contract consumes), and
emits `usageEntry` JSONL records when an emitter is bound (`--obs`).
Fault site `usage` fires once per drained batch ON the ledger thread:
a `hang` parks the ledger (meters go stale, over-cap events drop into
an honest `usage.dropped` counter), a `die` ends it — dispatch,
settlement, and writer drain never wait on it (tests pin it).

The per-JOB meter is NOT here: it lives on the Job itself
(serve/queue.py `Job.usage`), folded inline at each park fence by the
drive loop (plain dict arithmetic — nothing to hang), because the
snapshot wire needs a fence-consistent cursor: a shipped snapshot
carries the job's meter, and a failover-resumed job CONTINUES it on
the survivor instead of resetting (serve/snapshot.py). Tenant totals,
by contrast, stay per-replica — each replica counts only what it
metered itself — so the gateway's fleet-wide aggregation
(`GET /v1/usage`, summed over live ledgers plus dead replicas'
last-scraped copies, the incident-bundle stitching rule) never double
counts a resumed job's history.

The standing invariant: the record stream is identical with metering
on or off. `usageEntry` is a TIMING record (jsonl.TIMING_RECORDS),
counters write no records, and metering never touches dispatch inputs.

Stdlib-only at import time, like the rest of obs/: `tt usage` must run
on any machine a log was copied to.
"""

from __future__ import annotations

import collections
import json
import os
import re
import sys
import threading

from timetabling_ga_tpu.obs import metrics as obs_metrics

# the per-lane delta components a meter accumulates (wire + ledger +
# usageEntry all share this closed set, so the consumers cannot drift)
FIELDS = ("gens", "dispatches", "device_seconds", "compile_seconds",
          "flops", "queue_seconds", "park_seconds")

# integral components (rendered and serialized as ints)
_INT_FIELDS = ("gens", "dispatches")

# bound on the ledger's inbox: the drive loop appends and never waits,
# so a hung ledger thread must shed oldest events, not grow memory
# without bound (the dropped count is surfaced, never silent)
EVENTS_CAP = 4096

# bound on DISTINCT tenant labels per ledger: the tag is
# client-controlled (it rides unauthenticated POST /v1/solve
# payloads), and every distinct label allocates a ledger entry, ~8
# registry counters, and — because the history rings sample every
# registry series — ~8 bounded-but-real sample rings per process.
# Beyond the cap, NEW labels fold into the shared OVERFLOW_TENANT
# bucket (their work is still metered and conserved, just not singled
# out) and `usage.tenant_overflow` counts the folds — the same
# honest-truncation discipline as EVENTS_CAP/JobTail/ship rings.
TENANTS_CAP = int(os.environ.get("TT_USAGE_TENANTS_CAP", "256"))

DEFAULT_TENANT = "default"
OVERFLOW_TENANT = "other"

# no dots: the label is spliced into dotted metric names
# (`usage.tenant.<t>.gens`), and a dotted tenant would fork the
# namespace ambiguously
_LABEL_RE = re.compile(r"[^a-zA-Z0-9_-]")


def _faults():
    """Lazy import (the MemPoller pattern, obs/cost.py): this module
    stays importable without the runtime package; the ledger thread
    only exists inside serve processes, where it is long imported."""
    from timetabling_ga_tpu.runtime import faults
    return faults


def tenant_label(tenant) -> str:
    """Canonical tenant tag: a bounded, metric-name-safe string.
    Empty/None collapses to the shared DEFAULT_TENANT — an untagged
    submission is still metered, just not singled out."""
    t = str(tenant or "").strip()
    if not t:
        return DEFAULT_TENANT
    return _LABEL_RE.sub("_", t)[:64]


# ------------------------------------------------------- meter arithmetic


def new_usage() -> dict:
    return {f: 0 for f in _INT_FIELDS} | {
        f: 0.0 for f in FIELDS if f not in _INT_FIELDS}


def fold_into(dst: dict, src: dict) -> dict:
    """Accumulate `src`'s FIELDS into `dst` IN PLACE (ints stay ints)
    — THE one fold loop every accumulator shares (the live ledger,
    the fleet combine, the log-side fold), so 'log fold == live
    ledger' cannot drift on accumulation semantics."""
    for f in FIELDS:
        v = src.get(f)
        if v:
            dst[f] = (int(dst[f] + v) if f in _INT_FIELDS
                      else dst[f] + float(v))
    return dst


def add(usage: dict | None, delta: dict) -> dict:
    """Fold `delta` into `usage`, returning a NEW dict (the drive loop
    replaces `Job.usage` wholesale, so a handler thread reading it for
    `GET /v1/usage` sees one fence's meter or the next, never a torn
    mix)."""
    out = new_usage()
    for src in (usage or {}), delta:
        fold_into(out, src)
    return out


def rounded(usage: dict | None, ndigits: int = 6) -> dict:
    """JSON-presentation form: floats rounded, ints kept ints — the
    shape a result dict, wire cursor, or usageEntry carries."""
    out = {}
    for f in FIELDS:
        v = (usage or {}).get(f, 0)
        out[f] = int(v) if f in _INT_FIELDS else round(float(v), ndigits)
    return out


# the dyadic metering grid: shares and totals are integer multiples of
# this power-of-two quantum (~0.93 ns for the seconds components), so
# every partial sum a consumer computes is an exact float — see split()
QUANTUM = 2.0 ** -30


def split(total: float, weights, quantum: float = QUANTUM) -> tuple:
    """Proportional shares of `total` over `weights` whose float sum
    is EXACTLY the returned quantized total — THE conservation
    primitive (module docstring). Returns `(qtotal, shares)`.

    Exactness by construction, not by luck: assigning the last lane
    the float remainder `t - sum(rest)` provably CANNOT always close
    the sum (round-to-even can skip the target, so no representable
    remainder exists). Instead the total is quantized onto a dyadic
    grid (`round(total / quantum)` with a power-of-two quantum —
    ~0.93 ns for the seconds components, 1.0 for counts like FLOPs)
    and the integer quanta are apportioned largest-remainder-first.
    Every share and every left-to-right partial sum is then an
    integer multiple of the quantum below 2**53, i.e. an EXACT float,
    so `sum(shares) == qtotal` holds bit-exactly — through JSON round
    trips too (dyadics reprint exactly). The quantization error
    (≤ quantum/2, sub-nanosecond) lands on the TOTAL once, never on
    the split. All-zero weights split evenly (a dispatch of
    zero-gen lanes still had a measured wall); a total too large for
    the grid escalates to coarser power-of-two quanta until the
    integer fits."""
    ws = [max(0, int(w)) for w in weights]
    n = len(ws)
    if n == 0:
        return 0.0, []
    wsum = sum(ws)
    if wsum <= 0:
        ws = [1] * n
        wsum = n
    q = float(quantum)
    units = int(round(float(total) / q))
    while units >= 2 ** 53:
        q *= 2.0
        units = int(round(float(total) / q))
    base = [units * w // wsum for w in ws]
    # largest fractional remainder first; index as the deterministic
    # tie-break (stable attribution — the same dispatch always splits
    # the same way)
    order = sorted(range(n), key=lambda i: (-(units * ws[i] % wsum),
                                            i))
    short = units - sum(base)
    for i in order[:short]:
        base[i] += 1
    return units * q, [b * q for b in base]


# ------------------------------------------------------------- the ledger


class UsageLedger:
    """Per-tenant usage aggregation OFF the dispatch path.

    The drive loop calls `job()` / `dispatch()` / `final()` — each an
    O(1) bounded-deque append — and the `tt-usage` daemon thread folds
    the events into per-tenant totals, the live `usage.tenant.<t>.*`
    registry counters, and (when an emitter is bound) `usageEntry`
    JSONL records. `totals()` is the lock-guarded read `GET /v1/usage`
    serves (TT607: handlers READ the ledger, they never mutate it).

    Fault site `usage` fires once per drained batch on the ledger
    thread: `hang` parks it (events shed beyond EVENTS_CAP into
    `usage.dropped`), `die` ends it silently — dispatch, settlement,
    and writer drain never wait on the ledger (tests/test_usage.py).
    """

    def __init__(self, registry=None, out=None, now=None,
                 tenants_cap: int | None = None):
        self._reg = (obs_metrics.REGISTRY if registry is None
                     else registry)
        self._cap = int(TENANTS_CAP if tenants_cap is None
                        else tenants_cap)
        self._out = out          # usageEntry sink (an AsyncWriter —
        #                          a producer-side write; None = none)
        self._now = now
        self._lock = threading.Lock()
        self._tenants: dict[str, dict] = {}
        self._events: collections.deque = collections.deque(
            maxlen=EVENTS_CAP)
        self._wake = threading.Event()
        self._stop = False
        self._out_dead = False   # latched on a failed emission: the
        #                          gw_writer discipline — a dying
        #                          writer mutes records, never the
        #                          meter or the drive loop
        self._thread = threading.Thread(
            target=self._loop, name="tt-usage", daemon=True)
        self._thread.start()

    # -- producer side (drive loop; never blocks) -----------------------

    def _push(self, ev: tuple) -> None:
        if self._stop or not self._thread.is_alive():
            return
        if len(self._events) == self._events.maxlen:
            # deque drops the oldest on append — count it honestly
            self._reg.counter("usage.dropped").inc()
        self._events.append(ev)
        self._wake.set()

    def job(self, job_id: str, tenant: str) -> None:
        """One NEW job admitted for `tenant` (resumed re-admissions do
        NOT call this — the job was counted by its first replica, and
        fleet aggregation sums tenant ledgers)."""
        self._push(("job", str(job_id), tenant_label(tenant)))

    def dispatch(self, payload: dict) -> None:
        """One settled dispatch: `payload` carries the dispatch totals
        plus a `lanes` list of per-job shares (each with job/tenant +
        FIELDS deltas) whose components sum to the totals — the
        conservation invariant the scheduler's `split` guarantees."""
        self._push(("dispatch", payload))

    def final(self, job_id: str, tenant: str, usage: dict,
              mode: str = None) -> None:
        """A job settled: emit its cumulative meter as one usageEntry
        (event "total") — the authoritative per-job line `tt usage`
        prefers when summarizing a log. `mode` (tt-edit) tags
        non-default job modes ("edit") on the record so `tt usage`
        and `tt stats` can split edit traffic out; None/"solve" emits
        the pre-edit record byte-identically."""
        self._push(("final", str(job_id), tenant_label(tenant),
                    dict(usage or {}),
                    mode if mode and mode != "solve" else None))

    # -- the ledger thread ----------------------------------------------

    def _loop(self) -> None:
        while True:
            self._wake.wait()
            self._wake.clear()
            if not self.poll_once():
                return
            if self._stop and not self._events:
                return

    def poll_once(self) -> bool:
        """Drain the current batch; False when the thread should exit
        (injected death / teardown). The testable unit, like
        MemPoller.poll_once / HistoryRing.sample_once."""
        if sys.is_finalizing():
            return False
        batch = []
        while self._events:
            try:
                batch.append(self._events.popleft())
            except IndexError:
                break
        if not batch:
            return True
        try:
            _faults().maybe_fail("usage")
        except SystemExit:
            return False            # injected death: exit silently
        except Exception:
            pass
        for ev in batch:
            try:
                self._apply(ev)
            except Exception:
                # metering must never take down its own thread: one
                # torn event is one lost line, counted
                self._reg.counter("usage.errors").inc()
        return True

    def _resolve(self, label: str) -> str:
        """Tenant-cardinality bound (caller holds the lock): a label
        the ledger already tracks keeps its row; a NEW label past
        TENANTS_CAP folds into the shared overflow bucket — metered
        and conserved, just not singled out."""
        if label in self._tenants or len(self._tenants) < self._cap \
                or label == OVERFLOW_TENANT:
            return label
        self._reg.counter("usage.tenant_overflow").inc()
        return OVERFLOW_TENANT

    def _tenant(self, label: str) -> dict:
        t = self._tenants.get(label)
        if t is None:
            t = self._tenants[label] = new_usage() | {"jobs": 0}
        return t

    def _bump(self, label: str, delta: dict) -> None:
        with self._lock:
            label = self._resolve(label)
            fold_into(self._tenant(label), delta)
        base = f"usage.tenant.{label}"
        for f in FIELDS:
            v = delta.get(f)
            if v:
                self._reg.counter(f"{base}.{f}").inc(float(v))

    def _apply(self, ev: tuple) -> None:
        kind = ev[0]
        if kind == "job":
            _, job_id, label = ev
            with self._lock:
                label = self._resolve(label)
                self._tenant(label)["jobs"] += 1
            self._reg.counter(f"usage.tenant.{label}.jobs").inc()
        elif kind == "dispatch":
            payload = ev[1]
            for lane in payload.get("lanes", ()):
                self._bump(tenant_label(lane.get("tenant")), lane)
            self._reg.counter("usage.dispatches").inc()
            self._emit(dict(payload))
        elif kind == "final":
            _, job_id, label, usage = ev[:4]
            mode = ev[4] if len(ev) > 4 else None
            payload = {"event": "total", "job": job_id,
                       "tenant": label}
            if mode:
                payload["mode"] = mode
            self._emit({**payload, **rounded(usage)})

    def _emit(self, payload: dict) -> None:
        out = self._out
        if out is None or self._out_dead:
            return
        try:
            from timetabling_ga_tpu.runtime import jsonl
            ts = self._now() if self._now is not None else None
            jsonl.usage_entry(out, payload, ts=ts)
        except Exception:
            # a closed/dead writer mutes usageEntry emission; the
            # counters and totals stay live (gw_writer discipline)
            self._out_dead = True

    # -- read side (handler threads; read-only) -------------------------

    def alive(self) -> bool:
        return self._thread.is_alive()

    def totals(self) -> dict:
        """{tenant: {jobs, gens, device_seconds, ...}} — this
        replica's OWN metered contribution (the gateway sums these
        across replicas; resumed history is never re-counted here)."""
        with self._lock:
            return {label: dict(t, **rounded(t))
                    for label, t in sorted(self._tenants.items())}

    def drain(self, timeout: float = 2.0) -> bool:
        """Best-effort wait for the inbox to empty (tests; close())."""
        import time as _time
        deadline = _time.monotonic() + timeout
        while self._events and self._thread.is_alive():
            self._wake.set()
            if _time.monotonic() > deadline:
                return False
            _time.sleep(0.005)
        return not self._events

    def close(self) -> None:
        """Drain what is already queued, then stop; a hung ledger
        thread is abandoned (daemon), never waited out — the close
        path must not inherit the stall the fault site injects."""
        self._stop = True
        self.drain()
        self._wake.set()
        self._thread.join(timeout=2.0)


# ------------------------------------------------- fleet-wide aggregation


def progress(payload) -> float:
    """A monotone scalar over one `/v1/usage` payload's tenant ledgers
    (gens + dispatches + jobs — counters that only ever grow within
    one process incarnation). Two consecutive scrapes of the SAME
    replica URL where this number moves BACKWARD mean the process
    restarted behind our back and the fresh incarnation's ledger
    started over — the flight-recorder dump-counter discipline,
    applied to billing: fleet/replicas.py folds the dead incarnation's
    cached payload into `usage_base` when it sees one, so a static
    (non-spawned) replica's bill survives external restarts too."""
    total = 0.0
    for t in ((payload or {}).get("tenants") or {}).values():
        for f in ("gens", "dispatches", "jobs"):
            v = t.get(f, 0)
            if isinstance(v, (int, float)) and v == v:
                total += float(v)
    return total


def combine(payloads) -> dict:
    """Merge {tenants, jobs} usage payloads into one: tenant meters
    SUM (each payload counted only its own metered work), per-job
    meters take the highest-progress view (a failed-over job's
    survivor meter already CONTINUES the shipped cursor, so summing
    would double count its history). Used by the fleet aggregation
    AND by ReplicaHandle to carry a dead incarnation's ledger across
    a respawn (the fresh worker's near-empty payload must ADD to the
    retired one, never replace it — metered work does not vanish from
    the bill with its process)."""
    tenants: dict = {}
    jobs: dict = {}
    for payload in payloads:
        if not payload:
            continue
        for label, t in (payload.get("tenants") or {}).items():
            agg = tenants.setdefault(label, new_usage() | {"jobs": 0})
            fold_into(agg, t)
            agg["jobs"] += int(t.get("jobs", 0))
        for jid, j in (payload.get("jobs") or {}).items():
            have = jobs.get(jid)
            if have is None or int(j.get("usage", {}).get("gens", 0)) \
                    >= int(have.get("usage", {}).get("gens", 0)):
                jobs[jid] = dict(j)
    return {"tenants": tenants, "jobs": jobs}


def aggregate(payloads) -> dict:
    """Fleet totals from per-replica `GET /v1/usage` payloads:
    `payloads` is [(name, dead, payload-or-None), ...] (the gateway's
    prober cache — a dead replica contributes its LAST-scraped ledger,
    the incident-bundle stitching rule). The merge rules are
    `combine`'s; each job is stamped with the replica whose payload
    won its highest-progress view."""
    merged = combine([
        (dict(payload, jobs={jid: dict(j, replica=str(name))
                             for jid, j in
                             (payload.get("jobs") or {}).items()})
         if payload else None)
        for name, dead, payload in payloads])
    replicas = {str(name): {
        "dead": bool(dead),
        "scraped": payload is not None,
        "tenants": sorted((payload or {}).get("tenants", {})),
    } for name, dead, payload in payloads}
    return {"tenants": {k: dict(t, **rounded(t))
                        for k, t in sorted(merged["tenants"].items())},
            "jobs": dict(sorted(merged["jobs"].items())),
            "replicas": replicas}


# -------------------------------------------------- log-side summarizing


def fold_entries(records) -> dict:
    """Collapse a record stream's usageEntry lines into the
    {tenants, jobs} shape `aggregate`/`tt usage` render. Per-dispatch
    lane deltas accumulate; a job's `event: "total"` line (emitted at
    settle, cumulative ACROSS incarnations for a resumed job)
    overrides its delta sum — the authoritative per-job meter."""
    tenants: dict = {}
    jobs: dict = {}
    finals: dict = {}
    for rec in records:
        body = rec.get("usageEntry") if isinstance(rec, dict) else None
        if not isinstance(body, dict):
            continue
        if body.get("event") == "total":
            label = tenant_label(body.get("tenant"))
            finals[str(body.get("job"))] = {
                "tenant": label,
                "usage": rounded({f: body.get(f, 0) for f in FIELDS})}
            continue
        for lane in body.get("lanes", ()):
            label = tenant_label(lane.get("tenant"))
            fold_into(tenants.setdefault(
                label, new_usage() | {"jobs": 0}), lane)
            jid = str(lane.get("job"))
            j = jobs.setdefault(jid, {"tenant": label,
                                      "usage": new_usage()})
            j["usage"] = add(j["usage"], lane)
    seen_jobs: dict = {}
    for jid, j in {**jobs, **finals}.items():
        seen_jobs[jid] = {"tenant": j["tenant"],
                          "usage": rounded(j["usage"])}
        label = j["tenant"]
        t = tenants.setdefault(label, new_usage() | {"jobs": 0})
        t["jobs"] += 1
        if jid not in jobs:
            # a job visible ONLY through its settle total (its deltas
            # were truncated away, or live in another replica's log):
            # its meter still belongs in the tenant's sum
            fold_into(t, j["usage"])
    return {"tenants": {k: dict(t, **rounded(t))
                        for k, t in sorted(tenants.items())},
            "jobs": dict(sorted(seen_jobs.items()))}


def _fmt_usage(u: dict) -> str:
    return (f"gens {int(u.get('gens', 0))} "
            f"dispatches {int(u.get('dispatches', 0))} "
            f"device {float(u.get('device_seconds', 0.0)):.3f}s "
            f"compile {float(u.get('compile_seconds', 0.0)):.3f}s "
            f"flops {float(u.get('flops', 0.0)):.3g} "
            f"queued {float(u.get('queue_seconds', 0.0)):.3f}s "
            f"parked {float(u.get('park_seconds', 0.0)):.3f}s")


def render(report: dict, tenant: str | None = None) -> str:
    """The human `tt usage` report (and tt stats' `== usage` body)
    from a {tenants, jobs[, replicas]} shape."""
    lines = []
    tenants = report.get("tenants") or {}
    jobs = report.get("jobs") or {}
    if tenant is not None:
        label = tenant_label(tenant)
        tenants = {k: v for k, v in tenants.items() if k == label}
        jobs = {k: v for k, v in jobs.items()
                if tenant_label(v.get("tenant")) == label}
    lines.append(f"== usage by tenant ({len(tenants)})")
    for label, t in tenants.items():
        lines.append(f"  {label}: jobs {int(t.get('jobs', 0))} "
                     + _fmt_usage(t))
    if jobs:
        lines.append(f"== usage by job ({len(jobs)})")
        for jid, j in jobs.items():
            rep = (f" @{j['replica']}" if j.get("replica") else "")
            lines.append(f"  {jid} ({j.get('tenant')}{rep}): "
                         + _fmt_usage(j.get("usage") or {}))
    reps = report.get("replicas")
    if reps:
        lines.append(f"== replicas ({len(reps)})")
        for name, r in sorted(reps.items()):
            state = "dead, last-scraped ledger" if r.get("dead") \
                else ("live" if r.get("scraped") else "unscraped")
            lines.append(f"  {name}: {state}; tenants "
                         f"{', '.join(r.get('tenants') or ()) or '-'}")
    return "\n".join(lines)


def summarize_entries(records) -> str:
    """`tt stats`' `== usage` section body (logstats.py appends it
    when a stream carries usageEntry records)."""
    return render(fold_entries(records))


# ------------------------------------------------------------ tt usage CLI


_USAGE = """\
usage: tt usage <log.jsonl [more.jsonl ...] | URL> [--tenant T] [--json]

per-tenant / per-job usage report (README "Usage metering"):
  from logs:     parse usageEntry records out of one or more record
                 streams (several inputs concatenate — a fleet's
                 gateway + replica logs read together)
  from a URL:    GET <url>/v1/usage off a live replica or gateway
                 front (the gateway aggregates fleet-wide totals,
                 including dead replicas' last-scraped ledgers)
  --tenant T     only this tenant's rows
  --json         machine-readable report on stdout
  -h, --help     this message"""


def main_usage(argv) -> int:
    """`tt usage` entry point (cli.py dispatches here). Stdlib-only
    and device-free, like tt trace / tt stats."""
    inputs: list = []
    tenant = None
    as_json = False
    i = 0
    argv = list(argv)
    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            print(_USAGE)
            return 0
        if a == "--json":
            as_json = True
            i += 1
            continue
        if a == "--tenant":
            if i + 1 >= len(argv):
                raise SystemExit("flag --tenant needs a value")
            tenant = argv[i + 1]
            i += 2
            continue
        if a.startswith("-"):
            raise SystemExit(f"unknown argument: {a}")
        inputs.append(a)
        i += 1
    if not inputs:
        raise SystemExit(_USAGE)
    if len(inputs) == 1 and "://" in inputs[0]:
        import urllib.request
        url = inputs[0].rstrip("/") + "/v1/usage"
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                report = json.loads(resp.read().decode())
        except Exception as e:
            print(f"tt usage: {e}", file=sys.stderr)
            return 2
    else:
        from timetabling_ga_tpu.obs.trace_export import read_jsonl
        records: list = []
        for path in inputs:
            records.extend(read_jsonl(path))
        report = fold_entries(records)
    if as_json:
        if tenant is not None:
            label = tenant_label(tenant)
            report = {
                "tenants": {k: v for k, v in
                            (report.get("tenants") or {}).items()
                            if k == label},
                "jobs": {k: v for k, v in
                         (report.get("jobs") or {}).items()
                         if tenant_label(v.get("tenant")) == label}}
        print(json.dumps(report))
    else:
        print(render(report, tenant=tenant))
    return 0
