"""tt-obs span tracer: nestable host-side timing spans.

A span is one bracketed interval of HOST time — a dispatch enqueue-to-
fence, a control fetch, a checkpoint, a serve quantum. Spans ride the
run's existing `jsonl.AsyncWriter` as `spanEntry` records, so the
control-vs-telemetry fence rule (runtime/engine.py module docstring) is
preserved by construction: emitting a span costs one bounded-queue
enqueue on the dispatch path, and the serialization happens on the
writer thread. `tt trace <log.jsonl>` exports the records as Chrome
trace-event JSON loadable in Perfetto (obs/trace_export.py), next to
any `--trace-profile` device timeline.

Two emission shapes:

  with tracer.span("checkpoint", cat="engine", gens=n):   # bracketed
      ...
  tracer.record("dispatch", t0, dur, cat="device", ...)   # measured
                                                          # elsewhere

`record` exists because the engine's dispatch bracket is measured by
the pipeline's OWN clocks (td0/fence times that also feed the budget
predictor) — re-timing it would drift from the numbers the engine
acts on. `t0` is a raw `time.monotonic()` value; the tracer converts
to its epoch-relative timeline.

Flow ids (tt-obs v2, causal tracing): `new_flow()` allocates a small
process-unique id; spans that belong to one causal chain carry it as a
`flow=` attribute (an int, or a list when one span serves several
chains — a packed serve dispatch advancing many jobs). Flows are how a
trace crosses THREAD boundaries: the engine's dispatch (main thread) →
the fetch watchdog's read (tt-fetch-watchdog) → the writer's checkpoint
serialization (tt-jsonl-writer) render as connected arrows in Perfetto
(`tt trace` exports them as `s`/`t`/`f` flow events), and every span of
a serve job's life — admit → pack → quantum → park → resume → finalize
— shares the job's flow id so `tt trace --job ID` shows one end-to-end
timeline.

Cross-PROCESS flows (tt-obs v5, the fleet observatory): a tracer built
with `flow_base=XFLOW_BASE` allocates ids in a disjoint range reserved
for chains that cross process boundaries. The fleet gateway is the one
allocator in that range: it mints a flow per admitted job and ships it
to the owning replica as an `X-TT-Flow` header on POST /v1/solve; the
replica threads it into `Job.flow`, so every replica-side span of the
job CONTINUES the gateway's chain. When `tt trace` stitches several
logs (gateway + N replicas) into one timeline, ids at/above XFLOW_BASE
are kept verbatim (they are globally unique by construction — only one
process mints them) while each log's local ids are remapped into a
per-log namespace, so two replicas' unrelated chunk chains can never
merge by id collision (obs/trace_export.py export_stitched).

Clock discipline: all timestamps are `time.monotonic()` offsets from
the tracer's construction epoch — monotone, NTP-immune, and cheap.
Spans are HOST-side only: a wall-clock read inside a jitted function
executes at trace time and stamps compile time into the program
(tt-analyze rule TT601 bans exactly that).

Disabled tracers (the default) are pure no-ops: `span()` yields through
a reusable null context and `record` returns immediately — the hot
path pays one attribute read. Nesting depth is tracked per thread, so
serve-loop spans and engine spans never interleave their stacks.

Black-box capture (tt-flight, obs/flight.py): because every span rides
the writer as a spanEntry record, the flight recorder's stream tee
sees them all with no tracer hook — the last spans live on in a
byte-budget ring and ship inside incident bundles, which is how "the
30 seconds before the failover" stays answerable after the fact.

Stdlib-only: the CLI trace exporter imports this module without JAX.
"""

from __future__ import annotations

import contextlib
import threading
import time

# flow ids at/above this value are CROSS-PROCESS chains (module
# docstring): allocated only by the one process that owns the chain's
# root (the fleet gateway), shipped over the wire, and kept verbatim
# when `tt trace` stitches multiple logs. Local (per-process) flows
# stay far below it.
XFLOW_BASE = 1 << 32


class SpanTracer:
    """Emits spanEntry records onto a (writer-wrapped) stream.

    `out` is anything the jsonl emitters accept — normally the run's
    AsyncWriter, so span serialization rides the telemetry thread.
    `enabled=False` (or out=None) makes every call a no-op."""

    def __init__(self, out=None, enabled: bool = True,
                 clock=time.monotonic, flow_base: int = 0):
        self.enabled = bool(enabled) and out is not None
        self._out = out
        self._clock = clock
        self._epoch = clock()
        self._local = threading.local()
        self._tids: dict[int, int] = {}
        self._tid_lock = threading.Lock()
        # flow ids are flow_base + n: 0 for ordinary per-process
        # tracers, XFLOW_BASE for the one tracer whose chains cross
        # process boundaries (the fleet gateway's)
        self._flow_base = int(flow_base)
        self._next_flow = 0

    # -- flows ----------------------------------------------------------

    def new_flow(self) -> int:
        """Allocate a flow id for one causal chain (a dispatch's
        enqueue→fetch→process life, a serve job's admit→...→finalize).
        Spans of the chain carry it as `flow=<id>` (or `flow=[ids]` when
        one span advances several chains); `tt trace` turns shared ids
        into Perfetto flow arrows across thread lanes. Returns 0 when
        the tracer is disabled — callers thread the id through
        unconditionally and the no-op spans discard it."""
        if not self.enabled:
            return 0
        with self._tid_lock:
            self._next_flow += 1
            return self._flow_base + self._next_flow

    # -- clocks ---------------------------------------------------------

    def now(self) -> float:
        """Seconds since the tracer epoch (the spanEntry `ts` domain)."""
        return self._clock() - self._epoch

    def _tid(self) -> int:
        """Small stable per-thread id (0 = first thread seen, normally
        the main loop) — the Chrome trace `tid` lane."""
        ident = threading.get_ident()
        t = self._tids.get(ident)
        if t is None:
            with self._tid_lock:
                t = self._tids.setdefault(ident, len(self._tids))
        return t

    def _depth_stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    # -- emission -------------------------------------------------------

    def _emit(self, name: str, cat: str, ts: float, dur: float,
              depth: int, **attrs) -> None:
        # local import: obs must stay importable without the runtime
        # package half-initialized (jsonl imports faults only — cheap)
        from timetabling_ga_tpu.runtime import jsonl
        jsonl.span_entry(self._out, name, cat, ts, dur, depth,
                         self._tid(), **attrs)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "engine", **attrs):
        """Bracketed span; nests (depth = enclosing spans on this
        thread). Exceptions propagate after the span is emitted with
        `error=True`, so a failed phase is visible in the timeline."""
        if not self.enabled:
            yield self
            return
        stack = self._depth_stack()
        depth = len(stack)
        stack.append(name)
        t0 = self._clock()
        try:
            yield self
        except BaseException:
            attrs = dict(attrs, error=True)
            raise
        finally:
            stack.pop()
            t1 = self._clock()
            try:
                self._emit(name, cat, t0 - self._epoch, t1 - t0, depth,
                           **attrs)
            except Exception:
                # a dying writer must not mask the body's own outcome;
                # its error re-raises at the next direct write anyway
                pass

    def record(self, name: str, start_monotonic: float, dur: float,
               cat: str = "engine", **attrs) -> None:
        """Emit a span measured by the caller's own monotonic clocks
        (`start_monotonic` = a raw time.monotonic() reading)."""
        if not self.enabled:
            return
        self._emit(name, cat, start_monotonic - self._epoch,
                   max(0.0, dur), len(self._depth_stack()), **attrs)


# Shared disabled tracer: callers that may or may not have obs wired
# (e.g. _polish_chunks' default argument) use this instead of None-
# checking at every site.
NULL_TRACER = SpanTracer(out=None, enabled=False)
