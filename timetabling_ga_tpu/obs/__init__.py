"""tt-obs — span tracing, the unified metrics registry, and streaming
telemetry glue (README "Observability").

Three layers:

  obs.spans     SpanTracer — nestable host-side timing spans, emitted
                as `spanEntry` JSONL records through the run's
                AsyncWriter; `tt trace` exports them as Chrome
                trace-event JSON (obs.trace_export)
  obs.metrics   MetricsRegistry (counters / gauges / histograms) — ONE
                namespace for the engine, the serve scheduler and the
                writer; snapshotted as `metricsEntry` records, served
                live by `tt serve`'s `stats` command, and exported as
                Prometheus text exposition
  obs.logstats  `tt stats` — offline summarizer for any record stream
  obs.quality   the search-quality observatory's host side — packed-
                leaf layout constants, numpy decode into the quality.*
                namespace, the stall detector, and `tt quality`
                (README "Search-quality observatory")

The device-side half of the story — `--trace-mode full|deltas|stats`,
which shrinks the per-generation telemetry leaf the engine fetches —
lives with the island programs (parallel/islands.py) and the engine
(runtime/engine.py); this package is the host side.

Stdlib-only: every module here imports without JAX or a device (the
CLI subcommands and the analyzer depend on that).
"""

from timetabling_ga_tpu.obs.metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, REGISTRY)
from timetabling_ga_tpu.obs.spans import (  # noqa: F401
    NULL_TRACER, SpanTracer)

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "REGISTRY", "NULL_TRACER", "SpanTracer"]
