"""tt-obs cost observatory: compile accounting, live roofline telemetry,
device-memory polling, and on-demand profiler capture.

The device side of the stack was a black box: compile cost dominates the
serve path (ROADMAP item 3's bucket-affine routing needs a compile-hit
rate nobody measured) and kernel headroom (item 4) was visible only in a
one-off bench leg. This module makes cost a live, per-program quantity:

  COMPILE ACCOUNTING — `instrument(fn, program)` wraps a jitted program
  in a `CostProgram`: an AOT-dispatching proxy that performs ONE
  explicit `fn.lower(*args).compile()` per input signature (shapes +
  dtypes — for serve's lane programs the signature IS the shape bucket),
  timing lower and compile separately, then dispatches every later call
  straight through the cached executable. Every engine `cached_*` and
  serve `cached_lane_runner`/`cached_lane_init` program goes through it,
  so the registry carries real `/metrics` families:

      compile.count              compiles performed (+ per program:
                                 compile.count.<program>)
      compile.cache_hits         dispatches served by a warm executable
      compile.seconds            lower+compile wall-time histogram,
                                 exemplar = {program, sig} per bucket
      compile.retries            transient compile-RPC retries (the
                                 BENCH_r05 'response body closed' class)

  ROOFLINE — at compile time the executable's `cost_analysis()` /
  `memory_analysis()` land in per-program gauges (`cost.flops.<p>`,
  `cost.bytes.<p>`, `cost.intensity.<p>`, `cost.temp_bytes.<p>`) and,
  when an emitter is bound (`--obs`), in `costEntry` JSONL records. The
  dispatch loops combine the stored FLOP count with their own measured
  wall time into `cost.achieved_tflops` / `cost.flop_utilization_pct` /
  `cost.logical_gbps` — bench's `kernel_cost` numbers, live.

  MEMORY — `MemPoller` samples `device.memory_stats()` from its own
  daemon thread on the metricsEntry cadence, feeding `device.mem_*`
  gauges; /readyz (obs/http.py) degrades with reason `near_hbm_limit`
  when `device.mem_frac_used` crosses NEAR_HBM_FRAC. Polling runs OFF
  the dispatch path by construction — `memory_stats()` is a host sync
  hazard there (tt-analyze TT603 bans it in trace targets and dispatch
  loops; this module is the sanctioned home).

  PROFILE — `ProfileCapture` drives `jax.profiler` start/stop from a
  worker thread: `tt profile URL --for N` (or GET /profile?for=N on the
  `--obs-listen` front, or `--profile-for N` at launch) triggers a
  capture spanning the next N dispatches. The dispatch loop only flips
  a counter (`on_dispatch`), so a hung or dying capture — fault site
  `profile`, like the poller's `mem_poll` — can never stall dispatch,
  serve, or writer drain (tests pin it).

The standing invariant: the record stream is identical with the
observatory on or off. `costEntry` is a TIMING record (jsonl.
TIMING_RECORDS), counters/gauges write no records, and the AOT proxy
compiles the same program jit would — engine and serve A/Bs pin stream
identity with `TT_COST_OBS=0` (the kill switch that bypasses wrapping).

Import-time stdlib-only, like the rest of obs/ (`tt trace`/`tt stats`
must run without jax); the jax touches live behind function-local
imports used only by the engine/serve processes.
"""

from __future__ import annotations

import atexit
import hashlib
import os
import sys
import threading
import time

from timetabling_ga_tpu.obs import metrics as obs_metrics

# kill switch: TT_COST_OBS=0 makes instrument() the identity, restoring
# the plain jit dispatch path (the records-identical A/B's other leg)
ENABLED = os.environ.get("TT_COST_OBS", "1") != "0"


def _faults():
    """The fault-injection module, imported lazily: `runtime.__init__`
    pulls the engine (and so jax), and this module must stay
    importable without either — `tt profile` is a stdlib HTTP client.
    Poller/capture threads only exist inside engine/serve processes,
    where the runtime package is long imported."""
    from timetabling_ga_tpu.runtime import faults
    return faults

# chip peaks for the roofline placement (v5e public numbers — the same
# constants bench.py's kernel_cost leg reported offline)
HBM_PEAK_GBPS = 819.0       # HBM bandwidth
BF16_PEAK_TFLOPS = 197.0    # MXU bf16

# /readyz degrades with reason `near_hbm_limit` at this device.mem
# fraction: past it the next placement is an OOM gamble, so a fleet
# router should stop sending new work here
NEAR_HBM_FRAC = float(os.environ.get("TT_MEM_READY_FRAC", "0.92"))

# bounded transient-compile retries (the remote-compile RPC dies
# mid-response on tunneled devices — BENCH_r05, retry.TRANSIENT_MARKERS)
COMPILE_ATTEMPTS = 3


def _sig(args) -> tuple:
    """Input-signature key for the per-program executable cache: the
    pytree structure plus shapes and dtypes of every array leaf and
    the types of python scalars — the signature jax.jit keys its own
    cache on, so one CostProgram compile corresponds to one jit
    compile. For serve's lane programs the signature IS the shape
    bucket (pad_problem maps every in-bucket instance to these
    shapes).

    The primary path flattens through jax's own pytree machinery
    (lazy import — this module stays import-time stdlib-only), which
    sees REGISTERED custom nodes like ProblemArrays; a dataclass
    pytree is opaque to any hand-rolled walk, and missing its leaves
    once collided two serve buckets onto one compiled executable. The
    stdlib fallback (no jax importable) handles tuples/lists/dicts/
    dataclasses for plain-python callables."""
    try:
        from jax import tree_util as _tu
        leaves, treedef = _tu.tree_flatten(args)
        out: list = [str(treedef)]
        for x in leaves:
            if hasattr(x, "shape") and hasattr(x, "dtype"):
                out.append((str(x.dtype), tuple(x.shape)))
            else:
                out.append(type(x).__name__)
        return tuple(out)
    except Exception:
        pass
    import dataclasses as _dc
    out = []

    def walk(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            out.append((str(x.dtype), tuple(x.shape)))
        elif isinstance(x, (list, tuple)):
            out.append(type(x).__name__)
            for y in x:
                walk(y)
        elif isinstance(x, dict):
            for k in sorted(x):
                out.append(str(k))
                walk(x[k])
        elif _dc.is_dataclass(x) and not isinstance(x, type):
            out.append(type(x).__name__)
            for f in _dc.fields(x):
                walk(getattr(x, f.name))
        else:
            out.append(type(x).__name__)

    walk(args)
    return tuple(out)


def sig_tag(sig: tuple) -> str:
    """Short deterministic label for a signature (the exemplar /
    costEntry `sig` value a dashboard joins buckets on)."""
    return hashlib.md5(repr(sig).encode()).hexdigest()[:10]


def extract_cost(compiled) -> dict:
    """Normalize an XLA executable's `cost_analysis()` /
    `memory_analysis()` into one flat dict (missing pieces are simply
    absent — CPU backends report fewer fields). Duck-typed so this
    module never imports jax."""
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = ca or {}
        if ca.get("flops", 0.0) > 0:
            out["flops"] = float(ca["flops"])
        if ca.get("bytes accessed", 0.0) > 0:
            out["bytes_accessed"] = float(ca["bytes accessed"])
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        for field, key in (("temp_size_in_bytes", "temp_bytes"),
                           ("argument_size_in_bytes", "arg_bytes"),
                           ("output_size_in_bytes", "out_bytes"),
                           ("generated_code_size_in_bytes",
                            "code_bytes")):
            v = getattr(ma, field, None)
            if v:
                out[key] = float(v)
    except Exception:
        pass
    fl, by = out.get("flops"), out.get("bytes_accessed")
    if fl and by:
        out["intensity"] = fl / by
    return out


def roofline(flops_per_eval: float, bytes_per_eval: float,
             per_sec: float) -> dict:
    """The roofline-placement dict bench.py's `kernel_cost` leg reports
    (same keys as BENCH_r05's, so the archived JSON schema holds):
    achieved TFLOPS vs the bf16 peak, logical GB/s vs the HBM peak, and
    the fraction of logical bytes the HBM provably never served (XLA's
    'bytes accessed' is per-HLO LOGICAL traffic, counted before fusion
    keeps intermediates in VMEM — an upper bound on HBM bytes, so any
    excess over the HBM peak is positive evidence of fusion)."""
    out = {"flops_per_eval": round(flops_per_eval, 1),
           "logical_bytes_per_eval": round(bytes_per_eval, 1),
           "arithmetic_intensity_flops_per_byte":
               (round(flops_per_eval / bytes_per_eval, 3)
                if bytes_per_eval else None)}
    if bytes_per_eval and per_sec:
        logical_gbps = bytes_per_eval * per_sec / 1e9
        tflops = flops_per_eval * per_sec / 1e12
        out["achieved_tflops"] = round(tflops, 1)
        out["bf16_peak_tflops"] = BF16_PEAK_TFLOPS
        out["flop_utilization_vs_bf16_peak_pct"] = round(
            100 * tflops / BF16_PEAK_TFLOPS, 1)
        out["logical_gbps_at_measured_rate"] = round(logical_gbps, 1)
        out["hbm_peak_gbps"] = HBM_PEAK_GBPS
        out["min_fused_fraction_pct"] = round(
            max(0.0, 100 * (1 - HBM_PEAK_GBPS / logical_gbps)), 1)
    return out


def set_live_roofline(cost: dict | None, dt: float,
                      registry=None) -> None:
    """Update the live achieved-vs-peak gauges from one dispatched
    program's compile-time cost dict (`CostProgram.last_cost`) and its
    measured wall time — THE formula, owned here next to the peaks so
    the engine's `_process` and the serve scheduler's quantum cannot
    drift on it: `cost.achieved_tflops`, `cost.flop_utilization_pct`,
    `cost.logical_gbps`."""
    if not cost or dt <= 0:
        return
    reg = obs_metrics.REGISTRY if registry is None else registry
    fl = cost.get("flops")
    if fl:
        tf = fl / dt / 1e12
        reg.gauge("cost.achieved_tflops").set(tf)
        reg.gauge("cost.flop_utilization_pct").set(
            100.0 * tf / BF16_PEAK_TFLOPS)
    by = cost.get("bytes_accessed")
    if by:
        reg.gauge("cost.logical_gbps").set(by / dt / 1e9)


class Observatory:
    """Process-global costEntry emission target. The registry half of
    the observatory is always on (counters and gauges, like the rest of
    tt-obs); record emission binds per run: engine.run / SolveService
    `bind(writer, now=tracer.now)` under `--obs` and unbind in their
    finallys, so the global never holds a finished run's writer alive
    and the JSONL stream is identical with the observatory on or off
    (costEntry is a TIMING record either way)."""

    def __init__(self, registry=None):
        self.registry = (obs_metrics.REGISTRY if registry is None
                         else registry)
        self._lock = threading.Lock()
        self._out = None
        self._now = None
        # recent compile entries (program, sig, cost dict) — a bounded
        # introspection surface for tests and `last_cost` consumers
        self.entries: list = []

    def bind(self, out, now=None) -> None:
        with self._lock:
            self._out = out
            self._now = now

    def unbind(self) -> None:
        self.bind(None)

    def record_compile(self, program: str, sig: tuple, lower_s: float,
                       compile_s: float, cost: dict,
                       retries: int = 0) -> None:
        reg = self.registry
        reg.counter("compile.count").inc()
        reg.counter(f"compile.count.{program}").inc()
        tag = sig_tag(sig)
        reg.histogram("compile.seconds").observe(
            lower_s + compile_s, exemplar={"program": program,
                                           "sig": tag})
        if retries:
            reg.counter("compile.retries").inc(retries)
        fl = cost.get("flops")
        if fl is not None:
            reg.gauge(f"cost.flops.{program}").set(fl)
        by = cost.get("bytes_accessed")
        if by is not None:
            reg.gauge(f"cost.bytes.{program}").set(by)
        ai = cost.get("intensity")
        if ai is not None:
            reg.gauge(f"cost.intensity.{program}").set(ai)
        tb = cost.get("temp_bytes")
        if tb is not None:
            reg.gauge(f"cost.temp_bytes.{program}").set(tb)
        with self._lock:
            self.entries.append({"program": program, "sig": tag,
                                 "lower_s": lower_s,
                                 "compile_s": compile_s, **cost})
            del self.entries[:-256]
            out, now = self._out, self._now
        if out is not None:
            try:
                from timetabling_ga_tpu.runtime import jsonl
                extra = {k: (round(v, 3) if isinstance(v, float) else v)
                         for k, v in cost.items()}
                if retries:
                    extra["retries"] = retries
                if now is not None:
                    extra["ts"] = round(max(0.0, float(now())), 6)
                jsonl.cost_entry(out, program, sig=tag,
                                 lowerSeconds=round(lower_s, 4),
                                 compileSeconds=round(compile_s, 4),
                                 **extra)
            except Exception:
                pass   # telemetry must never fail a compile

    def hit(self, program: str) -> None:
        self.registry.counter("compile.cache_hits").inc()


OBSERVATORY = Observatory()


def compile_hit_rate(registry=None) -> float:
    """Warm-dispatch fraction: cache_hits / (cache_hits + count). THE
    serve-path number ROADMAP item 3's bucket-affine routing steers on;
    bench's soak leg reports its per-leg delta."""
    reg = OBSERVATORY.registry if registry is None else registry
    hits = reg.counter("compile.cache_hits").value
    total = hits + reg.counter("compile.count").value
    return hits / total if total else 0.0


class CostProgram:
    """AOT-dispatching proxy around one jitted program.

    Per input signature the FIRST call runs `fn.lower(args)` +
    `.compile()` explicitly — each half timed, the executable's
    cost/memory analyses extracted (this is the only moment they are
    free: later they would cost a recompile, which is why TT603 bans
    the introspection calls anywhere near the dispatch path) — then
    dispatches through the compiled executable; later calls with the
    same signature dispatch directly (a `compile.cache_hits` tick).
    Transient compile failures (the tunnel's remote-compile RPC deaths)
    retry bounded with `compile.retries` accounting; anything
    unexpected about the AOT path itself falls back to the plain jit
    call so the observatory can degrade but never break a run.

    `last_cost` holds the cost dict of the executable the most recent
    call used — the dispatch loops join it with their own measured wall
    time into the achieved-vs-peak gauges. `last_compiled` says whether
    that call PAID the compile: a compiling dispatch's wall time is
    compile+execute, and dividing FLOPs by it would crater the
    roofline gauges 10-100x on every cold dispatch — callers skip the
    roofline update when it is True (compile.seconds carries that cost
    under its own name)."""

    __slots__ = ("_fn", "program", "_obs", "_compiled", "_lock",
                 "last_cost", "last_compiled", "last_compile_s")

    def __init__(self, fn, program: str, observatory=None):
        self._fn = fn
        self.program = program
        self._obs = OBSERVATORY if observatory is None else observatory
        self._compiled: dict = {}
        self._lock = threading.Lock()
        self.last_cost: dict | None = None
        self.last_compiled = False
        # the lower+compile wall the most recent call paid (0.0 on a
        # warm dispatch): the usage ledger's compile-amortization
        # input — a cold dispatch's wall time is compile+execute, and
        # tt-meter attributes the two halves under their own names
        self.last_compile_s = 0.0

    def _compile(self, sig: tuple, args):
        from timetabling_ga_tpu.runtime import retry
        retries = 0
        while True:
            try:
                t0 = time.perf_counter()
                lowered = self._fn.lower(*args)
                t1 = time.perf_counter()
                exe = lowered.compile()
                t2 = time.perf_counter()
                break
            except Exception as e:
                if (retry.is_transient(e)
                        and retries + 1 < COMPILE_ATTEMPTS):
                    retries += 1
                    continue
                # the AOT path failed non-transiently: degrade to the
                # plain jit call (which may still succeed — e.g. an
                # argument AOT is stricter about) and stop wrapping
                # this signature; accounting still counts the compile
                print(f"warning: cost observatory AOT compile failed "
                      f"for {self.program} ({str(e)[:120]}); falling "
                      f"back to plain dispatch", file=sys.stderr)
                self._obs.record_compile(self.program, sig, 0.0, 0.0,
                                         {}, retries=retries)
                return {"exe": None, "cost": {}, "seconds": 0.0}
        cost = extract_cost(exe)
        self._obs.record_compile(self.program, sig, t1 - t0, t2 - t1,
                                 cost, retries=retries)
        # tt-prof sidecar harvest: the compiled module's per-op
        # metadata carries the named_scope phase path the trace events
        # don't — compile time is the only free moment to read it
        # (the same TT603 argument as extract_cost above)
        try:
            from timetabling_ga_tpu.obs import prof as obs_prof
            obs_prof.note_executable(exe)
        except Exception:
            pass
        return {"exe": exe, "cost": cost, "seconds": t2 - t0}

    def __call__(self, *args):
        sig = _sig(args)
        compiled_now = False
        entry = self._compiled.get(sig)
        if entry is None:
            with self._lock:
                entry = self._compiled.get(sig)
                if entry is None:
                    entry = self._compile(sig, args)
                    self._compiled[sig] = entry
                    compiled_now = True
        if not compiled_now:
            self._obs.hit(self.program)
        self.last_compiled = compiled_now
        self.last_compile_s = (entry.get("seconds", 0.0)
                               if compiled_now else 0.0)
        self.last_cost = entry["cost"] or None
        exe = entry["exe"]
        if exe is None:
            return self._fn(*args)
        try:
            return exe(*args)
        except TypeError as e:
            # an aval mismatch means the signature keying missed a
            # distinction the executable enforces — degrade THIS
            # signature to the plain jit path (which re-specializes
            # correctly) instead of failing the dispatch; a wrong
            # RESULT is impossible either way, the executable refuses
            # mismatched avals outright
            print(f"warning: cost observatory signature miss for "
                  f"{self.program} ({str(e)[:120]}); falling back to "
                  f"plain dispatch", file=sys.stderr)
            entry["exe"] = None
            return self._fn(*args)


def instrument(fn, program: str, observatory=None):
    """Wrap `fn` (a jitted program) in compile accounting; the identity
    when the observatory is disabled (TT_COST_OBS=0) so the plain jit
    dispatch path remains one env var away."""
    if not ENABLED or fn is None or isinstance(fn, CostProgram):
        return fn
    return CostProgram(fn, program, observatory=observatory)


# ------------------------------------------------------------ mem poller


def jax_memory_stats_fn():
    """A stats source for MemPoller reading the local devices'
    `memory_stats()` (summed over local devices; None where the backend
    has no allocator stats — CPU). Built by the engine/serve processes
    only; the jax import is function-local so this module stays
    import-time stdlib-only."""
    import jax
    devices = jax.local_devices()

    def read():
        agg: dict = {}
        for d in devices:
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if not ms:
                continue
            for k in ("bytes_in_use", "bytes_limit",
                      "peak_bytes_in_use"):
                if k in ms:
                    agg[k] = agg.get(k, 0) + int(ms[k])
        return agg or None

    return read


class MemPoller:
    """Off-dispatch-path device memory telemetry: a daemon thread
    samples `stats_fn()` every `interval_s` seconds and feeds the
    `device.mem_*` gauges (`bytes_in_use`, `bytes_limit`,
    `peak_bytes_in_use`, `frac_used`) plus a `device.mem_polls`
    counter. /readyz turns `device.mem_frac_used` >= NEAR_HBM_FRAC into
    the `near_hbm_limit` degraded reason.

    Fault site `mem_poll` fires once per sample on THIS thread: `hang`
    parks the poller (gauges go stale, nothing else notices), `die`
    ends it silently — dispatch, serve, and writer drain never wait on
    it (tests pin that). Writes no records, so the JSONL stream is
    identical with the poller on or off."""

    def __init__(self, stats_fn, interval_s: float = 1.0, registry=None):
        self._stats_fn = stats_fn
        self._interval = max(0.05, float(interval_s))
        self._reg = (obs_metrics.REGISTRY if registry is None
                     else registry)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="tt-mem-poll", daemon=True)

    def start(self) -> "MemPoller":
        self._thread.start()
        # stop the poller before interpreter teardown even on abrupt
        # exits: a daemon thread inside the runtime's memory_stats RPC
        # while the backend is being destroyed is a segfault, not an
        # exception (close() is idempotent — normal owners still call
        # it from their finallys)
        atexit.register(self.close)
        return self

    def alive(self) -> bool:
        return self._thread.is_alive()

    def poll_once(self) -> bool:
        """One sample; False when the thread should exit (injected
        death)."""
        if sys.is_finalizing():
            return False
        try:
            _faults().maybe_fail("mem_poll")
            stats = self._stats_fn()
        except SystemExit:
            return False            # injected death: exit silently
        except Exception:
            self._reg.counter("device.mem_poll_errors").inc()
            return True
        self._reg.counter("device.mem_polls").inc()
        if not stats:
            return True
        in_use = stats.get("bytes_in_use")
        limit = stats.get("bytes_limit")
        if in_use is not None:
            self._reg.gauge("device.mem_bytes_in_use").set(in_use)
        if limit:
            self._reg.gauge("device.mem_bytes_limit").set(limit)
            if in_use is not None:
                self._reg.gauge("device.mem_frac_used").set(
                    in_use / limit)
        peak = stats.get("peak_bytes_in_use")
        if peak is not None:
            self._reg.gauge("device.mem_peak_bytes_in_use").set(peak)
        return True

    def _loop(self) -> None:
        while True:
            if not self.poll_once():
                return
            if self._stop.wait(self._interval):
                return

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)   # a hung poller is abandoned
        #                                  (daemon), never waited out
        atexit.unregister(self.close)    # don't accumulate one atexit
        #                                  entry per closed run


# -------------------------------------------------------- profile capture


class ProfileCapture:
    """On-demand `jax.profiler` capture spanning N dispatches, driven
    entirely OFF the dispatch path.

    A worker thread owns the profiler start/stop calls (`start_fn(dir)`
    / `stop_fn()` — the engine passes jax.profiler closures, keeping
    this module jax-free); the dispatch loop only calls `on_dispatch()`
    — a lock-guarded counter decrement — and the HTTP front only calls
    `trigger(n)` — a state flip plus a worker wake. The first capture
    in a process pays jax.profiler's lazy profiler-plugin import
    (tensorflow — tens of seconds) ON THE WORKER, so a short run may
    end before its capture starts; close() then guarantees the late
    start is abandoned rather than leaving a stray session (the
    `_closed` re-check below). On-demand profiling targets long-lived
    runs and serve processes, where the one-time import is noise; for
    one-dispatch captures of short runs `--trace-profile` (main
    thread, synchronous) remains the tool. Fault site
    `profile` fires on the worker around each start/stop: `hang` parks
    the worker (the capture never materializes; dispatches continue),
    `die` ends it — either way nothing on the solve path blocks (tests
    pin it). One capture at a time: `trigger` while one is active
    answers busy instead of queueing.

    tt-prof rides the worker too: set `on_complete` to a callable of
    the finished capture's directory (obs/prof.capture_hook — sidecar
    write + attribution + gauge/profEntry publish) and it runs ON THIS
    WORKER after each successful stop; its return value is kept as
    `last()` for the /profile?last=1 poll `tt profile --attribute`
    reads. Hook failures warn and never break the capture machinery;
    the close-race teardown path skips the hook (the capture being
    abandoned was never cleanly stopped)."""

    def __init__(self, start_fn, stop_fn, default_dir: str | None = None,
                 registry=None):
        self._start_fn = start_fn
        self._stop_fn = stop_fn
        self.default_dir = default_dir or "tt-profile"
        self._reg = (obs_metrics.REGISTRY if registry is None
                     else registry)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._cmd = None          # ("start", n, dir) | ("stop",) | close
        self._busy = False        # trigger accepted, capture not closed
        self._remaining = 0       # dispatches left in the live capture
        self._closed = False
        self.on_complete = None   # callable(dir) after a clean stop
        self._active_dir = None   # dir of the live capture
        self._last_attr = None    # last on_complete return (tt-prof)
        self._completed = 0       # captures fully stopped
        self._thread = threading.Thread(
            target=self._worker, name="tt-profile", daemon=True)
        self._thread.start()
        # close (stopping any live capture) before interpreter
        # teardown on abrupt exits — an active profiler session plus a
        # half-destroyed backend is a crash at exit, not an error.
        # Idempotent; normal owners still close() from their finallys.
        atexit.register(self.close)

    def trigger(self, n: int, out_dir: str | None = None) -> dict:
        """Request a capture of the next `n` dispatches. Returns the
        ack the /profile endpoint serializes."""
        n = max(1, int(n))
        with self._lock:
            if self._closed:
                return {"ok": False, "reason": "capture closed"}
            if self._busy:
                return {"ok": False, "reason": "capture already active"}
            self._busy = True
            self._cmd = ("start", n, out_dir or self.default_dir)
        self._wake.set()
        return {"ok": True, "dispatches": n,
                "dir": out_dir or self.default_dir}

    def on_dispatch(self) -> None:
        """One dispatch retired (called by the engine/serve loops;
        never blocks beyond the counter lock)."""
        with self._lock:
            if self._remaining <= 0:
                return
            self._remaining -= 1
            if self._remaining > 0:
                return
            self._cmd = ("stop",)
        self._wake.set()

    def active(self) -> bool:
        with self._lock:
            return self._busy

    def last(self) -> dict:
        """Completed-capture count plus the newest attribution result
        (None until an on_complete hook has produced one). Served by
        /profile?last=1 — a pure read, like every handler-path touch
        of this object (TT602)."""
        with self._lock:
            return {"completed": self._completed,
                    "result": self._last_attr}

    def _worker(self) -> None:
        while True:
            self._wake.wait()
            self._wake.clear()
            with self._lock:
                cmd, self._cmd = self._cmd, None
                if self._closed and cmd is None:
                    return
            if cmd is None:
                continue
            if cmd[0] == "start":
                try:
                    _faults().maybe_fail("profile")
                except SystemExit:
                    return          # injected death: dispatches go on
                with self._lock:
                    if self._closed:
                        # close() won the race while this worker was
                        # parked (the `hang` fault): starting now
                        # would leave a stray profiler session nobody
                        # stops — poisoning every later capture in the
                        # process
                        self._busy = False
                        return
                try:
                    self._start_fn(cmd[2])
                except SystemExit:
                    return
                except Exception as e:
                    print(f"warning: profiler capture failed to start: "
                          f"{str(e)[:120]}", file=sys.stderr)
                    with self._lock:
                        self._busy = False
                    continue
                self._reg.counter("profile.captures").inc()
                with self._lock:
                    self._remaining = cmd[1]
                    self._active_dir = cmd[2]
            elif cmd[0] == "stop":
                stopped = True
                try:
                    _faults().maybe_fail("profile")
                    self._stop_fn()
                except SystemExit:
                    return
                except Exception as e:
                    stopped = False
                    print(f"warning: profiler capture failed to stop: "
                          f"{str(e)[:120]}", file=sys.stderr)
                with self._lock:
                    self._busy = False
                    self._remaining = 0
                    hook, cdir = self.on_complete, self._active_dir
                    self._active_dir = None
                # tt-prof attribution on THIS worker (never the
                # dispatch path): sidecar + parse + publish; a hook
                # failure degrades to an unattributed capture, the
                # capture machinery itself never breaks on it
                res = None
                if stopped and hook is not None and cdir is not None:
                    try:
                        res = hook(cdir)
                    except Exception as e:
                        print(f"warning: profile attribution failed: "
                              f"{str(e)[:120]}", file=sys.stderr)
                with self._lock:
                    self._last_attr = res
                    self._completed += 1
            # a close() that arrived WITH the command just processed
            # (its wake was consumed above) must end the worker now —
            # looping back to wait() would park the thread forever and
            # make every such close() burn its full join timeout. And
            # if close() raced the START just performed (it checked
            # _remaining before this worker set it, so it queued no
            # stop), the live session must be stopped HERE — returning
            # with it open would leave a stray profiler session nobody
            # ever stops (the docstring's abandonment guarantee).
            with self._lock:
                if not (self._closed and self._cmd is None):
                    continue
                live = self._remaining > 0
                self._busy = False
                self._remaining = 0
            if live:
                try:
                    self._stop_fn()
                except Exception:
                    pass
            return

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._busy and self._remaining > 0:
                self._remaining = 0
                self._cmd = ("stop",)
        self._wake.set()
        self._thread.join(timeout=2.0)   # hung worker: abandoned daemon
        atexit.unregister(self.close)


# ------------------------------------------------------- tt profile (CLI)


def main_profile(argv) -> int:
    """`tt profile <url> [--for N] [--attribute [--timeout S]]` —
    trigger an on-demand profiler capture on a live run/serve process
    through its `--obs-listen` front (GET /profile?for=N).
    `--attribute` then polls GET /profile?last=1 until the capture
    lands and renders the tt-prof phase breakdown (obs/prof.render).
    Stdlib-only and device-free, like `tt trace`/`tt stats`: it talks
    to the process, it is not one."""
    url, n, attrib, timeout_s = None, 1, False, 120.0
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            print("usage: tt profile <http://host:port> [--for N] "
                  "[--attribute [--timeout S]]\n\n"
                  "ask a live run (--obs-listen) to capture a "
                  "jax.profiler trace of its next N dispatches into "
                  "its --profile-dir; view with tensorboard/xprof.\n"
                  "--attribute waits for the capture to land and "
                  "renders the tt-prof per-phase device-time table")
            return 0
        if a == "--for":
            if i + 1 >= len(argv):
                raise SystemExit("flag --for needs a value")
            n = int(argv[i + 1])
            i += 2
            continue
        if a == "--attribute":
            attrib = True
            i += 1
            continue
        if a == "--timeout":
            if i + 1 >= len(argv):
                raise SystemExit("flag --timeout needs a value")
            timeout_s = float(argv[i + 1])
            i += 2
            continue
        if url is None:
            url = a
            i += 1
            continue
        raise SystemExit(f"unknown argument: {a}")
    if url is None:
        raise SystemExit("usage: tt profile <http://host:port> "
                         "[--for N] [--attribute]")
    if "://" not in url:
        url = "http://" + url
    import json as _json
    import urllib.error
    import urllib.request

    def get(path: str) -> dict:
        try:
            with urllib.request.urlopen(
                    f"{url.rstrip('/')}{path}", timeout=10) as resp:
                return _json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                return _json.loads(e.read().decode())
            except Exception:
                return {"ok": False, "reason": str(e)}
        except Exception as e:
            raise SystemExit(f"tt profile: {e}") from None

    before = get("/profile?last=1").get("completed", 0) if attrib else 0
    body = get(f"/profile?for={int(n)}")
    print(_json.dumps(body))
    if not body.get("ok"):
        return 1
    if not attrib:
        return 0
    # poll until the capture's stop (and its worker-side attribution)
    # lands — the completed counter bumps exactly once per capture
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        last = get("/profile?last=1")
        if last.get("completed", 0) > before:
            res = last.get("result")
            if res is None:
                print("tt profile: capture landed but no attribution "
                      "(no on-complete hook or parse failed)",
                      file=sys.stderr)
                return 1
            from timetabling_ga_tpu.obs import prof as obs_prof
            print(obs_prof.render(res))
            return 0
        time.sleep(0.5)
    print(f"tt profile: capture did not land within {timeout_s:.0f}s "
          f"(needs {int(n)} more dispatches?)", file=sys.stderr)
    return 1
