"""`tt trace` — export a JSONL log's spans as Chrome trace-event JSON.

    tt trace run.jsonl -o trace.json

The output is the Trace Event Format's "JSON object" flavor
({"traceEvents": [...]}) loadable in Perfetto / chrome://tracing, so a
run's host-side span timeline (dispatch / fetch / process / checkpoint
/ serve quanta) can be read next to a `--trace-profile` device
timeline. Mapping:

  spanEntry    -> complete event (ph "X"): ts/dur in microseconds,
                  tid = the tracer's per-thread lane, args = every
                  extra attribute the span carried
  phase        -> complete event on its own lane ("phases"): the legacy
                  `--trace` records have no start timestamp, so they
                  are laid end-to-end in record order — coarse, but it
                  puts pre-obs logs on the same screen
  metricsEntry -> counter events (ph "C") for every numeric counter/
                  gauge, at the snapshot's `ts` — Perfetto renders
                  them as tracks (gens/sec, queue depth over time)

Stdlib-only and device-free: exporting a log must work on any machine
the log was copied to.
"""

from __future__ import annotations

import json
import sys


def _span_event(e: dict) -> dict:
    args = {k: v for k, v in e.items()
            if k not in ("name", "cat", "ts", "dur", "depth", "tid")}
    args["depth"] = e.get("depth", 0)
    return {"name": e.get("name", "?"), "cat": e.get("cat", "engine"),
            "ph": "X", "pid": 0, "tid": int(e.get("tid", 0)),
            "ts": round(float(e.get("ts", 0.0)) * 1e6, 3),
            "dur": round(max(0.0, float(e.get("dur", 0.0))) * 1e6, 3),
            "args": args}


def _counter_events(rec: dict) -> list[dict]:
    ts = rec.get("ts")
    if ts is None:
        return []
    out = []
    for kind in ("counters", "gauges"):
        for name, v in (rec.get(kind) or {}).items():
            if isinstance(v, (int, float)) and v == v:
                out.append({"name": name, "ph": "C", "pid": 0, "tid": 0,
                            "ts": round(float(ts) * 1e6, 3),
                            "args": {"value": v}})
    return out


def export_chrome_trace(records) -> dict:
    """JSONL record dicts -> Chrome trace-event JSON object."""
    events: list[dict] = []
    phase_t = 0.0
    for rec in records:
        if "spanEntry" in rec:
            events.append(_span_event(rec["spanEntry"]))
        elif "metricsEntry" in rec:
            events.extend(_counter_events(rec["metricsEntry"]))
        elif "phase" in rec:
            p = rec["phase"]
            dur = max(0.0, float(p.get("seconds", 0.0)))
            args = {k: v for k, v in p.items()
                    if k not in ("name", "seconds")}
            events.append({"name": p.get("name", "?"), "cat": "phase",
                           "ph": "X", "pid": 0, "tid": 999,
                           "ts": round(phase_t * 1e6, 3),
                           "dur": round(dur * 1e6, 3), "args": args})
            phase_t += dur
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"source": "tt trace",
                          "format": "timetabling_ga_tpu JSONL"}}


def read_jsonl(path: str) -> list[dict]:
    records = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                # a torn tail line (killed run) must not block export
                continue
    return records


def main_trace(argv) -> int:
    """`tt trace <log.jsonl> [-o trace.json]` entry point."""
    inp, out = None, None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            print("usage: tt trace <log.jsonl> [-o trace.json]\n\n"
                  "export spanEntry/phase/metricsEntry records as "
                  "Chrome trace-event JSON (Perfetto / chrome://tracing)")
            return 0
        if a == "-o":
            if i + 1 >= len(argv):
                raise SystemExit("flag -o needs a value")
            out = argv[i + 1]
            i += 2
            continue
        if inp is None:
            inp = a
            i += 1
            continue
        raise SystemExit(f"unknown argument: {a}")
    if inp is None:
        raise SystemExit("usage: tt trace <log.jsonl> [-o trace.json]")
    doc = export_chrome_trace(read_jsonl(inp))
    if out is None:
        out = inp + ".trace.json"
    with open(out, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    n = len(doc["traceEvents"])
    print(f"tt trace: {n} event{'s' if n != 1 else ''} -> {out}",
          file=sys.stderr)
    return 0
