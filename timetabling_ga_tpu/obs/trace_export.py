"""`tt trace` — export JSONL logs' spans as Chrome trace-event JSON.

    tt trace run.jsonl -o trace.json
    tt trace --job j42 serve.jsonl -o j42.json
    tt trace --job j42 gateway.jsonl replica0.jsonl replica1.jsonl

The output is the Trace Event Format's "JSON object" flavor
({"traceEvents": [...]}) loadable in Perfetto / chrome://tracing, so a
run's host-side span timeline (dispatch / fetch / process / checkpoint
/ serve quanta) can be read next to a `--trace-profile` device
timeline.

MULTIPLE inputs (tt-obs v5, the fleet observatory) stitch into ONE
timeline: each log becomes its own Perfetto PROCESS (pid = input
order, labeled with the file's basename via process_name metadata), so
a fleet trace shows the gateway's routing lanes above each replica's
dispatch lanes. Flow chains stitch across the process boundary: ids
at/above obs/spans.py XFLOW_BASE are CROSS-PROCESS chains (minted only
by the gateway and shipped to replicas as X-TT-Flow, so they are
globally unique) and are kept verbatim — the gateway's route/submit/
routed spans and the replica's admit/quantum/finalize spans share one
id and render as arrows crossing pids. Each log's LOCAL flow ids are
remapped into a per-input namespace, so two replicas' unrelated chunk
chains can never merge by id collision. Mapping:

  spanEntry    -> complete event (ph "X"): ts/dur in microseconds,
                  tid = the tracer's per-thread lane, args = every
                  extra attribute the span carried
  flow= attrs  -> Perfetto flow events (ph "s"/"t"/"f"): spans sharing
                  a flow id (SpanTracer.new_flow — one causal chain:
                  a dispatch's dispatch→fetch-read→process life across
                  the watchdog thread, a checkpoint's enqueue→write
                  handoff onto the writer thread, a serve job's
                  admit→pack→quantum→park→resume→finalize) render as
                  connected arrows across thread lanes. A span whose
                  `flow` is a LIST (a packed serve dispatch advancing
                  many jobs) participates in every listed chain.
  phase        -> complete event on its own lane ("phases"): the legacy
                  `--trace` records have no start timestamp, so they
                  are laid end-to-end in record order — coarse, but it
                  puts pre-obs logs on the same screen
  metricsEntry -> counter events (ph "C") for every numeric counter/
                  gauge, at the snapshot's `ts` — Perfetto renders
                  them as tracks (gens/sec, queue depth over time)
  qualityEntry -> counter events (ph "C") for every numeric quality
                  field (diversity Hamming/variance, operator win
                  counts, migration gain) at the entry's `ts` — the
                  search-quality observatory's per-dispatch telemetry
                  as live tracks next to the dispatch spans
  costEntry    -> complete event on the "compiles" lane (tid 998): a
                  slab of lowerSeconds+compileSeconds ENDING at the
                  record's `ts` (the observatory stamps emission right
                  after the compile returns), named
                  compile:<program> — XLA compile cost sits on the
                  same screen as the dispatches it delayed

`--job ID` filters to ONE job's causal trace: the spans tagged
`job=ID` (scalar, or carrying ID in a packed dispatch's job list),
connected by the job's own flow chain — its end-to-end
admit→pack→quantum→park→resume→finalize timeline (plus, in a stitched
fleet trace, the gateway's route→submit→routed→settle leg) across
lanes, parks, and co-tenant dispatches, without the other tenants'
noise. Counter tracks and phase lanes are process-global, so job mode
drops them.

Clock caveat for stitched traces: each log's `ts` is seconds since ITS
tracer's epoch, so lanes from different processes are aligned only as
well as the processes started together (a gateway and the replicas it
spawned share a start to within boot time). The flow ARROWS are exact
— they bind by id, not by clock.

Stdlib-only and device-free: exporting a log must work on any machine
the log was copied to.
"""

from __future__ import annotations

import json
import os
import sys

from timetabling_ga_tpu.obs.spans import XFLOW_BASE

# per-input namespace stride for LOCAL flow ids in stitched exports:
# far above both any realistic local id and the XFLOW_BASE range the
# gateway allocates in, so remapped ids collide with nothing
_LOCAL_FLOW_NS = 1 << 48


def _span_event(e: dict) -> dict:
    args = {k: v for k, v in e.items()
            if k not in ("name", "cat", "ts", "dur", "depth", "tid",
                         "_pid")}
    args["depth"] = e.get("depth", 0)
    return {"name": e.get("name", "?"), "cat": e.get("cat", "engine"),
            "ph": "X", "pid": int(e.get("_pid", 0)),
            "tid": int(e.get("tid", 0)),
            "ts": round(float(e.get("ts", 0.0)) * 1e6, 3),
            "dur": round(max(0.0, float(e.get("dur", 0.0))) * 1e6, 3),
            "args": args}


def _counter_events(rec: dict, pid: int = 0) -> list[dict]:
    ts = rec.get("ts")
    if ts is None:
        return []
    out = []
    for kind in ("counters", "gauges"):
        for name, v in (rec.get(kind) or {}).items():
            if isinstance(v, (int, float)) and v == v:
                out.append({"name": name, "ph": "C", "pid": pid,
                            "tid": 0,
                            "ts": round(float(ts) * 1e6, 3),
                            "args": {"value": v}})
    return out


def _quality_counter_events(rec: dict, pid: int = 0) -> list[dict]:
    """qualityEntry -> one Perfetto counter sample per numeric quality
    field. Serve entries are job-tagged (one entry per lane per
    dispatch); their track names get a `[job]` suffix so co-tenants'
    tracks stay apart."""
    ts = rec.get("ts")
    if ts is None:
        return []
    job = rec.get("job")
    out = []
    for name, v in rec.items():
        if name in ("ts", "job", "dispatch", "gens"):
            continue
        if isinstance(v, (int, float)) and v == v:
            track = f"{name}[{job}]" if job is not None else name
            out.append({"name": track, "ph": "C", "pid": pid, "tid": 0,
                        "ts": round(float(ts) * 1e6, 3),
                        "args": {"value": v}})
    return out


def _flow_ids(e: dict) -> list[int]:
    """A span's flow memberships: `flow` is an int, or a list when one
    span advances several causal chains (a packed serve dispatch).
    0/None entries mean 'no chain' (a disabled tracer's new_flow)."""
    f = e.get("flow")
    ids = f if isinstance(f, list) else [f]
    return [int(i) for i in ids
            if isinstance(i, (int, float)) and int(i) > 0]


def _span_matches_job(e: dict, job: str) -> bool:
    j = e.get("job")
    if isinstance(j, list):
        return job in [str(x) for x in j]
    return j is not None and str(j) == job


def _flow_events(spans: list[dict], only=None) -> list[dict]:
    """Perfetto flow events binding spans that share a flow id.

    The event timestamp sits at the MIDDLE of its span: flow events
    bind to the slice open at their ts on that thread lane, and the
    midpoint is inside the slice regardless of how sub-microsecond
    rounding moved its edges. Chain members are ORDERED by that same
    midpoint — not by span start — so the emitted `s` (first), `t`
    (steps), `f` (finish, bp="e") sequence is monotone in the
    timestamps it carries even when one member nests inside an
    earlier-starting sibling (a serve job's `finalize` runs inside the
    scheduler's `park` span). Chains with a single member draw no
    arrow — there is nothing to connect. `only` restricts to a set of
    chain ids (the --job view draws the job's own chain, not every
    co-tenant chain its packed dispatches also advanced)."""
    chains: dict[int, list[dict]] = {}
    for e in spans:
        for fid in _flow_ids(e):
            chains.setdefault(fid, []).append(e)
    out = []
    for fid, members in sorted(chains.items()):
        if len(members) < 2 or (only is not None and fid not in only):
            continue
        mids = sorted(((float(e.get("ts", 0.0))
                        + max(0.0, float(e.get("dur", 0.0))) / 2.0, e)
                       for e in members), key=lambda t: t[0])
        last = len(mids) - 1
        for i, (mid, e) in enumerate(mids):
            ev = {"name": "flow", "cat": "flow",
                  "ph": "s" if i == 0 else ("f" if i == last else "t"),
                  "id": fid, "pid": int(e.get("_pid", 0)),
                  "tid": int(e.get("tid", 0)),
                  "ts": round(mid * 1e6, 3)}
            if i == last:
                ev["bp"] = "e"     # bind to the enclosing slice
            out.append(ev)
    return out


def _remap_flow(flow, pid: int):
    """Stitched exports keep CROSS-PROCESS ids (>= XFLOW_BASE — minted
    by exactly one process, so globally unique) verbatim and move each
    log's local ids into a per-input namespace: replica 0's chunk
    chain 3 and replica 1's chunk chain 3 are different chains."""
    def one(i):
        if isinstance(i, (int, float)) and 0 < int(i) < XFLOW_BASE:
            return (pid + 1) * _LOCAL_FLOW_NS + int(i)
        return i
    if isinstance(flow, list):
        return [one(i) for i in flow]
    return one(flow)


def _collect(records, pid: int, remap: bool, job_mode: bool):
    """One log's records -> (span bodies tagged `_pid` [+ remapped
    flows], non-span events). Counter tracks / compile slabs / phase
    lanes are process-global, so job mode drops them (module
    docstring)."""
    spans: list[dict] = []
    events: list[dict] = []
    phase_t = 0.0
    for rec in records:
        if "spanEntry" in rec:
            e = dict(rec["spanEntry"])
            e["_pid"] = pid
            if remap and "flow" in e:
                e["flow"] = _remap_flow(e["flow"], pid)
            spans.append(e)
        elif not job_mode and "metricsEntry" in rec:
            events.extend(_counter_events(rec["metricsEntry"], pid))
        elif not job_mode and "qualityEntry" in rec:
            events.extend(
                _quality_counter_events(rec["qualityEntry"], pid))
        elif not job_mode and "costEntry" in rec:
            c = rec["costEntry"]
            ts = c.get("ts")
            if ts is not None:
                dur = max(0.0, float(c.get("lowerSeconds", 0.0))
                          + float(c.get("compileSeconds", 0.0)))
                args = {k: v for k, v in c.items()
                        if k not in ("ts", "program")}
                events.append(
                    {"name": f"compile:{c.get('program', '?')}",
                     "cat": "compile", "ph": "X", "pid": pid,
                     "tid": 998,
                     "ts": round(max(0.0, float(ts) - dur) * 1e6, 3),
                     "dur": round(dur * 1e6, 3), "args": args})
        elif not job_mode and "phase" in rec:
            p = rec["phase"]
            dur = max(0.0, float(p.get("seconds", 0.0)))
            args = {k: v for k, v in p.items()
                    if k not in ("name", "seconds")}
            events.append({"name": p.get("name", "?"), "cat": "phase",
                           "ph": "X", "pid": pid, "tid": 999,
                           "ts": round(phase_t * 1e6, 3),
                           "dur": round(dur * 1e6, 3), "args": args})
            phase_t += dur
    return spans, events


def export_stitched(inputs, job: str | None = None) -> dict:
    """[(label, records), ...] -> ONE Chrome trace-event JSON object.

    Each input becomes its own Perfetto process lane (pid = position,
    named `label` via process_name metadata when there are several);
    flow chains connect across inputs by shared CROSS-PROCESS ids
    (module docstring) while local ids are kept per-input. `job`
    filters to one job's causal trace across every input — for a fleet
    log set that is the gateway routing leg AND the replica solve leg,
    joined by the job's X-TT-Flow chain."""
    multi = len(inputs) > 1
    spans: list[dict] = []
    events: list[dict] = []
    meta: list[dict] = []
    for pid, (label, records) in enumerate(inputs):
        s, ev = _collect(records, pid, remap=multi,
                         job_mode=job is not None)
        spans.extend(s)
        events.extend(ev)
        if multi and label:
            meta.append({"name": "process_name", "ph": "M",
                         "pid": pid, "tid": 0,
                         "args": {"name": str(label)}})
    only = None
    if job is not None:
        job = str(job)
        spans = [e for e in spans if _span_matches_job(e, job)]
        # the job's OWN chain: the flow id its exclusively-tagged spans
        # (admit / shed / finalize — scalar job=) carry. Packed spans
        # also list the co-tenants' chain ids; drawing those would wire
        # the job's timeline to arrows about other tenants. Fallback to
        # every chain among the kept spans when no scalar tag survived
        # (a torn log that lost the admit record).
        only = {fid for e in spans
                if not isinstance(e.get("job"), list)
                for fid in _flow_ids(e)} or None
    events = meta + [_span_event(e) for e in spans] \
        + _flow_events(spans, only=only) + events
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"source": "tt trace",
                         "format": "timetabling_ga_tpu JSONL"}}
    if multi:
        doc["otherData"]["inputs"] = [str(lb) for lb, _ in inputs]
    if job is not None:
        doc["otherData"]["job"] = job
    return doc


def export_chrome_trace(records, job: str | None = None) -> dict:
    """JSONL record dicts -> Chrome trace-event JSON object (the
    single-log form; `tt trace` with several inputs uses
    export_stitched).

    `job` filters to one serve job's causal trace (see module
    docstring): its tagged spans, every span sharing its flow ids, and
    their flow arrows only."""
    return export_stitched([(None, records)], job=job)


def read_jsonl(path: str) -> list[dict]:
    records = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                # a torn tail line (killed run) must not block export
                continue
    return records


def main_trace(argv) -> int:
    """`tt trace <log.jsonl> [more.jsonl ...] [-o trace.json]
    [--job ID]` entry point."""
    inputs: list[str] = []
    out, job = None, None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            print("usage: tt trace <log.jsonl> [more.jsonl ...] "
                  "[-o trace.json] [--job ID]\n\n"
                  "export spanEntry/phase/metricsEntry records as "
                  "Chrome trace-event JSON (Perfetto / chrome://tracing)"
                  "\nwith flow arrows connecting causal chains across "
                  "thread lanes; --job ID renders one serve job's\n"
                  "end-to-end timeline (admit -> pack -> quantum -> "
                  "park -> resume) and nothing else.\n"
                  "Several inputs (gateway.jsonl replica*.jsonl) "
                  "stitch into ONE timeline with a process lane per\n"
                  "log and flow arrows crossing the process boundary "
                  "(a routed job's gateway leg + replica leg)")
            return 0
        if a in ("-o", "--job"):
            if i + 1 >= len(argv):
                raise SystemExit(f"flag {a} needs a value")
            if a == "-o":
                out = argv[i + 1]
            else:
                job = argv[i + 1]
            i += 2
            continue
        if a.startswith("-"):
            raise SystemExit(f"unknown argument: {a}")
        inputs.append(a)
        i += 1
    if not inputs:
        raise SystemExit("usage: tt trace <log.jsonl> [more.jsonl ...]"
                         " [-o trace.json] [--job ID]")
    resolved: list = []
    for p in inputs:
        records = read_jsonl(p)
        # an INCIDENT BUNDLE (obs/flight.py) is accepted next to JSONL
        # logs: its span/record rings expand into ordinary inputs — a
        # stitched bundle contributes one process lane per member, so
        # `tt trace gateway-bundle.json replica.jsonl` just works
        bundle = next((r["incident"] for r in records
                       if isinstance(r, dict)
                       and isinstance(r.get("incident"), dict)), None)
        if bundle is not None:
            from timetabling_ga_tpu.obs.flight import bundle_records
            base = os.path.basename(p)
            for label, recs in bundle_records(bundle):
                resolved.append((f"{base}:{label}", recs))
        else:
            resolved.append((os.path.basename(p), records))
    doc = export_stitched(resolved, job=job)
    if out is None:
        out = inputs[0] + ".trace.json"
    with open(out, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    n = len(doc["traceEvents"])
    tag = f" (job {job})" if job is not None else ""
    src = (inputs[0] if len(inputs) == 1
           else f"{len(inputs)} stitched logs")
    print(f"tt trace: {n} event{'s' if n != 1 else ''}{tag} from "
          f"{src} -> {out}", file=sys.stderr)
    return 0
