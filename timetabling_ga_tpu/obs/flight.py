"""tt-flight incident recorder: bounded black-box rings + automatic
incident bundles.

The wafer-scale island-GA experience (PAPERS.md) is blunt about scale:
nobody replays an 850k-core run to catch a transient. When something
goes wrong in a long run, a serve replica, or a fleet gateway, the
question is always "what happened in the 30 seconds BEFORE that" — and
by the time a human is looking, the live gauges have moved on. This
module keeps the answer on hand, continuously and bounded:

  SPAN TEE RING     the last spans the process emitted, under a byte
                    budget (`TT_FLIGHT_SPAN_BYTES`, default 256 KiB) —
                    the timeline of the final seconds
  RECORD RING       the last non-span records (logEntry / jobEntry /
                    faultEntry / metricsEntry ...), count-bounded
                    (`TT_FLIGHT_RECORDS_CAP`, default 512)

Both rings are fed by a TEE on the process's record stream
(`FlightRecorder.tee(stream)` wraps the stream the AsyncWriter drains
into, so ingestion runs on the WRITER thread — the same off-dispatch
discipline as the fleet JobTail) and cost O(1) per record. The tee
writes nothing and reorders nothing: the JSONL stream is bit-identical
with the recorder on or off (tests pin it).

TRIGGERS — when one fires, the recorder's own daemon thread (fault
site `flight_dump`: hang parks it, die ends it, dispatch/settlement/
writer drain never wait on it) dumps a self-contained INCIDENT BUNDLE
to `--incident-dir`:

  - a `/readyz` reason flips ON (the recorder polls
    obs/http.readiness() over the registry — covers `stalled`,
    `degraded`, `near_hbm_limit`, `backlog_full`, `slo_burn`,
    `dispatcher_stalled`, ... for every process uniformly);
  - a `faultEntry` lands on the record stream (recoveries, injected
    faults, SLO burn events, quantum requeues — detected by the tee);
  - an owner calls `trigger(reason)` directly (the gateway's failover
    path does).

Dumps are rate-limited by `--incident-min-interval` (a reason storm
produces one bundle, not a bundle storm) and retained oldest-first
under `TT_INCIDENT_KEEP` bundles per directory. A bundle carries:
trigger + readiness reasons, the config fingerprint, a full registry
snapshot, the metrics HISTORY window (obs/history.py), the span ring,
the record ring, and the `device.mem_*` sample series — everything the
"30 seconds before" question needs, with no external scrape store.

CROSS-PROCESS bundles: replicas serve their newest bundle in-memory at
`GET /v1/incident` (fleet/replicas.py — the handler reads `latest()`,
no file I/O: TT602/TT606). The gateway, on failover or SLO burn,
triggers its own recorder with the involved replica names; the
recorder thread pulls those replicas' bundles (live, falling back to
the prober's last cached copy for a replica that just died) and writes
ONE STITCHED bundle whose `trace` section reuses
obs/trace_export.export_stitched — same pid-lane and XFLOW-remap rules
as `tt trace`, so a routed job's gateway leg and replica leg share one
flow chain across process lanes. `tt incident DIR [--job ID]` renders
any bundle (stitched or single-process) back into Perfetto-loadable
JSON; `tt trace` accepts bundle files next to JSONL logs.

Stdlib-only and jax-free, like the rest of obs/ (`tt incident` must
run on any machine a bundle was copied to).
"""

from __future__ import annotations

import atexit
import collections
import hashlib
import itertools
import json
import os
import sys
import threading
import time

from timetabling_ga_tpu.obs import http as obs_http
from timetabling_ga_tpu.obs import metrics as obs_metrics
from timetabling_ga_tpu.obs import trace_export

BUNDLE_VERSION = 1

# per-process recorder ordinal: two recorders in ONE process (a
# gateway plus in-proc replicas sharing a directory) must not collide
# on pid+seq filenames — the second os.replace would silently clobber
# the first's bundle
_RECORDER_IDS = itertools.count(1)

# span tee ring byte budget and record ring capacity (module docstring)
SPAN_BYTES = int(os.environ.get("TT_FLIGHT_SPAN_BYTES",
                                str(256 * 1024)))
RECORDS_CAP = int(os.environ.get("TT_FLIGHT_RECORDS_CAP", "512"))
# bundles retained per --incident-dir (oldest-first deletion)
INCIDENT_KEEP = int(os.environ.get("TT_INCIDENT_KEEP", "16"))
# history window captured into a bundle (seconds)
BUNDLE_HISTORY_S = float(os.environ.get("TT_FLIGHT_HISTORY_S", "120"))


def _faults():
    from timetabling_ga_tpu.runtime import faults
    return faults


def config_fingerprint(cfg) -> dict:
    """A small, self-contained identity for the process's configuration
    — enough to tell two incident bundles apart ("was that the pop-256
    run?") without shipping the instance data. Values are stringified
    (a bundle must always serialize); the md5 is over the sorted field
    reprs, so two processes with identical flags fingerprint equal."""
    import dataclasses
    if dataclasses.is_dataclass(cfg) and not isinstance(cfg, type):
        items = {f.name: getattr(cfg, f.name)
                 for f in dataclasses.fields(cfg)}
    elif isinstance(cfg, dict):
        items = dict(cfg)
    else:
        items = dict(vars(cfg)) if hasattr(cfg, "__dict__") else {}
    values = {}
    for k in sorted(items):
        v = items[k]
        if isinstance(v, (str, int, float, bool)) or v is None:
            values[k] = v
        else:
            values[k] = repr(v)[:200]
    blob = repr(sorted((k, repr(v)) for k, v in values.items()))
    return {"kind": type(cfg).__name__,
            "md5": hashlib.md5(blob.encode()).hexdigest()[:12],
            "values": values}


def _approx_bytes(obj) -> int:
    """Cheap serialized-size estimate for the span ring's byte budget.
    Deliberately NOT json.dumps: ring accounting runs on the writer
    thread per span, and bundle serialization is banned anywhere near
    the hot paths (TT606) — an estimate within ~20% is plenty for a
    retention budget."""
    if isinstance(obj, dict):
        return 2 + sum(len(str(k)) + 4 + _approx_bytes(v)
                       for k, v in obj.items())
    if isinstance(obj, (list, tuple)):
        return 2 + sum(2 + _approx_bytes(v) for v in obj)
    if isinstance(obj, str):
        return len(obj) + 2
    return 8


class FlightTee:
    """Record-stream tee feeding a FlightRecorder's rings.

    Sits between the AsyncWriter and the real output stream (the fleet
    JobTail's position and discipline): every byte passes through
    unchanged, each complete line is parsed ON THE WRITER THREAD and
    handed to the recorder as a dict. Adds no records, reorders
    nothing — the stream is bit-identical with the tee on or off."""

    def __init__(self, stream, recorder: "FlightRecorder"):
        self._stream = stream
        self._rec = recorder
        self._buf = ""

    def write(self, s: str) -> None:
        self._stream.write(s)
        self._buf += s
        while "\n" in self._buf:
            line, self._buf = self._buf.split("\n", 1)
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec:
                self._rec.note_record(rec)

    def flush(self) -> None:
        self._stream.flush()


class FlightRecorder:
    """The black-box rings + the incident-dump daemon thread.

    `note_record` (writer thread, via FlightTee) feeds the rings and
    latches faultEntry triggers; `trigger` (any thread) requests a dump
    directly; the recorder THREAD polls readiness, merges pending
    triggers, applies the rate limit, and performs every file write —
    dumps belong on this thread and nowhere else (TT606)."""

    def __init__(self, incident_dir: str, registry=None, history=None,
                 min_interval_s: float = 30.0, process: str = "engine",
                 config=None, tracer=None, peers_fn=None,
                 span_bytes: int | None = None,
                 records_cap: int | None = None,
                 keep: int | None = None, readiness_fn=None,
                 poll_every: float = 0.25, now=time.monotonic):
        self.dir = incident_dir
        os.makedirs(incident_dir, exist_ok=True)
        self._reg = (obs_metrics.REGISTRY if registry is None
                     else registry)
        self.history = history
        self.min_interval = max(0.0, float(min_interval_s))
        self.process = process
        self._config = (config_fingerprint(config)
                        if config is not None else None)
        self.tracer = tracer
        self._peers_fn = peers_fn
        self._span_budget = int(span_bytes if span_bytes is not None
                                else SPAN_BYTES)
        self._rec_cap = int(records_cap if records_cap is not None
                            else RECORDS_CAP)
        self.keep = int(keep if keep is not None else INCIDENT_KEEP)
        self._readiness = (readiness_fn if readiness_fn is not None
                           else (lambda: obs_http.readiness(self._reg)))
        self._poll_every = max(0.02, float(poll_every))
        self._now = now
        self._epoch = now()   # bundle `ts` domain: seconds since the
        #                       recorder came up (raw monotonic would
        #                       read as tens of thousands of seconds)
        self._lock = threading.Lock()
        self._spans: collections.deque = collections.deque()
        self._span_bytes = 0
        self.span_bytes_hw = 0          # high-water (bench extra.flight)
        self._spans_dropped = 0
        self._records: collections.deque = collections.deque(
            maxlen=self._rec_cap)
        self._records_seen = 0
        self._pending: list = []        # (trigger, t_trig, peers)
        self._prev_reasons = None       # None until the FIRST good
        #                                 readiness poll seeds the
        #                                 baseline: flip-edge detection
        #                                 must not read boot-time state
        #                                 (a gateway's replicas are
        #                                 always unprobed for its first
        #                                 seconds) as a fresh incident
        self._last_dump = None
        self._defer_counted = False     # rate_limited counted once
        #                                 per deferral stretch, not
        #                                 once per 0.25 s re-check
        self._dump_retries = 0          # failed-dump requeue budget
        #                                 for the CURRENT batch
        self._rid = next(_RECORDER_IDS)
        self._seq = 0
        self.latest_path = None
        self._latest = None             # newest bundle, in memory (the
        #                                 /v1/incident payload — served
        #                                 without file I/O)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="tt-flight", daemon=True)

    # -- ring feeds (writer thread) -------------------------------------

    def note_record(self, rec: dict) -> None:
        """One parsed record off the stream tee: spans into the
        byte-budget ring, everything else into the record ring; a
        faultEntry latches a dump trigger (performed on the recorder
        thread, never here)."""
        span = rec.get("spanEntry")
        with self._lock:
            if span is not None:
                n = _approx_bytes(span)
                self._spans.append((span, n))
                self._span_bytes += n
                while (self._span_bytes > self._span_budget
                       and len(self._spans) > 1):
                    _, dn = self._spans.popleft()
                    self._span_bytes -= dn
                    self._spans_dropped += 1
                if self._span_bytes > self.span_bytes_hw:
                    self.span_bytes_hw = self._span_bytes
                return
            self._records_seen += 1
            self._records.append(rec)
            fault = rec.get("faultEntry")
            if fault is not None:
                self._pending.append(
                    (f"fault:{fault.get('site', '?')}/"
                     f"{fault.get('action', '?')}",
                     self._now(), ()))
        if fault is not None:
            self._reg.counter("flight.triggers").inc()
            self._wake.set()

    def trigger(self, reason: str, peers=()) -> None:
        """Request an incident dump (any thread; cheap — the recorder
        thread does the work). `peers` names replicas whose bundles a
        gateway dump should pull and stitch."""
        with self._lock:
            self._pending.append((str(reason), self._now(),
                                  tuple(peers)))
        self._reg.counter("flight.triggers").inc()
        self._wake.set()

    def tee(self, stream):
        """Wrap `stream` so its records feed the rings (writer-thread
        ingestion — see FlightTee)."""
        return FlightTee(stream, self)

    def bind_tracer(self, tracer) -> None:
        """Late-bind the span tracer the `flight_dump` spans ride
        (construction order: the recorder must exist before the writer
        it tees, the tracer only after)."""
        self.tracer = tracer

    # -- the recorder thread --------------------------------------------

    def start(self) -> "FlightRecorder":
        self._thread.start()
        atexit.register(self.close)
        return self

    def alive(self) -> bool:
        return self._thread.is_alive()

    def poll_once(self, flush: bool = False) -> bool:
        """One trigger-detection + dump tick; False when the thread
        should exit (injected death). Testable without the thread.
        `flush` bypasses the rate limit — the shutdown drain's mode,
        so a deferred incident never dies with the process."""
        if sys.is_finalizing():
            return False
        # readiness-flip detection: any reason not present last tick is
        # a fresh incident (a CLEARED reason is recovery, not an
        # incident). readiness() reads one registry snapshot — the same
        # pure-observer discipline as the /readyz handler.
        try:
            _, detail = self._readiness()
            reasons = set(detail.get("reasons", ()))
        except Exception:
            # one torn poll must NOT clear _prev_reasons: a still-on
            # reason would otherwise re-read as "freshly flipped" on
            # the next good poll and dump a duplicate incident for a
            # condition that never changed
            reasons = None
        with self._lock:
            hw = self.span_bytes_hw
        # ring occupancy high-water as a gauge (recorder thread — the
        # bench extra.flight leg reads it back after the run)
        self._reg.gauge("flight.span_ring_bytes_hw").set(hw)
        if reasons is not None:
            if self._prev_reasons is None:
                # first good poll: seed the baseline, trigger nothing
                # (module docstring — a condition already on at boot
                # is /readyz's business; the recorder watches FLIPS)
                self._prev_reasons = reasons
            else:
                new = reasons - self._prev_reasons
                self._prev_reasons = reasons
                if new:
                    with self._lock:
                        for r in sorted(new):
                            self._pending.append(
                                (f"reason:{r}", self._now(), ()))
                    self._reg.counter("flight.triggers").inc(len(new))
        else:
            reasons = self._prev_reasons or set()
        with self._lock:
            pending, self._pending = self._pending, []
        if not pending:
            return True
        peers: list = []
        for _, _, ps in pending:
            for p in ps:
                if p not in peers:
                    peers.append(p)
        now = self._now()
        if (self._last_dump is not None
                and now - self._last_dump < self.min_interval
                and not peers and not flush):
            # DEFER, never drop: the rate limit exists so a storm
            # yields ONE bundle, not ZERO — a distinct new incident
            # inside the interval (its reason already merged into
            # _prev_reasons, its faultEntry already consumed) would
            # otherwise leave no bundle at all. Re-queued triggers
            # dump as one merged bundle when the interval elapses.
            # Peer-carrying triggers (the gateway's failover/burn
            # correlation dumps) BYPASS the limit outright: losing
            # the one stitched bundle a failover asked for because a
            # reason flapped seconds earlier would defeat the
            # recorder's whole purpose.
            with self._lock:
                self._pending = pending + self._pending
            if not self._defer_counted:
                self._defer_counted = True
                self._reg.counter("flight.rate_limited").inc(
                    len(pending))
            return True
        self._defer_counted = False
        trigger, t_trig, _ = pending[0]
        if peers:
            # name the dump after the trigger that brought the peers
            trigger, t_trig, _ = next(
                p for p in pending if p[2])
        try:
            # the dump's fault site: a `hang` parks THIS thread only
            # (no bundle materializes; dispatch and settlement run on),
            # a `die` ends it — tests pin the isolation
            _faults().maybe_fail("flight_dump")
            self._dump(trigger, t_trig, peers, sorted(reasons))
            self._dump_retries = 0
        except SystemExit:
            return False
        except Exception as e:
            self._reg.counter("flight.dump_errors").inc()
            print(f"warning: flight recorder dump failed: "
                  f"{str(e)[:160]}", file=sys.stderr)
            if self._dump_retries < 3:
                # defer-never-drop applies to FAILED dumps too: a
                # transiently unwritable --incident-dir (ENOSPC for a
                # second mid-failover) must not eat the incident —
                # re-queue the batch and retry next tick, bounded so a
                # permanently dead disk degrades to the warning above
                self._dump_retries += 1
                with self._lock:
                    self._pending = pending + self._pending
            else:
                self._dump_retries = 0
        return True

    def _loop(self) -> None:
        while True:
            if not self.poll_once():
                return
            if self._stop.is_set():
                # close() raced the poll above: a trigger enqueued
                # DURING it (the drained writer's last faultEntry —
                # an abort's, say) is still pending; one final FLUSH
                # tick (still on THIS thread, so the flight_dump
                # isolation contract holds; flush bypasses the rate
                # limit so a deferred incident is not dropped either)
                # gets it its bundle instead of dying with the queue
                self.poll_once(flush=True)
                return
            self._wake.wait(self._poll_every)
            self._wake.clear()
            if self._stop.is_set():
                self.poll_once(flush=True)   # same final drain
                return

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread.ident is not None:   # never-started: no join
            self._thread.join(timeout=2.0)   # hung dumper: abandoned
            #                                  daemon, never waited out
        atexit.unregister(self.close)

    # -- bundle assembly (recorder thread only) -------------------------

    def _core(self, trigger: str, t_trig: float, reasons: list) -> dict:
        with self._lock:
            spans = [dict(s) for s, _ in self._spans]
            records = [dict(r) for r in self._records]
            spans_dropped = self._spans_dropped
            rec_dropped = max(0, self._records_seen
                              - len(self._records))
        hist = None
        mem = {}
        if self.history is not None:
            hist = self.history.window(BUNDLE_HISTORY_S)
            mem = {n: s for n, s in hist.get("series", {}).items()
                   if n.startswith("device.mem_")}
        core = {"version": BUNDLE_VERSION, "process": self.process,
                "pid": os.getpid(), "trigger": trigger,
                "reasons": reasons,
                "ts": round(t_trig - self._epoch, 6),
                "unix_time": round(time.time(), 3),
                "config": self._config,
                "metrics": self._reg.snapshot(),
                "history": hist, "mem": mem,
                "spans": spans, "records": records,
                "spans_dropped": spans_dropped,
                "records_dropped": rec_dropped}
        return core

    def _dump(self, trigger: str, t_trig: float, peers: list,
              reasons: list) -> None:
        core = self._core(trigger, t_trig, reasons)
        if peers and self._peers_fn is not None:
            fetched = []
            for label, bundle, err in self._peers_fn(peers):
                fetched.append({"label": label, "incident": bundle,
                                "error": err})
            core["stitched"] = True
            core["peers"] = fetched
            # ONE cross-process timeline, by the same stitching rules
            # as `tt trace` (pid per process lane, XFLOW ids kept
            # verbatim, local flows remapped per input): the bundle is
            # directly Perfetto-loadable via `tt incident`
            core["trace"] = trace_export.export_stitched(
                bundle_records(core))
        self._seq += 1
        slug = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in trigger)[:48]
        # pid + per-process recorder ordinal + seq: unique across
        # processes AND across several recorders sharing one directory
        # within a process (in-proc fleets)
        path = os.path.join(
            self.dir, f"incident-{os.getpid()}.{self._rid}-"
                      f"{self._seq:04d}-{slug}.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"incident": core}, fh)
        os.replace(tmp, path)
        self._retain()
        self._last_dump = self._now()
        with self._lock:
            self._latest = core
            self.latest_path = path
        self._reg.counter("flight.dumps").inc()
        # time-to-dump: trigger instant -> bundle on disk (what the
        # "how fast is the black box" question actually asks)
        self._reg.histogram("flight.dump_seconds").observe(
            max(0.0, self._now() - t_trig),
            exemplar={"trigger": trigger})
        tracer = self.tracer
        if tracer is not None and getattr(tracer, "enabled", False):
            try:
                # time-to-dump: trigger instant -> bundle on disk (the
                # `tt stats` "== incidents" latency source)
                tracer.record("flight_dump", t_trig,
                              self._now() - t_trig, cat="flight",
                              trigger=trigger,
                              path=os.path.basename(path))
            except Exception:
                pass   # a dying writer must not fail the dump

    def _retain(self) -> None:
        """Oldest-first retention: at most `keep` bundles in the
        directory (by mtime — robust across process restarts)."""
        try:
            paths = sorted(_bundle_paths(self.dir),
                           key=lambda p: (os.path.getmtime(p), p))
            for p in paths[:max(0, len(paths) - self.keep)]:
                os.unlink(p)
        except OSError:
            pass

    def latest(self) -> dict | None:
        """The newest bundle, in memory — the replica/gateway
        `GET /v1/incident` payload (read-only: no file I/O on the
        handler thread — TT602/TT606)."""
        with self._lock:
            return self._latest


def wire(cfg, out, registry=None, process: str = "engine",
         peers_fn=None, now=time.monotonic,
         history_always: bool = False):
    """The one tt-flight wiring every process shares — engine.run,
    SolveService.__init__ and Gateway all call this instead of keeping
    three drifting copies: build the history ring (under the shared
    enable gate — any obs surface, or always for a gateway), the
    recorder, and the teed record sink. Returns (history, flight,
    sink); the caller still owns `bind_tracer(...)` + `start()` (the
    tracer exists only after the writer the sink feeds) and the
    teardown ordering. If the recorder's construction fails, the
    just-started sampler is closed before the error propagates — no
    half-wired thread leaks."""
    history = None
    if cfg.history_every > 0 and (
            history_always or getattr(cfg, "obs", False)
            or getattr(cfg, "obs_listen", None) or cfg.incident_dir):
        from timetabling_ga_tpu.obs import history as obs_history
        history = obs_history.HistoryRing(
            registry=registry, every_s=cfg.history_every,
            now=now).start()
    flight = None
    sink = out
    if cfg.incident_dir:
        try:
            flight = FlightRecorder(
                cfg.incident_dir, registry=registry, history=history,
                min_interval_s=cfg.incident_min_interval,
                process=process, config=cfg, peers_fn=peers_fn,
                now=now)
        except BaseException:
            if history is not None:
                history.close()
            raise
        if sink is not None:
            sink = flight.tee(sink)
    return history, flight, sink


def incident_response(flight) -> tuple:
    """THE `GET /v1/incident` (status, body) — shared by the replica
    and gateway Api surfaces (fleet/replicas.py, fleet/gateway.py) so
    the wire shape cannot drift between them. Read-only over the
    recorder's in-memory `latest()`; no file I/O on the handler
    thread (TT602/TT606)."""
    if flight is None:
        return 404, {"error": "no flight recorder wired "
                              "(--incident-dir)"}
    core = flight.latest()
    if core is None:
        return 404, {"error": "no incident recorded yet"}
    return 200, {"incident": core}


# -------------------------------------------------- bundle -> records


def bundle_records(core: dict) -> list:
    """An incident bundle's processes as `tt trace` inputs:
    [(label, records), ...] where records are ordinary JSONL record
    dicts (spanEntry bodies re-wrapped + the record ring verbatim).
    A stitched bundle contributes one input per process — the same
    pid-lane layout `export_stitched` gives a fleet's log files."""
    def recs(c: dict) -> list:
        return ([{"spanEntry": dict(s)} for s in c.get("spans", ())]
                + [dict(r) for r in c.get("records", ())])

    inputs = [(str(core.get("process", "?")), recs(core))]
    for p in core.get("peers", ()) or ():
        inc = p.get("incident")
        if inc:
            inputs.append((str(p.get("label", "?")), recs(inc)))
    return inputs


def load_bundle(path: str) -> dict:
    """Read one bundle file; returns the inner `incident` object.
    Raises ValueError on anything that is not a bundle."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    core = doc.get("incident") if isinstance(doc, dict) else None
    if not isinstance(core, dict):
        raise ValueError(f"{path}: not an incident bundle "
                         f"(no 'incident' object)")
    return core


def _bundle_paths(dir_path: str) -> list:
    """incident-*.json files EXCLUDING `tt incident`'s own rendered
    `*.trace.json` artifacts — those would otherwise be re-picked as
    'the newest bundle' (and counted against retention) once a render
    lands in the incident directory."""
    return [os.path.join(dir_path, n) for n in os.listdir(dir_path)
            if n.startswith("incident-") and n.endswith(".json")
            and not n.endswith(".trace.json")]


def list_bundles(dir_path: str) -> list:
    """Bundle paths in a directory, oldest first (mtime order — the
    retention order)."""
    return sorted(_bundle_paths(dir_path),
                  key=lambda p: (os.path.getmtime(p), p))


def summarize_bundle(core: dict, path: str | None = None) -> str:
    """One human block per bundle — what `tt incident` prints."""
    lines = []
    head = f"== incident: {core.get('trigger', '?')}"
    if path:
        head += f"  ({os.path.basename(path)})"
    lines.append(head)
    lines.append(f"  process {core.get('process', '?')} "
                 f"pid {core.get('pid', '?')} "
                 f"v{core.get('version', '?')} "
                 f"ts {core.get('ts', 0.0):.1f}s")
    if core.get("reasons"):
        lines.append(f"  readiness reasons: "
                     f"{', '.join(core['reasons'])}")
    cfg = core.get("config") or {}
    if cfg:
        lines.append(f"  config {cfg.get('kind', '?')} "
                     f"md5 {cfg.get('md5', '?')}")
    lines.append(
        f"  rings: {len(core.get('spans', ()))} spans "
        f"(+{core.get('spans_dropped', 0)} dropped), "
        f"{len(core.get('records', ()))} records "
        f"(+{core.get('records_dropped', 0)} dropped)")
    hist = core.get("history") or {}
    if hist:
        lines.append(f"  history: {len(hist.get('series', {}))} series"
                     f" @ {hist.get('every_s', '?')}s cadence")
    mets = core.get("metrics") or {}
    counters = mets.get("counters") or {}
    for name in ("engine.recoveries", "serve.jobs_failed",
                 "fleet.jobs_failed_over", "faults.injected"):
        if counters.get(name):
            lines.append(f"  {name}: {counters[name]}")
    peers = core.get("peers") or ()
    if peers:
        got = sum(1 for p in peers if p.get("incident"))
        lines.append(f"  stitched: {got}/{len(peers)} peer bundle(s) "
                     + ", ".join(str(p.get("label")) for p in peers))
    faults = [r["faultEntry"] for r in core.get("records", ())
              if "faultEntry" in r]
    if faults:
        last = faults[-1]
        lines.append(f"  last fault: {last.get('site')}/"
                     f"{last.get('action')} "
                     f"{str(last.get('error', ''))[:80]}")
    return "\n".join(lines)


# ------------------------------------------------------- tt incident


def main_incident(argv) -> int:
    """`tt incident <dir-or-bundle.json> [--job ID] [-o trace.json]
    [--list]` — summarize incident bundles and render one (the newest,
    or the named file) as Perfetto-loadable Chrome trace JSON via the
    same stitching rules as `tt trace`. Stdlib-only and jax-free."""
    target, out, job, list_only = None, None, None, False
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            print("usage: tt incident <dir-or-bundle.json> [--job ID] "
                  "[-o trace.json] [--list]\n\n"
                  "summarize the flight recorder's incident bundles "
                  "(--incident-dir) and export the newest (or the\n"
                  "named bundle) as Chrome trace-event JSON — a "
                  "stitched gateway bundle renders the cross-process\n"
                  "timeline (gateway + replica lanes, XFLOW arrows); "
                  "--job ID filters to one job's chain; --list only\n"
                  "lists the directory's bundles")
            return 0
        if a == "--list":
            list_only = True
            i += 1
            continue
        if a in ("-o", "--job"):
            if i + 1 >= len(argv):
                raise SystemExit(f"flag {a} needs a value")
            if a == "-o":
                out = argv[i + 1]
            else:
                job = argv[i + 1]
            i += 2
            continue
        if a.startswith("-"):
            raise SystemExit(f"unknown argument: {a}")
        if target is not None:
            raise SystemExit("tt incident takes one directory or "
                             "bundle file")
        target = a
        i += 1
    if target is None:
        raise SystemExit("usage: tt incident <dir-or-bundle.json> "
                         "[--job ID] [-o trace.json] [--list]")
    if os.path.isdir(target):
        paths = list_bundles(target)
        if not paths:
            raise SystemExit(f"no incident bundles in {target} "
                             f"(incident-*.json)")
        if list_only:
            for p in paths:
                try:
                    core = load_bundle(p)
                except ValueError as e:
                    print(f"  {os.path.basename(p)}: {e}")
                    continue
                print(f"  {os.path.basename(p)}: "
                      f"{core.get('trigger', '?')} "
                      f"({len(core.get('spans', ()))} spans, "
                      f"{len(core.get('records', ()))} records"
                      + (", stitched" if core.get("stitched") else "")
                      + ")")
            return 0
        path = paths[-1]              # newest
    else:
        path = target
    core = load_bundle(path)
    print(summarize_bundle(core, path))
    doc = trace_export.export_stitched(bundle_records(core), job=job)
    if out is None:
        out = path + ".trace.json"
    with open(out, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    n = len(doc["traceEvents"])
    tag = f" (job {job})" if job is not None else ""
    print(f"tt incident: {n} trace event{'s' if n != 1 else ''}{tag} "
          f"-> {out}", file=sys.stderr)
    return 0
