"""tt-obs pull front: an opt-in localhost HTTP listener.

Before this module the only way to get metrics OUT of a run was the
push path — metricsEntry JSONL records a sidecar had to tail and relay.
`--obs-listen HOST:PORT` (RunConfig and ServeConfig) starts a stdlib
`http.server` on a daemon thread serving three endpoints, so Prometheus
scrapes and k8s-style probes need no sidecar at all:

  /metrics   OpenMetrics 1.0 text from the process MetricsRegistry
             (obs/metrics.py), WITH histogram exemplars: the latest
             `serve.job_seconds` / `engine.dispatch_seconds`
             observation per bucket carries its `job=` / `dispatch=`
             label, so a latency spike on the dashboard joins straight
             back to that job's jobEntry lifecycle on the record stream
  /healthz   process + writer-thread liveness (the `probes` dict the
             owner registers; 503 when any probe fails)
  /readyz    readiness derived from REGISTRY state alone: queue depth
             vs the admission bound, the fault supervisor's degradation
             ladder level (which steps back UP after a clean stretch,
             so the reason clears live), the remaining recovery budget,
             and the memory poller's near-HBM fraction — 503 flips
             exactly when the stack is shedding, degraded, or about to
             OOM
  /metrics/history   the tt-flight history ring (obs/history.py) as
             JSON: per-series (t, value) samples, `?window=S` bounded
             — the windowed substrate the autoscaler primitives
             (`rate`/`mean_over`/`sustained`) and the incident
             bundles read; absent ring answers 404. The handler only
             READS the ring's lock-guarded deques (TT602-pure)
  /profile   on-demand profiler trigger (?for=N): flips the cost
             observatory's ProfileCapture state and wakes ITS worker
             thread — no blocking I/O, no registry touch (TT602-pure);
             `tt profile URL --for N` is the stdlib client. ?last=1
             reads the newest completed capture's tt-prof phase
             attribution (obs/prof.py; produced on the capture
             worker) — the poll `tt profile --attribute` rides

Design rules (enforced by tt-analyze TT602):

  - handlers only READ registry snapshots/expositions — no counter
    bumps, no gauge writes, no get-or-create touches. A scraper must be
    a pure observer: a scrape that mutates the registry changes the
    numbers every OTHER consumer (metricsEntry, `tt serve` stats)
    reads, and a scrape storm would contend the registry lock the
    dispatch path holds.
  - handlers do no blocking I/O beyond their own response socket. The
    listener must never be able to stall the run it observes: it
    shares nothing with the dispatch loop but the registry lock, held
    only for the snapshot copy.

The server is `ThreadingHTTPServer` with daemon threads and
`block_on_close=False`: one hung handler (the `scrape` fault site's
`hang` action — runtime/faults.py) parks its own thread and nothing
else; close() returns without joining it. The JSONL record stream is
byte-identical with the listener on or off — this module writes no
records (tests/test_obs.py and bench.py `extra.scrape` pin it).

Stdlib-only, like the rest of obs/: importable without JAX.
"""

from __future__ import annotations

import http.server
import json
import threading

from timetabling_ga_tpu.obs import cost as obs_cost
from timetabling_ga_tpu.obs import metrics as obs_metrics
from timetabling_ga_tpu.runtime import faults

OPENMETRICS_CT = "application/openmetrics-text; version=1.0.0; charset=utf-8"


def parse_listen(spec: str) -> tuple[str, int]:
    """'HOST:PORT' -> (host, port); port 0 binds an ephemeral port
    (tests/bench). Raises ValueError on anything else."""
    host, sep, port_s = str(spec).rpartition(":")
    if not sep or not host:
        raise ValueError(
            f"listen spec wants HOST:PORT, got {spec!r}")
    try:
        port = int(port_s)
    except ValueError:
        raise ValueError(
            f"listen port must be an integer, got {port_s!r}"
        ) from None
    if not 0 <= port <= 65535:
        raise ValueError(f"listen port out of range: {port}")
    return host, port


def readiness(registry) -> tuple[bool, dict]:
    """Readiness decision from registry state ALONE (read-only: one
    snapshot). Not ready when any of:

      - `serve.queue_depth` >= `serve.backlog` (admission would reject
        — new work should be routed to another replica);
      - `engine.degrade_level` >= 2 (the fault supervisor's ladder is
        past 'serial': the process is shrinking dispatches to survive;
        the ladder also steps back UP after a clean stretch —
        engine._Supervisor.maybe_relax — so this reason CLEARS live,
        it is not a one-way trip);
      - `engine.recovery_budget_remaining` <= 0 while recovery was
        configured (the next transient failure aborts the run);
      - `device.mem_frac_used` >= obs/cost.py NEAR_HBM_FRAC (the cost
        observatory's memory poller says the next placement is an OOM
        gamble — route new work elsewhere until the pressure clears);
      - `engine.stalled` >= 1 (the search-quality observatory's stall
        detector: the run has plateaued with a collapsed population —
        obs/quality.py StallDetector; the gauge clears when a new best
        lands or the auto-kick fires, so the reason is live, not a
        one-way trip);
      - `serve.draining` >= 1 (a fleet drain is in flight — the
        replica finishes its parked jobs but admits nothing new, so
        the router must stop sending work; fleet/replicas.py sets the
        gauge from the drive loop when a `/v1/drain` lands);
      - gateway-only (fleet/gateway.py, tt-obs v5): `no_ready_replica`
        (zero ready replicas behind the front), `dispatcher_stalled`
        (the dispatcher's tick age exceeded `--stall-after` — it
        accepts jobs it will never place) and `slo_burn` (the
        `--slo-p99` rolling-window latency monitor is over its bound)
        — the gateway answers the SAME pinned contract as replicas,
        so HA stacks and meta-gateways route around it identically.

    Absent gauges (an engine run has no serve queue; a serve process
    may never have set the ladder; no memory poller on CPU) are simply
    not conditions.

    The body is structured JSON (content-type application/json):
    `{"ready": bool, "reasons": [...], ...}` with one context key per
    condition — the fleet router (fleet/router.py) PARSES the reasons
    (`near_hbm_limit`, `stalled`, `draining`, ...) rather than
    scraping text, so the reason strings here are a wire contract
    (tests/test_fleet.py pins body shape and content type)."""
    gauges = registry.snapshot().get("gauges", {})
    reasons = []
    depth = gauges.get("serve.queue_depth")
    bound = gauges.get("serve.backlog")
    if depth is not None and bound is not None and bound > 0 \
            and depth >= bound:
        reasons.append("backlog_full")
    level = gauges.get("engine.degrade_level")
    if level is not None and level >= 2:
        reasons.append("degraded")
    budget = gauges.get("engine.recovery_budget_remaining")
    if budget is not None and budget <= 0 and gauges.get(
            "engine.recovery_budget_configured", 0) > 0:
        reasons.append("recovery_exhausted")
    mem_frac = gauges.get("device.mem_frac_used")
    if mem_frac is not None and mem_frac >= obs_cost.NEAR_HBM_FRAC:
        reasons.append("near_hbm_limit")
    stalled = gauges.get("engine.stalled")
    if stalled is not None and stalled >= 1:
        reasons.append("stalled")
    draining = gauges.get("serve.draining")
    if draining is not None and draining >= 1:
        reasons.append("draining")
    # gateway-only gauge (fleet/gateway.py binds it to the replica
    # set): a fleet front with zero ready replicas can accept work but
    # not place it — upstream load balancers should know
    fleet_ready = gauges.get("fleet.replicas_ready")
    if fleet_ready is not None and fleet_ready < 1:
        reasons.append("no_ready_replica")
    # gateway dispatcher watchdog (fleet/gateway.py, tt-obs v5):
    # `fleet.tick_age_s` is a pull gauge over the dispatcher's last
    # loop tick, `fleet.tick_stall_after` the configured threshold
    # (--stall-after; 0/absent disables). A dead or wedged dispatcher
    # still ACCEPTS jobs it will never place — an HA stack must see
    # that on the same /readyz contract replicas answer.
    tick_age = gauges.get("fleet.tick_age_s")
    stall_after = gauges.get("fleet.tick_stall_after")
    if (tick_age is not None and stall_after is not None
            and stall_after > 0 and tick_age >= stall_after):
        reasons.append("dispatcher_stalled")
    # gateway SLO monitor (--slo-p99): the rolling-window p99 over
    # e2e job latencies is over its bound — stop sending latency-
    # sensitive traffic here until the burn clears (the gauge flips
    # back when the window's p99 recovers, so the reason is live)
    slo_burn = gauges.get("fleet.slo_burn")
    if slo_burn is not None and slo_burn >= 1:
        reasons.append("slo_burn")
    return not reasons, {"ready": not reasons, "reasons": reasons,
                         "queue_depth": depth, "backlog": bound,
                         "degrade_level": level,
                         "recovery_budget_remaining": budget,
                         "mem_frac_used": mem_frac,
                         "stalled": stalled,
                         "draining": draining}


class _Handler(http.server.BaseHTTPRequestHandler):
    """GET router for the three endpoints. READ-ONLY over the registry
    (TT602): snapshots and expositions, never instrument touches."""

    # the default HTTPServer protocol closes per request; 1.1 lets a
    # scraper keep its connection
    protocol_version = "HTTP/1.1"

    def do_GET(self):  # noqa: N802 (http.server's naming)
        # fault-injection point (runtime/faults.py `scrape` site): a
        # `hang` parks THIS daemon handler thread only; `die`/`error`
        # abort this request — the serve/dispatch/writer paths never
        # block on any of it (tests pin that)
        try:
            faults.maybe_fail("scrape")
        except SystemExit:
            # `die`: this handler ends with no response — the client
            # sees a dropped connection, nothing else notices. Absorbed
            # here because a SystemExit escaping the handler thread
            # trips process-wide thread-excepthook machinery, which is
            # exactly the cross-thread coupling the listener must not
            # have.
            self.close_connection = True
            return
        path, _, query = self.path.partition("?")
        if path == "/metrics":
            body = self.server.registry.to_openmetrics().encode()
            self._reply(200, body, OPENMETRICS_CT)
        elif path == "/metrics/history":
            # tt-flight (obs/history.py): the bounded per-series
            # sample rings as JSON — `?window=S` restricts to the last
            # S seconds. TT602-pure by construction: window() reads
            # the ring under ITS lock and never touches the registry
            # (the sampler thread owns the registry reads).
            ring = getattr(self.server, "history", None)
            if ring is None:
                self._reply_json(404, {"ok": False,
                                       "reason": "no history ring "
                                                 "wired "
                                                 "(--history-every)"})
                return
            params = dict(
                p.split("=", 1) for p in query.split("&") if "=" in p)
            window = None
            if "window" in params:
                try:
                    window = float(params["window"])
                except ValueError:
                    self._reply_json(400, {"ok": False,
                                           "reason": "window must be "
                                                     "seconds"})
                    return
            out = ring.window(window)
            if window is not None:
                out["window"] = window
            self._reply_json(200, out)
        elif path == "/profile":
            # the cost observatory's on-demand capture trigger
            # (obs/cost.py ProfileCapture; `tt profile` is the client).
            # TT602-pure by design: trigger() flips state and wakes the
            # capture WORKER thread — this handler does no blocking I/O
            # and touches no registry instrument; the jax.profiler
            # calls happen on the worker, never here.
            capture = getattr(self.server, "profile", None)
            if capture is None:
                self._reply_json(404, {"ok": False,
                                       "reason": "no profile capture "
                                                 "wired (--profile-dir"
                                                 "/--profile-for)"})
                return
            params = dict(
                p.split("=", 1) for p in query.split("&") if "=" in p)
            if params.get("last"):
                # tt-prof poll: the newest completed capture's
                # attribution (obs/prof.capture_hook ran on the
                # capture worker). A pure READ of worker-produced
                # state — no trigger, no registry touch (TT602).
                last = capture.last()
                self._reply_json(200, {"ok": True, **last})
                return
            try:
                n = int(params.get("for", 1))
            except ValueError:
                self._reply_json(400, {"ok": False,
                                       "reason": "for must be an int"})
                return
            ack = capture.trigger(n)
            self._reply_json(200 if ack.get("ok") else 409, ack)
        elif path == "/healthz":
            probes = {}
            for name, fn in self.server.probes.items():
                try:
                    probes[name] = bool(fn())
                except Exception:
                    probes[name] = False
            ok = all(probes.values())
            self._reply_json(200 if ok else 503,
                             {"ok": ok, "probes": probes})
        elif path == "/readyz":
            ok, detail = readiness(self.server.registry)
            self._reply_json(200 if ok else 503, detail)
        else:
            self._reply_json(404, {"error": f"no route {path!r}"})

    def _reply(self, status: int, body: bytes, ctype: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, status: int, obj: dict) -> None:
        self._reply(status, json.dumps(obj).encode(),
                    "application/json")

    def log_message(self, fmt, *args):
        """Silence the default stderr access log: the run's stderr
        carries solver warnings, not scrape noise."""


class _Server(http.server.ThreadingHTTPServer):
    daemon_threads = True      # a hung handler must not survive exit
    block_on_close = False     # ...nor block close() until it returns
    allow_reuse_address = True

    def handle_error(self, request, client_address):
        """Silence per-request tracebacks (the default prints to
        stderr): a failed scrape — including the `scrape` fault site's
        die/error actions — aborts its own request and nothing else;
        the run's stderr carries solver warnings, not scrape noise."""


class ObsServer:
    """The listener lifecycle: bind at construction (so the ephemeral
    port is known immediately), serve from a daemon thread after
    `start()`, stop on `close()`.

    `probes` maps name -> zero-arg callable for /healthz (the owner
    registers e.g. its AsyncWriter's worker liveness). `profile` is an
    optional obs/cost.py ProfileCapture the /profile endpoint triggers
    (absent: 404). The registry defaults to THE process REGISTRY — the
    same numbers every other consumer sees.

    The fleet fronts (fleet/gateway.py) reuse this lifecycle with
    their own handler class: `handler` swaps the request router (a
    `_Handler` subclass adding the `/v1` solve API), `api` is the
    enqueue-or-read-only object those handlers talk to, and `site`
    names the accept loop's fault-injection point (`obs_listen` here,
    `gateway` for the fleet gateway — runtime/faults.py)."""

    def __init__(self, listen: str, registry=None, probes=None,
                 profile=None, handler=None, api=None,
                 site: str = "obs_listen", history=None):
        host, port = parse_listen(listen)
        self._srv = _Server((host, port), handler or _Handler)
        self._srv.registry = (obs_metrics.REGISTRY if registry is None
                              else registry)
        self._srv.probes = dict(probes or {})
        self._srv.profile = profile
        self._srv.api = api
        # tt-flight: the obs/history.py ring /metrics/history serves
        # (absent: 404) — handlers only READ it, like the registry
        self._srv.history = history
        self._site = site
        self._thread = threading.Thread(
            target=self._serve, name=f"tt-{site}", daemon=True)
        self._state_lock = threading.Lock()
        self._serving = False
        self._closed = False

    @property
    def address(self) -> tuple[str, int]:
        """(host, port) actually bound — port is resolved for ':0'."""
        return self._srv.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def _serve(self) -> None:
        # fault-injection point (`obs_listen` site — `gateway` when the
        # fleet front owns this server): a `die` here kills ONLY the
        # accept loop — the process, and every solve path, runs on
        # untouched
        try:
            faults.maybe_fail(self._site)
        except SystemExit:
            self._srv.server_close()
            return
        # handshake with close() under the state lock: close() may only
        # call shutdown() once serve_forever is (about to be) running —
        # shutdown() waits on an event ONLY serve_forever sets, so a
        # never-started accept loop (hang/die injected above) would
        # deadlock it. And if close() already won the race and closed
        # the socket, entering serve_forever here would die with a
        # ValueError on the dead descriptor — exactly the cross-thread
        # stderr noise this module promises not to make.
        with self._state_lock:
            if self._closed:
                return
            self._serving = True
        self._srv.serve_forever(poll_interval=0.1)

    def start(self) -> "ObsServer":
        self._thread.start()
        return self

    def alive(self) -> bool:
        return self._thread.is_alive()

    def close(self) -> None:
        with self._state_lock:
            if self._closed:
                return
            self._closed = True
            serving = self._serving
        if serving:
            try:
                self._srv.shutdown()
            except Exception:
                pass
        self._srv.server_close()
        if self._thread.ident is not None:   # never-started: no join
            self._thread.join(timeout=2.0)
