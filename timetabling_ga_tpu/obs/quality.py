"""tt-obs search-quality observatory — the host side.

The machine observability stack (spans, roofline, compile-hit rate,
HBM pressure) says nothing live about the SEARCH: whether populations
have collapsed, which operators actually produce improvements, or
whether migration earns its ppermute. The wafer-scale island-GA paper
(PAPERS.md) makes the case that island GAs at scale are only debuggable
when quality signals are collected ON DEVICE alongside the run; this
module owns everything about those signals that does not need jax:

  LAYOUT      the packed quality block the island/lane runners append
              to the compressed telemetry leaf (parallel/islands.py
              packs it; QUALITY_WIDTH int32 columns per island) —
              operator efficacy counters, migration gain, and bitcast
              float32 diversity moments + a bounded coprime-stride
              Hamming-distance sample over slot assignments
  DECODE      `decode_rows` / `aggregate` / `lane_payload`: numpy-only
              host decode into the `quality.*` metrics namespace and
              the `qualityEntry` JSONL payloads (emitted under --obs)
  STALLS      `StallDetector`: no-improvement window x diversity-
              collapse threshold -> the `engine.stalled` gauge, a
              /readyz-visible `stalled` condition, and the opt-in
              `--auto-kick-on-stall` trigger for the existing kick path
  CLI         `tt quality <log.jsonl>` — stdlib, jax-free summary of a
              run's qualityEntry stream (diversity trend, operator hit
              rates, migration gain, stall/kick events)

Record-stream discipline (the established tt-obs contract): the quality
observatory changes WHAT telemetry ships, never what the solver does —
engine and serve record streams are bit-identical with it on or off
(modulo qualityEntry/timing records; tests/test_quality.py pins the
A/B), and every reduction runs on device so the dispatch loop never
recomputes quality on host (tt-analyze TT604 lints that).

numpy is imported lazily inside the decode helpers so the CLI summarizer
stays importable on a log-analysis box with no scientific stack.
"""

from __future__ import annotations

import os

# ---------------------------------------------------------------------------
# Packed-leaf layout. One quality block per island/lane, appended after
# the compressed trace leaf's event/count[/moment] columns — all int32,
# so the fetch stays ONE leaf (islands._compress_trace + the runners
# own the device-side packing; islands.split_quality splits it back).

# operator-efficacy counters (int32 counts, summed over the dispatch):
#   crossover attempts / wins, mutation attempts / wins — a WIN is a
#   child that strictly improved on its base parent's penalty, credited
#   to every operator that touched it (ops/ga.py generation)
N_GA = 4
# sweep-move acceptance counters: Move1 / Move2 / Move3 accepted moves
# across every sweep pass the dispatch ran (ops/sweep.py sweep_pass)
N_SWEEP = 3
N_OPS = N_GA + N_SWEEP
# migration gain: per-island improvement of the reported best across
# the dispatch's ring exchanges (reported-int domain, summed; 0 on the
# serve lane path — lanes never migrate)
N_MIG = 1
# diversity block (bitcast float32): penalty mean/var/min/max,
# scv mean/var/min/max, Hamming sample mean (fraction of differing live
# slot assignments over HAMMING_PAIRS coprime-stride pairs)
N_DIV = 9
QUALITY_WIDTH = N_OPS + N_MIG + N_DIV

# column offsets inside the quality block
OFF_GA = 0
OFF_SWEEP = N_GA
OFF_MIG = N_OPS
OFF_DIV = N_OPS + N_MIG

# bounded Hamming sample: at most this many coprime-stride pairs per
# island per dispatch (parallel/islands.py _div_stats)
HAMMING_PAIRS = int(os.environ.get("TT_QUALITY_HAMMING_PAIRS", "32"))

_OP_NAMES = ("crossover_attempts", "crossover_wins",
             "mutation_attempts", "mutation_wins",
             "move1_accepts", "move2_accepts", "move3_accepts")
_DIV_NAMES = ("penalty_mean", "penalty_var", "penalty_min", "penalty_max",
              "scv_mean", "scv_var", "scv_min", "scv_max", "hamming")


def decode_rows(rows):
    """(n_islands, QUALITY_WIDTH) int32 quality block -> dict of
    per-island numpy arrays (op counts + migration gain as int64,
    diversity columns as float32 via bitcast)."""
    import numpy as np
    rows = np.asarray(rows, np.int32)
    if rows.ndim != 2 or rows.shape[1] != QUALITY_WIDTH:
        raise ValueError(f"quality block must be (n, {QUALITY_WIDTH}) "
                         f"int32, got {rows.shape}")
    out = {name: rows[:, OFF_GA + i].astype(np.int64)
           for i, name in enumerate(_OP_NAMES)}
    out["migration_gain"] = rows[:, OFF_MIG].astype(np.int64)
    div = np.ascontiguousarray(rows[:, OFF_DIV:]).view(np.float32)
    for i, name in enumerate(_DIV_NAMES):
        out[name] = div[:, i]
    return out


def aggregate(decoded) -> dict:
    """Cross-island aggregation of one dispatch's decoded quality block
    into the `quality.*` namespace: {"counters": {...}, "gauges":
    {...}}. Counters are per-dispatch DELTAS (the registry accumulates
    them); gauges are the dispatch's latest cross-island view —
    `hamming_min` is the most-collapsed island, the stall detector's
    input."""
    counters = {
        "quality.ops.crossover_attempts":
            int(decoded["crossover_attempts"].sum()),
        "quality.ops.crossover_wins": int(decoded["crossover_wins"].sum()),
        "quality.ops.mutation_attempts":
            int(decoded["mutation_attempts"].sum()),
        "quality.ops.mutation_wins": int(decoded["mutation_wins"].sum()),
        "quality.ops.move1_accepts": int(decoded["move1_accepts"].sum()),
        "quality.ops.move2_accepts": int(decoded["move2_accepts"].sum()),
        "quality.ops.move3_accepts": int(decoded["move3_accepts"].sum()),
        "quality.migration.gain": int(decoded["migration_gain"].sum()),
    }
    gauges = {
        "quality.diversity.penalty_mean":
            float(decoded["penalty_mean"].mean()),
        "quality.diversity.penalty_var":
            float(decoded["penalty_var"].mean()),
        "quality.diversity.scv_mean": float(decoded["scv_mean"].mean()),
        "quality.diversity.scv_var": float(decoded["scv_var"].mean()),
        "quality.diversity.hamming": float(decoded["hamming"].mean()),
        "quality.diversity.hamming_min": float(decoded["hamming"].min()),
    }
    return {"counters": counters, "gauges": gauges}


def lane_payload(decoded, lane: int) -> dict:
    """One lane's (serve job's) flat qualityEntry payload."""
    out = {}
    for name in _OP_NAMES:
        out[name] = int(decoded[name][lane])
    for name in _DIV_NAMES:
        out[name] = round(float(decoded[name][lane]), 6)
    return out


def entry_payload(agg: dict, **extra) -> dict:
    """Flat qualityEntry payload from an `aggregate` result (dots in
    the metric names are kept — `tt trace` renders each key as its own
    Perfetto counter track)."""
    out = {}
    for kind in ("counters", "gauges"):
        for name, v in agg[kind].items():
            out[name] = round(float(v), 6) if kind == "gauges" else int(v)
    out.update(extra)
    return out


def entry_total(entries, key: str) -> int:
    """Run total of one counter field across qualityEntry payloads —
    the entries carry per-dispatch DELTAS (see `aggregate`), so every
    consumer (bench extra.quality, the race rows, `tt quality`) must
    sum, never read the last entry. Owned here with the key names so
    the summers cannot drift."""
    return sum(int(e.get(key, 0)) for e in entries)


def entry_win_rate(entries, wins_key: str, attempts_key: str):
    """wins/attempts across qualityEntry payloads; None when the
    operator never ran (distinct from a true 0% hit rate)."""
    attempts = entry_total(entries, attempts_key)
    if not attempts:
        return None
    return round(entry_total(entries, wins_key) / attempts, 3)


class StallDetector:
    """No-improvement window x diversity-collapse threshold.

    `update(best, hamming)` is fed once per retired dispatch with the
    run's control best (min over islands of best_seen) and the
    most-collapsed island's Hamming diversity. The run is STALLED when
    `window` consecutive dispatches brought no new best AND diversity
    sits at/below `hamming_floor` — a plateau with a collapsed
    population is one more dispatches cannot fix, where a plateau with
    diversity left may still recombine its way off. window <= 0
    disables the detector entirely."""

    def __init__(self, window: int, hamming_floor: float):
        self.window = int(window)
        self.hamming_floor = float(hamming_floor)
        self.streak = 0
        self.stalled = False
        self._best = None

    def update(self, best: int, hamming: float) -> bool:
        if self.window <= 0:
            return False
        if self._best is None or best < self._best:
            self._best = best
            self.streak = 0
        else:
            self.streak += 1
        self.stalled = (self.streak >= self.window
                        and hamming <= self.hamming_floor)
        return self.stalled

    def reset(self) -> None:
        """Re-arm after an intervention (the auto-kick): the kick
        re-diversified the population, so the stall evidence is
        stale — a new window must accumulate before firing again."""
        self.streak = 0
        self.stalled = False


# ---------------------------------------------------------------------------
# `tt quality` — offline summarizer (stdlib + read_jsonl only).


def summarize(records) -> str:
    """Quality report text for a list of JSONL record dicts: diversity
    trend across the run's qualityEntry snapshots, operator hit rates,
    migration gain, and the stall/kick event log (faultEntry site
    `quality`)."""
    entries: list = []
    stalls: list = []
    for rec in records:
        if "qualityEntry" in rec:
            entries.append(rec["qualityEntry"])
        elif "faultEntry" in rec:
            f = rec["faultEntry"]
            if f.get("site") == "quality":
                stalls.append(f)
    lines = [f"== quality entries: {len(entries)}"]
    if entries:
        # per-job streams (serve logs) are summarized separately from
        # the run-wide engine stream
        run_wide = [e for e in entries if "job" not in e]
        jobs: dict = {}
        for e in entries:
            if "job" in e:
                jobs.setdefault(str(e["job"]), []).append(e)

        def _trend(es, key):
            vals = [e[key] for e in es if isinstance(e.get(key),
                                                     (int, float))]
            if not vals:
                return None
            return vals[0], vals[-1]

        def _rate(es, wins, attempts):
            w = entry_total(es, wins)
            a = entry_total(es, attempts)
            return w, a, (w / a if a else 0.0)

        def _section(name, es):
            out = [f"== {name}"]
            for key, label in (
                    ("quality.diversity.hamming", "hamming"),
                    ("quality.diversity.penalty_var", "penalty var"),
                    ("quality.diversity.scv_var", "scv var")):
                tr = _trend(es, key)
                if tr is not None:
                    out.append(f"  {label}: {tr[0]:.4g} -> {tr[1]:.4g}")
            for wins, attempts, label in (
                    ("quality.ops.crossover_wins",
                     "quality.ops.crossover_attempts", "crossover"),
                    ("quality.ops.mutation_wins",
                     "quality.ops.mutation_attempts", "mutation")):
                w, a, r = _rate(es, wins, attempts)
                out.append(f"  {label}: {w}/{a} wins ({r:.1%})")
            for key, label in (
                    ("quality.ops.move1_accepts", "move1"),
                    ("quality.ops.move2_accepts", "move2"),
                    ("quality.ops.move3_accepts", "move3")):
                out.append(f"  sweep {label} accepts: "
                           f"{entry_total(es, key)}")
            out.append(f"  migration gain: "
                       f"{entry_total(es, 'quality.migration.gain')}")
            return out

        if run_wide:
            lines.extend(_section("run", run_wide))
        for jid, es in sorted(jobs.items()):
            # serve payloads are lane_payload-flat (no quality. prefix)
            out = [f"== job {jid}"]
            tr = _trend(es, "hamming")
            if tr is not None:
                out.append(f"  hamming: {tr[0]:.4g} -> {tr[1]:.4g}")
            for wins, attempts, label in (
                    ("crossover_wins", "crossover_attempts", "crossover"),
                    ("mutation_wins", "mutation_attempts", "mutation")):
                w = sum(int(e.get(wins, 0)) for e in es)
                a = sum(int(e.get(attempts, 0)) for e in es)
                out.append(f"  {label}: {w}/{a} wins "
                           f"({w / a if a else 0.0:.1%})")
            lines.extend(out)
    if stalls:
        lines.append(f"== stalls ({len(stalls)} events)")
        for f in stalls:
            extra = ""
            if f.get("action") == "kick":
                extra = f" moves={f.get('moves')}"
            elif "streak" in f:
                extra = (f" streak={f.get('streak')}"
                         f" hamming={f.get('hamming')}")
            lines.append(f"  {f.get('action')} @ {f.get('time', 0.0):.1f}s"
                         + extra)
    else:
        lines.append("== stalls: none")
    return "\n".join(lines)


def main_quality(argv) -> int:
    """`tt quality <log.jsonl>` entry point (stdlib, device-free)."""
    inp = None
    for a in argv:
        if a in ("-h", "--help"):
            print("usage: tt quality <log.jsonl>\n\n"
                  "summarize a run's search-quality telemetry: diversity "
                  "trend (Hamming sample, penalty/scv variance), operator "
                  "hit rates (crossover/mutation wins, sweep Move1/2/3 "
                  "accepts), migration gain, and stall/kick events")
            return 0
        if inp is None:
            inp = a
        else:
            raise SystemExit(f"unknown argument: {a}")
    if inp is None:
        raise SystemExit("usage: tt quality <log.jsonl>")
    from timetabling_ga_tpu.obs.trace_export import read_jsonl
    print(summarize(read_jsonl(inp)))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main_quality(sys.argv[1:]))
