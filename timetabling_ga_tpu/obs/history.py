"""tt-flight history rings: bounded per-series metrics history.

Every signal in the obs stack so far is INSTANTANEOUS — live gauges on
/metrics, streamed metricsEntry snapshots — so "sustained backlog",
"burn for N seconds", and "what did this gauge do over the last
30 seconds" were unanswerable without an external scrape store. Yet
ROADMAP item 3's autoscaling loop is defined entirely in terms of
SUSTAINED signals: backlog trend as the spawn trigger, SLO burn
duration, warmth over time as the scale-down guard. This module is the
substrate that loop consumes.

`HistoryRing` samples the process MetricsRegistry every `every_s`
seconds FROM ITS OWN DAEMON THREAD (the MemPoller discipline,
obs/cost.py: atexit-guarded, `sys.is_finalizing`-guarded, die/hang
isolated behind the `history` fault site — a parked or dead sampler
means stale history, never a stalled dispatch, settlement, or writer
drain), keeping a fixed-capacity ring of `(t, value)` samples per
series. Counters and gauges are sampled as-is; each histogram
contributes its `<name>.count` and `<name>.sum` series so `rate()`
over them yields live throughput and mean-latency trends.

Window queries (stdlib-only, lock-guarded ring reads):

  rate(name, window)        (last - first) / dt over the window — the
                            counter-rate primitive (records/s, jobs/s)
  mean_over(name, window)   arithmetic mean of the window's samples —
                            the gauge-trend primitive (mean backlog)
  sustained(name, op, threshold, for_s)
                            True iff the ring COVERS the last `for_s`
                            seconds and EVERY sample in that window
                            satisfies `value <op> threshold`. This is
                            THE autoscaler trigger primitive: a spike
                            that visited the threshold once is not a
                            sustained condition, and neither is a
                            freshly started ring that has not watched
                            the signal long enough to know. ROADMAP
                            item 3's loop is specified against it
                            (e.g. `sustained("serve.queue_depth",
                            ">=", hwm, 30.0)` as the spawn trigger).
  window(window_s)          {name: [[t, v], ...]} — the JSON payload
                            `GET /metrics/history?window=S` serves on
                            the pull front (obs/http.py; the handler
                            only READS this ring — TT602-pure).

Timestamps are seconds on the ring's own monotonic clock (`now=`
injectable for tests). Capacity is per-series (`TT_HISTORY_CAP`,
default 600 samples — ten minutes at the default 1 s cadence); series
that stop existing keep their last samples until they age out of every
window, which is exactly what an incident bundle wants.

Stdlib-only, like the rest of obs/: importable without JAX.
"""

from __future__ import annotations

import atexit
import collections
import operator
import os
import sys
import threading
import time

from timetabling_ga_tpu.obs import metrics as obs_metrics

# per-series ring capacity: ten minutes of samples at the default 1 s
# cadence — enough for every window the autoscaler primitives take,
# bounded regardless of process lifetime
HISTORY_CAP = int(os.environ.get("TT_HISTORY_CAP", "600"))

_OPS = {">=": operator.ge, "<=": operator.le, ">": operator.gt,
        "<": operator.lt, "==": operator.eq}


def _faults():
    """Lazy import (the MemPoller pattern): this module must stay
    importable wherever obs/ is, and the sampler thread only exists
    inside engine/serve/gateway processes."""
    from timetabling_ga_tpu.runtime import faults
    return faults


class HistoryRing:
    """Fixed-capacity per-series sample rings over one MetricsRegistry.

    `start()` launches the sampler daemon thread; `sample_once()` is
    the testable unit (and returns False when the thread should exit —
    injected death or interpreter teardown). All query methods read
    under the ring lock and never touch the registry, so the
    `/metrics/history` handler path stays a pure observer."""

    def __init__(self, registry=None, every_s: float = 1.0,
                 capacity: int | None = None, now=time.monotonic):
        self._reg = (obs_metrics.REGISTRY if registry is None
                     else registry)
        self.every_s = max(0.05, float(every_s))
        self._cap = int(capacity if capacity is not None
                        else HISTORY_CAP)
        self._now = now
        self._series: dict[str, collections.deque] = {}
        self._lock = threading.Lock()
        self._samples = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="tt-history", daemon=True)

    # -- sampling (the off-path daemon thread) --------------------------

    def start(self) -> "HistoryRing":
        self._thread.start()
        # stop the sampler before interpreter teardown even on abrupt
        # exits (the MemPoller discipline — a daemon thread snapshotting
        # a registry mid-teardown is undefined); close() is idempotent,
        # normal owners still call it from their finallys
        atexit.register(self.close)
        return self

    def alive(self) -> bool:
        return self._thread.is_alive()

    def sample_once(self) -> bool:
        """One registry snapshot into the rings; False when the sampler
        thread should exit (injected death / teardown)."""
        if sys.is_finalizing():
            return False
        try:
            _faults().maybe_fail("history")
            snap = self._reg.snapshot()
        except SystemExit:
            return False            # injected death: exit silently
        except Exception:
            return True             # a torn snapshot skips one tick
        t = self._now()
        points: list[tuple[str, float]] = []
        for kind in ("counters", "gauges"):
            for name, v in (snap.get(kind) or {}).items():
                if isinstance(v, (int, float)) and v == v:
                    points.append((name, float(v)))
        for name, h in (snap.get("histograms") or {}).items():
            points.append((f"{name}.count", float(h.get("count", 0))))
            points.append((f"{name}.sum", float(h.get("sum", 0.0))))
        with self._lock:
            self._samples += 1
            for name, v in points:
                ring = self._series.get(name)
                if ring is None:
                    ring = self._series[name] = collections.deque(
                        maxlen=self._cap)
                ring.append((t, v))
        return True

    def _loop(self) -> None:
        while True:
            if not self.sample_once():
                return
            if self._stop.wait(self.every_s):
                return

    def close(self) -> None:
        self._stop.set()
        if self._thread.ident is not None:   # never-started: no join
            self._thread.join(timeout=2.0)   # a hung sampler is
            #                                  abandoned (daemon),
            #                                  never waited out
        atexit.unregister(self.close)

    # -- window queries --------------------------------------------------

    def _window(self, name: str, window_s: float | None
                ) -> list[tuple[float, float]]:
        with self._lock:
            ring = self._series.get(name)
            pts = list(ring) if ring is not None else []
        if window_s is None or not pts:
            return pts
        cut = self._now() - max(0.0, float(window_s))
        return [p for p in pts if p[0] >= cut]

    def series(self, name: str, window_s: float | None = None
               ) -> list[tuple[float, float]]:
        """The raw (t, value) samples of one series, newest last."""
        return self._window(name, window_s)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def rate(self, name: str, window_s: float) -> float | None:
        """(last - first) / dt over the window — the counter-rate
        primitive. None with fewer than two samples (or zero dt)."""
        pts = self._window(name, window_s)
        if len(pts) < 2:
            return None
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        dt = t1 - t0
        return (v1 - v0) / dt if dt > 0 else None

    def mean_over(self, name: str, window_s: float) -> float | None:
        """Mean of the window's samples — the gauge-trend primitive.
        None when the window holds no samples."""
        pts = self._window(name, window_s)
        if not pts:
            return None
        return sum(v for _, v in pts) / len(pts)

    def sustained(self, name: str, op: str, threshold: float,
                  for_s: float) -> bool:
        """True iff the ring COVERS the last `for_s` seconds of `name`
        and EVERY sample in that window satisfies `value <op>
        threshold` (op in >=, <=, >, <, ==) — the documented
        autoscaler trigger primitive (module docstring). Coverage
        means the window's OLDEST sample is at least `for_s` old: a
        ring that has not watched the signal that long answers False,
        never a guess."""
        cmp = _OPS.get(op)
        if cmp is None:
            raise ValueError(f"sustained() op must be one of "
                             f"{sorted(_OPS)}, got {op!r}")
        for_s = max(0.0, float(for_s))
        pts = self._window(name, for_s)
        if not pts:
            return False
        if self._now() - pts[0][0] < for_s - self.every_s:
            # the window is not covered: the oldest in-window sample is
            # too young (one cadence of slack — the sampler ticks at
            # every_s, so exact coverage would never be observable)
            return False
        return all(cmp(v, threshold) for _, v in pts)

    def window(self, window_s: float | None = None) -> dict:
        """Every series' in-window samples — the
        `GET /metrics/history?window=S` payload (and the incident
        bundle's `history` section, obs/flight.py). ONE locked pass
        with ONE cut timestamp: every series is filtered against the
        same 'now', and a scrape over many series costs one lock
        round-trip, not one per series."""
        cut = (None if window_s is None
               else self._now() - max(0.0, float(window_s)))
        with self._lock:
            samples = self._samples
            series = {n: [[round(t, 6), v] for t, v in ring
                          if cut is None or t >= cut]
                      for n, ring in sorted(self._series.items())}
        return {"every_s": self.every_s, "capacity": self._cap,
                "samples": samples, "series": series}
