"""Shared OpenMetrics/Prometheus text-exposition parser.

Before this module every consumer of a `/metrics` scrape grew its own
regex: the fleet prober split lines by the first space
(fleet/replicas.py `_scrape_metrics`), and each new dashboard tool was
about to add a third copy. One parser, unit-tested once, consumed by:

  - the ReplicaSet prober (fleet/replicas.py): refreshes each handle's
    router inputs — the backlog gauge and the compile-hit counters —
    from one parse per probe;
  - the router (fleet/router.py): scores on exactly the families named
    here (`QUEUE_DEPTH`, `COMPILE_COUNT`, `COMPILE_HITS`), read back
    off the handle fields the prober filled;
  - `tools/bench_report.py --metrics FILE`: renders a saved exposition
    snapshot (`curl gateway:PORT/metrics > snap.txt`) as a table — the
    fleet dashboard with no Prometheus installed;
  - the bench `extra.fleet` obs leg: counts the gateway's span/route
    records against its own scraped families.

Handles both expositions our registry emits (obs/metrics.py): the
Prometheus 0.0.4 text format and OpenMetrics 1.0 with exemplars
(`name{le="0.5"} 3 # {job="j42"} 0.93`) and the `# EOF` trailer.
Unparseable lines are skipped, never fatal — a scrape must degrade,
not raise (the prober treats a failed parse as stale gauges).

Stdlib-only and device-free, like the rest of obs/.
"""

from __future__ import annotations

import re

# the metric families the fleet router scores on (fleet/router.py):
# kept here, next to the parser, so the prober and any future scrape
# consumer name them identically
QUEUE_DEPTH = "tt_serve_queue_depth"
BACKLOG = "tt_serve_backlog"
COMPILE_COUNT = "tt_compile_count_total"
COMPILE_HITS = "tt_compile_cache_hits_total"
# tt-flight: the replica's incident-dump counter (obs/flight.py). The
# prober watches it across probes and fetches GET /v1/incident when it
# advances, so the gateway holds a replica's newest bundle even after
# the replica dies — the "30 seconds before the failover" evidence
FLIGHT_DUMPS = "tt_flight_dumps_total"
# device residency (serve/scheduler.py RESIDENCY): groups parked on
# device between quanta and the bytes a retire would flush. The
# autoscaler's residency-aware victim choice scores on both
# (fleet/autoscaler.py choose_victim) — retiring a cold replica costs
# nothing; retiring a warm one flushes every resident group
RESIDENT_GROUPS = "tt_serve_resident_groups"
RESIDENT_BYTES = "tt_serve_resident_bytes"

# one sample line: name, optional {labels}, value, optional exemplar
# (OpenMetrics: " # {labels} value [timestamp]")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^\s#]+)"
    r"(?:\s+#\s+\{(?P<exlabels>[^}]*)\}\s+(?P<exvalue>\S+).*)?"
    r"\s*$")

_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    """Label-value unescaping (the inverse of obs/metrics.py
    `_escape_label`): backslash, double quote, newline."""
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def parse_labels(block: str) -> dict:
    """`le="0.5",job="j 42"` -> {"le": "0.5", "job": "j 42"}."""
    return {m.group(1): _unescape(m.group(2))
            for m in _LABEL_RE.finditer(block or "")}


def parse_exposition(text: str) -> dict:
    """Exposition text -> {sample_name: [(labels_dict, value), ...]}.

    Sample names are the WIRE names (`tt_serve_queue_depth`,
    `tt_compile_count_total`, `tt_fleet_job_seconds_bucket`) — one
    entry per sample line, in document order. Comment lines (`# TYPE`,
    `# HELP`, `# EOF`) and anything unparseable are skipped."""
    out: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        out.setdefault(m.group("name"), []).append(
            (parse_labels(m.group("labels")), value))
    return out


def parse_exemplars(text: str) -> list:
    """OpenMetrics bucket exemplars: [(sample_name, labels_dict,
    value), ...] in document order — the (job/dispatch, latency)
    pairs a p99 spike joins back to. Same regex as parse_exposition,
    so there is exactly one copy of the format knowledge."""
    out = []
    for line in text.splitlines():
        m = _SAMPLE_RE.match(line.strip())
        if m is None or m.group("exvalue") is None:
            continue
        try:
            v = float(m.group("exvalue"))
        except ValueError:
            continue
        out.append((m.group("name"),
                    parse_labels(m.group("exlabels")), v))
    return out


def scalar(families: dict, name: str, default=None):
    """First unlabeled (or only) sample of `name`, or `default` — the
    gauge/counter read every router input is."""
    samples = families.get(name)
    if not samples:
        return default
    for labels, value in samples:
        if not labels:
            return value
    return samples[0][1]


def labeled(families: dict, name: str, **want):
    """First sample of `name` whose labels include all of `want`
    (e.g. `labeled(fams, "tt_fleet_job_seconds_bucket", le="+Inf")`),
    or None."""
    for labels, value in families.get(name, ()):
        if all(labels.get(k) == v for k, v in want.items()):
            return value
    return None


def hit_rate(families: dict) -> float:
    """Measured compile-hit rate from the families the router scrapes
    (obs/cost.py accounting): hits / (count + hits), 0.0 when the
    process has never compiled."""
    count = scalar(families, COMPILE_COUNT, 0.0) or 0.0
    hits = scalar(families, COMPILE_HITS, 0.0) or 0.0
    total = count + hits
    return hits / total if total > 0 else 0.0
