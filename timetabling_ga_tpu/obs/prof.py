"""tt-prof phase profiler: phase-level device-time attribution for
jax.profiler captures, hotspot ranking/diffing, and the profEntry feed.

ROADMAP item 4 is an indictment the rest of tt-obs could not answer:
gens/s has been flat across bench rounds and `tt profile` captures
device timelines NOBODY parses — the roofline gauges say how fast the
machine runs, not WHICH phase of a generation (room matching vs Move1
sweep vs fitness vs migration) owns the missing time. This module
closes the capture -> attribute -> rank -> gate loop:

  PHASE SCOPES — `scope(name)` wraps `jax.named_scope` with a single
  validated registry (`PHASES`); the ops modules and island runners
  enter a scope around each algorithmic phase so every XLA op's HLO
  metadata `op_name` carries its phase path. Scopes are METADATA-ONLY:
  record streams, trajectories and trace counts are bit-identical with
  scopes on or off (tests/test_prof.py pins it, the TT202 discipline),
  and TT_PROF_SCOPES=0 is the kill switch that turns every scope into
  a nullcontext. tt-analyze TT310 rejects free-form scope strings —
  a typo'd scope silently unattributes.

  SIDECAR JOIN — on CPU (and some TPU runtimes) the trace events carry
  `{hlo_module, hlo_op}` args but NOT the named_scope path; the scope
  lives in the compiled module's per-instruction metadata. So the cost
  observatory calls `note_executable(exe)` at compile time (the one
  moment introspection is free — the TT603 argument), which regex-walks
  `exe.as_text()` for `metadata={... op_name="..."}` and keeps a
  bounded {hlo module -> {op -> phase}} map; `write_scope_map(dir)`
  drops it as a `tt_scope_map.json` sidecar into the capture dir, and
  the parser joins trace events against it. Events the sidecar misses
  fall back to scanning the event strings for `tt.*` tokens; events
  neither path can place land in an HONEST `unattributed` bucket —
  never silently folded into a phase.

  ATTRIBUTION — `attribute(capture_dir)` walks a jax.profiler capture
  directory (the Chrome trace.json.gz the plugin writes), computes
  per-event SELF time (container ops like `while.N` span their body
  ops on the same thread — raw durations double-count; a stack pass
  subtracts each child from its immediate parent), buckets self time
  by innermost `tt.*` scope, and returns the per-phase table: seconds,
  fraction of device time, top-K ops per phase.

  WIRING — `capture_hook(out, registry, now)` builds the ProfileCapture
  on-complete callback: sidecar write + attribute + `publish` into
  `prof.phase_seconds.<phase>` gauges (the history ring samples them
  for free) and a `profEntry` JSONL record when an emitter is bound.
  profEntry is a TIMING record (jsonl.TIMING_RECORDS): the stream
  identity contract holds with profiling on or off by construction.

  CLI — `tt hotspots DIR|LOG [--top K] [--json]` renders the ranked
  table from a capture dir or a log's profEntries; `tt hotspots
  --diff A B` prints per-phase deltas between two captures — the A/B
  instrument every item-4 kernel attack verifies with.

Import-time stdlib-only, like the rest of obs/ (`tt hotspots` must run
on a machine with no jax); the one jax touch (named_scope) hides
behind a function-local import that only engine/serve processes take.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import re
import sys
import threading

from timetabling_ga_tpu.obs import metrics as obs_metrics

# THE scope registry: every named_scope string in the package comes
# from here (tt-analyze TT310 enforces it — a free-form scope string
# would silently land in `unattributed`). One entry per algorithmic
# phase of the memetic loop; names are dotted so the innermost-wins
# attribution can pick them out of an op_name path.
PHASES = ("tt.fitness", "tt.rooms", "tt.delta", "tt.sweep", "tt.ga",
          "tt.moves", "tt.migrate", "tt.lahc", "tt.polish",
          "tt.quality")

_PHASE_SET = frozenset(PHASES)

# kill switch: TT_PROF_SCOPES=0 turns every scope() into a
# nullcontext and note_executable into a no-op — the bit-identity
# A/B's other leg, like TT_COST_OBS for the cost observatory
SCOPES_ENABLED = os.environ.get("TT_PROF_SCOPES", "1") != "0"

# sidecar file name written into a capture dir: the compile-time
# {hlo module -> {op -> phase}} join table
SIDECAR = "tt_scope_map.json"

# cap on remembered HLO modules (serve processes compile one program
# per bucket; a runaway would otherwise grow without bound)
_MAX_MODULES = 64


def short(phase: str) -> str:
    """Gauge/JSON key for a phase: the registry name minus the `tt.`
    prefix (`prof.phase_seconds.sweep`, profEntry `phases.sweep`)."""
    return phase[3:] if phase.startswith("tt.") else phase


class _NullScope(contextlib.nullcontext, contextlib.ContextDecorator):
    """nullcontext that also works as a decorator (stdlib nullcontext
    grew that only in 3.12) — scope() must swap in for jax.named_scope
    in BOTH positions when scopes are off."""


def scope(name: str):
    """Phase scope `name` (must be in PHASES) as a jax.named_scope —
    usable as a context manager or a function decorator, a trace-time
    METADATA annotation either way: no op changes, no record changes,
    no compile-cache key changes. Returns a null scope when scopes are
    disabled (TT_PROF_SCOPES=0) or jax is not importable (host-only
    tools never pay the import)."""
    if name not in _PHASE_SET:
        raise ValueError(
            f"unknown phase scope {name!r}: tt-prof scopes must come "
            f"from obs/prof.py PHASES {sorted(_PHASE_SET)}")
    if not SCOPES_ENABLED:
        return _NullScope()
    try:
        import jax
    except Exception:        # pragma: no cover - jax-free host tools
        return _NullScope()
    return jax.named_scope(name)


# ------------------------------------------------- compile-time sidecar

# {hlo module name -> {instruction name -> phase}} harvested from
# compiled executables; only tt-phased ops are kept (the join table
# stays small — a few hundred entries per program)
_SCOPE_MAPS: dict = {}
# {hlo module name -> {instruction name}} assigned DIFFERENT phases by
# two same-named executables — the trace only records the module name,
# so such an op can't be attributed without guessing (note_executable)
_AMBIG_OPS: dict = {}
_MAPS_LOCK = threading.Lock()

_HLO_MODULE_RE = re.compile(r"^HloModule\s+([^\s,]+)")
# one HLO instruction line: `  %name = type op(...), ...,
# metadata={... op_name="jit(f)/.../tt.sweep/dot_general" ...}` —
# anchored at the assignment so `calls=`/`dimensions={...}` noise
# inside the line cannot fake a match
_HLO_OP_RE = re.compile(
    r'^\s*(?:ROOT\s+)?%?([^\s=]+)\s+=\s+.*'
    r'metadata=\{[^}]*op_name="([^"]+)"')
# any instruction line (metadata or not) — for the call-graph fallback
_HLO_ANY_OP_RE = re.compile(r'^\s+(?:ROOT\s+)?%?([^\s=]+)\s+=\s+')
# a computation header starts at column 0: `%name (params...) -> ... {`
# (the ENTRY computation keeps the module's name and is irrelevant to
# the fallback — instructions calling into it don't exist)
_HLO_COMP_RE = re.compile(r'^(?:ENTRY\s+)?%?([^\s(]+)\s*\(')
# computations an instruction calls into: `calls=%f`, `body=%b`,
# `condition=%c`, `to_apply=%t` — optimizer-synthesized whiles/fusions
# often carry NO metadata, so their phase is recovered from the ops of
# the computations they call (majority vote)
_HLO_CALLS_RE = re.compile(
    r'(?:calls|body|condition|to_apply)=%?([\w.\-]+)')


def phase_of_op_name(op_name: str):
    """Innermost `tt.*` component of an HLO op_name path, or None.
    Scopes nest (`.../tt.ga/.../tt.sweep/dot`): the INNERMOST scope is
    the phase that actually owns the op — attributing to the outermost
    would fold every nested phase into `tt.ga`."""
    last = None
    for part in op_name.split("/"):
        if part in _PHASE_SET:
            last = part
    return last


def note_executable(exe) -> None:
    """Harvest {op -> phase} from a freshly compiled executable's HLO
    metadata into the module-keyed sidecar map. Called by the cost
    observatory at compile time (CostProgram._compile) — the only
    moment executable introspection is free (TT603); duck-typed and
    failure-swallowing so a backend without `as_text()` degrades to
    the substring fallback instead of breaking a compile."""
    if not SCOPES_ENABLED:
        return
    try:
        text = exe.as_text()
    except Exception:
        return
    if not text:
        return
    module = None
    ops: dict = {}
    comp_counts: dict = {}      # computation -> {phase -> op count}
    insts: list = []            # (op, [callee comps], containing comp)
    comp = None
    entry_comps: set = set()
    for line in text.splitlines():
        if module is None:
            m = _HLO_MODULE_RE.match(line)
            if m:
                module = m.group(1)
                continue
        if line and not line[0].isspace():
            m = _HLO_COMP_RE.match(line)
            if m:
                comp = m.group(1)
                if line.startswith("ENTRY"):
                    entry_comps.add(comp)
            continue
        if " parameter(" in line:
            continue   # no compute; names repeat across computations
        m = _HLO_OP_RE.match(line)
        if m:
            insts.append((m.group(1), _HLO_CALLS_RE.findall(line),
                          comp))
            phase = phase_of_op_name(m.group(2))
            if phase is not None:
                ops[m.group(1)] = phase
                cc = comp_counts.setdefault(comp, {})
                cc[phase] = cc.get(phase, 0) + 1
            continue
        m = _HLO_ANY_OP_RE.match(line)
        if m and "metadata=" not in line:
            insts.append((m.group(1), _HLO_CALLS_RE.findall(line),
                          comp))
    # Fixpoint over the call graph, both directions. Optimizer-
    # synthesized whiles/fusions carry no op_name, and whole scan
    # bodies can end up metadata-free; an unresolved op takes:
    #   1. UP   the majority phase of the ops inside the computations
    #           it calls (calls=/body=/condition=/to_apply=), else the
    #           inherited phase of those computations;
    #   2. DOWN the phase its own computation inherits from its
    #           callers — every phase-resolved op calling into a
    #           computation agrees => the computation runs inside that
    #           phase (time in a tt.rooms while body IS rooms time);
    #   3. the majority phase of its sibling ops (non-entry only).
    # Entry-computation glue with no resolvable phase stays out of
    # `ops` and lands in the parser's honest `unattributed` bucket —
    # folding it into the entry's majority would overclaim a phase.
    # Once resolved, an op votes in its own computation, so nested
    # synthesized loops resolve outward; bounded iterations (call
    # graphs are shallow).
    pending = [i for i in insts if i[0] not in ops]
    for _ in range(8):
        caller_ph: dict = {}
        for op, callees, _owner in insts:
            ph = ops.get(op)
            if ph is not None:
                for c in callees:
                    caller_ph.setdefault(c, set()).add(ph)
        comp_phase = {c: next(iter(s))
                      for c, s in caller_ph.items() if len(s) == 1}
        progressed = False
        still = []
        for op, callees, owner in pending:
            votes: dict = {}
            for c in callees:
                for ph, n in comp_counts.get(c, {}).items():
                    votes[ph] = votes.get(ph, 0) + n
            if not votes:
                for c in callees:
                    ph = comp_phase.get(c)
                    if ph is not None:
                        votes[ph] = votes.get(ph, 0) + 1
            if not votes and owner not in entry_comps:
                votes = dict(comp_counts.get(owner, {}))
                if not votes and owner in comp_phase:
                    votes = {comp_phase[owner]: 1}
            if votes:
                phase = max(votes.items(), key=lambda kv: (kv[1], kv[0]))[0]
                ops[op] = phase
                cc = comp_counts.setdefault(owner, {})
                cc[phase] = cc.get(phase, 0) + 1
                progressed = True
            else:
                still.append((op, callees, owner))
        pending = still
        if not progressed or not pending:
            break
    if module is None or not ops:
        return
    with _MAPS_LOCK:
        existing = _SCOPE_MAPS.get(module)
        if existing is None:
            if len(_SCOPE_MAPS) >= _MAX_MODULES:
                return
            _SCOPE_MAPS[module] = ops
            return
        # Same module name compiled again. XLA names a module after
        # the jitted callable, so two structurally DIFFERENT programs
        # can collide (the islands._donate `name=` parameter keeps the
        # stock runners distinct, but user jits can still clash) — and
        # the trace only records the module NAME. Merge the op tables;
        # an op name two variants put in DIFFERENT phases is dropped
        # (and pinned dropped) to the honest unattributed bucket
        # rather than attributed by guess.
        ambig = _AMBIG_OPS.setdefault(module, set())
        for name, phase in ops.items():
            if name in ambig:
                continue
            cur = existing.get(name)
            if cur is None:
                existing[name] = phase
            elif cur != phase:
                del existing[name]
                ambig.add(name)


def write_scope_map(capture_dir: str):
    """Drop the harvested join table as `tt_scope_map.json` inside
    `capture_dir` (next to the plugin's `plugins/` tree, so the
    sidecar travels with the capture). Returns the path, or None when
    nothing was harvested (the parser then runs on its substring
    fallback alone)."""
    with _MAPS_LOCK:
        if not _SCOPE_MAPS:
            return None
        payload = {"modules": {k: dict(v)
                               for k, v in _SCOPE_MAPS.items()}}
    try:
        path = os.path.join(capture_dir, SIDECAR)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        return path
    except OSError:
        return None


def _reset_scope_maps() -> None:
    """Test hook: forget every harvested module."""
    with _MAPS_LOCK:
        _SCOPE_MAPS.clear()
        _AMBIG_OPS.clear()


# ------------------------------------------------------------ the parser


def _find_trace_files(capture_dir: str) -> list:
    """Trace files of the NEWEST profiler run under `capture_dir` —
    `plugins/profile/<run>/<host>.trace.json.gz` is where the plugin
    writes; a dir holding trace files directly, or a single trace file
    path, is accepted too (synthetic fixtures, copied captures)."""
    if os.path.isfile(capture_dir):
        return [capture_dir]
    direct = sorted(
        glob.glob(os.path.join(capture_dir, "*.trace.json.gz"))
        + glob.glob(os.path.join(capture_dir, "*.trace.json")))
    if direct:
        return direct
    runs = sorted(glob.glob(os.path.join(
        capture_dir, "plugins", "profile", "*")))
    if not runs:
        return []
    newest = runs[-1]
    return sorted(
        glob.glob(os.path.join(newest, "*.trace.json.gz"))
        + glob.glob(os.path.join(newest, "*.trace.json")))


def _load_trace(path: str) -> dict:
    if path.endswith(".gz"):
        with gzip.open(path, "rt", encoding="utf-8", errors="replace") as f:
            return json.load(f)
    with open(path, encoding="utf-8", errors="replace") as f:
        return json.load(f)


def _load_sidecar(capture_dir: str, trace_files: list) -> dict:
    """The sidecar join table for a capture: looked up next to the
    capture root AND next to the trace files (copies may keep either
    layout)."""
    cands = []
    if os.path.isdir(capture_dir):
        cands.append(os.path.join(capture_dir, SIDECAR))
    for tf in trace_files:
        cands.append(os.path.join(os.path.dirname(tf), SIDECAR))
    for path in cands:
        if os.path.isfile(path):
            try:
                with open(path, encoding="utf-8") as f:
                    return json.load(f).get("modules", {})
            except (OSError, ValueError):
                continue
    return {}


def _self_times(events: list) -> list:
    """Per-event SELF duration for one thread's complete events.

    Container ops (`while.N`, fusion wrappers) are emitted as events
    spanning their body ops on the SAME thread — summing raw durations
    counts the body twice. Sort by (ts, -dur) so parents precede their
    children, then a stack pass subtracts each event's duration from
    its immediate parent's self time. Returns (event, self_dur) pairs;
    self is clamped at 0 against clock jitter."""
    evs = sorted(events, key=lambda e: (e["ts"], -e["dur"]))
    out = []
    stack: list = []      # [ev_index_in_out, end_ts]
    for ev in evs:
        while stack and stack[-1][1] <= ev["ts"]:
            stack.pop()
        out.append([ev, ev["dur"]])
        if stack:
            parent = out[stack[-1][0]]
            parent[1] -= ev["dur"]
        stack.append([len(out) - 1, ev["ts"] + ev["dur"]])
    return [(ev, max(0.0, s)) for ev, s in out]


def _event_phase(ev: dict, args: dict, sidecar: dict):
    """Attribute one device-op event: the sidecar join (module+op from
    the event args against the compile-time map) wins; misses fall
    back to scanning the event's own strings for `tt.*` tokens, the
    INNERMOST (last-occurring) token winning — some runtimes inline
    the scope path into the event name. None = unattributed."""
    module = args.get("hlo_module")
    op = args.get("hlo_op") or ev.get("name")
    if module is not None:
        phase = sidecar.get(module, {}).get(op)
        if phase is not None:
            return phase
    hay = [str(ev.get("name", ""))]
    for v in args.values():
        if isinstance(v, str):
            hay.append(v)
    text = "/".join(hay)
    best, best_pos = None, -1
    for phase in PHASES:
        pos = text.rfind(phase)
        if pos > best_pos:
            best, best_pos = phase, pos
    return best if best_pos >= 0 else None


def attribute(capture_dir: str, top_k: int = 5) -> dict:
    """Walk a jax.profiler capture dir and return the per-phase
    device-time table:

      {"capture_dir": ..., "trace_files": [...], "n_events": N,
       "total_s": t, "phases": {"sweep": {"seconds": s, "frac": f,
                                          "top_ops": [[op, s], ...]},
                                ...},
       "unattributed_s": u, "unattributed_frac": uf,
       "unattributed_top_ops": [[op, s], ...]}

    Device ops are the complete ("X") events carrying hlo_op/
    hlo_module args; their SELF time (container-corrected) is what is
    bucketed, so total_s is real device-op time, counted once. The
    `unattributed` bucket is honest: everything neither the sidecar
    nor the token scan can place, reported — never folded."""
    trace_files = _find_trace_files(capture_dir)
    if not trace_files:
        raise FileNotFoundError(
            f"no trace.json(.gz) under {capture_dir!r} (expected a "
            f"jax.profiler capture dir: plugins/profile/<run>/)")
    sidecar = _load_sidecar(capture_dir, trace_files)
    phase_s: dict = {}
    phase_ops: dict = {}
    unattr_s = 0.0
    unattr_ops: dict = {}
    n_events = 0
    for tf in trace_files:
        trace = _load_trace(tf)
        by_tid: dict = {}
        for ev in trace.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            args = ev.get("args") or {}
            if "hlo_op" not in args and "hlo_module" not in args:
                continue
            try:
                ts = float(ev["ts"])
                dur = float(ev.get("dur", 0.0))
            except (KeyError, TypeError, ValueError):
                continue
            if dur <= 0:
                continue
            by_tid.setdefault(
                (ev.get("pid"), ev.get("tid")), []).append(
                    {"ts": ts, "dur": dur, "name": ev.get("name"),
                     "args": args})
        for evs in by_tid.values():
            for ev, self_us in _self_times(evs):
                if self_us <= 0:
                    continue
                n_events += 1
                sec = self_us / 1e6
                phase = _event_phase(ev, ev["args"], sidecar)
                opname = str(ev["args"].get("hlo_op")
                             or ev.get("name") or "?")
                if phase is None:
                    unattr_s += sec
                    unattr_ops[opname] = unattr_ops.get(opname, 0.0) + sec
                else:
                    phase_s[phase] = phase_s.get(phase, 0.0) + sec
                    ops = phase_ops.setdefault(phase, {})
                    ops[opname] = ops.get(opname, 0.0) + sec
    total = sum(phase_s.values()) + unattr_s

    def top(ops: dict) -> list:
        return [[op, round(s, 6)] for op, s in
                sorted(ops.items(), key=lambda kv: -kv[1])[:top_k]]

    phases = {}
    for phase, sec in sorted(phase_s.items(), key=lambda kv: -kv[1]):
        phases[short(phase)] = {
            "seconds": round(sec, 6),
            "frac": round(sec / total, 4) if total else 0.0,
            "top_ops": top(phase_ops.get(phase, {}))}
    return {"capture_dir": str(capture_dir),
            "trace_files": [os.path.basename(t) for t in trace_files],
            "n_events": n_events,
            "total_s": round(total, 6),
            "phases": phases,
            "unattributed_s": round(unattr_s, 6),
            "unattributed_frac": (round(unattr_s / total, 4)
                                  if total else 0.0),
            "unattributed_top_ops": top(unattr_ops)}


# ------------------------------------------------------- publish / hook


def publish(attr: dict, registry=None, out=None, now=None) -> None:
    """Feed one attribution result into the metrics registry
    (`prof.phase_seconds.<phase>`, `prof.total_seconds`,
    `prof.unattributed_seconds` — the history ring samples them for
    free) and, when an emitter is bound (`--obs`), emit the profEntry
    record. profEntry is a TIMING record: strip_timing drops it, so
    the stream identity contract (profiling on vs off) holds by
    construction."""
    reg = obs_metrics.REGISTRY if registry is None else registry
    for name, d in attr.get("phases", {}).items():
        reg.gauge(f"prof.phase_seconds.{name}").set(d["seconds"])
    reg.gauge("prof.total_seconds").set(attr.get("total_s", 0.0))
    reg.gauge("prof.unattributed_seconds").set(
        attr.get("unattributed_s", 0.0))
    if out is None:
        return
    try:
        from timetabling_ga_tpu.runtime import jsonl
        payload = {"dir": attr.get("capture_dir"),
                   "totalSeconds": attr.get("total_s", 0.0),
                   "phases": {n: {"s": d["seconds"], "frac": d["frac"],
                                  "top_ops": d.get("top_ops", [])[:3]}
                              for n, d in attr.get("phases",
                                                   {}).items()},
                   "unattributedSeconds": attr.get("unattributed_s",
                                                   0.0),
                   "unattributedFrac": attr.get("unattributed_frac",
                                                0.0)}
        ts = None
        if now is not None:
            try:
                ts = max(0.0, float(now()))
            except Exception:
                ts = None
        jsonl.prof_entry(out, payload, ts=ts)
    except Exception:
        pass   # telemetry must never fail a capture


def capture_hook(out=None, registry=None, now=None):
    """The ProfileCapture on-complete callback: write the sidecar into
    the finished capture dir, attribute it, publish gauges/profEntry,
    and return the attribution (ProfileCapture keeps it as `last()`
    for the /profile?last=1 poll `tt profile --attribute` rides).
    Runs on the capture WORKER thread — never the dispatch path."""

    def hook(capture_dir: str):
        write_scope_map(capture_dir)
        attr = attribute(capture_dir)
        publish(attr, registry=registry, out=out, now=now)
        return attr

    return hook


# --------------------------------------------------------- render / diff


def render(attr: dict, top_k: int = 3) -> str:
    """The ranked phase table as text (`tt hotspots`, `tt profile
    --attribute`)."""
    lines = [f"== phases ({attr.get('capture_dir', '?')}: "
             f"{attr.get('n_events', 0)} device ops, "
             f"{attr.get('total_s', 0.0):.4f}s device time)"]
    rows = list(attr.get("phases", {}).items())
    rows.sort(key=lambda kv: -kv[1]["seconds"])
    for name, d in rows:
        ops = ", ".join(f"{op} {s:.4f}s"
                        for op, s in d.get("top_ops", [])[:top_k])
        lines.append(f"  {('tt.' + name):<13} {d['seconds']:>9.4f}s "
                     f"{100 * d['frac']:>5.1f}%"
                     + (f"   {ops}" if ops else ""))
    ua = attr.get("unattributed_s", 0.0)
    uf = attr.get("unattributed_frac", 0.0)
    ops = ", ".join(f"{op} {s:.4f}s"
                    for op, s in attr.get("unattributed_top_ops",
                                          [])[:top_k])
    lines.append(f"  {'unattributed':<13} {ua:>9.4f}s "
                 f"{100 * uf:>5.1f}%" + (f"   {ops}" if ops else ""))
    return "\n".join(lines)


def diff(a: dict, b: dict) -> dict:
    """Per-phase deltas B - A between two attribution results: seconds
    delta and fraction-point delta per phase (union of both sides;
    `unattributed` included as its own row). The A/B instrument a
    kernel attack verifies with: phase X should shrink, nothing else
    should grow."""
    rows = {}
    pa = dict(a.get("phases", {}))
    pb = dict(b.get("phases", {}))
    for name in sorted(set(pa) | set(pb)):
        sa = pa.get(name, {}).get("seconds", 0.0)
        sb = pb.get(name, {}).get("seconds", 0.0)
        fa = pa.get(name, {}).get("frac", 0.0)
        fb = pb.get(name, {}).get("frac", 0.0)
        rows[name] = {"a_s": sa, "b_s": sb,
                      "delta_s": round(sb - sa, 6),
                      "delta_frac_pts": round(100 * (fb - fa), 2)}
    rows["unattributed"] = {
        "a_s": a.get("unattributed_s", 0.0),
        "b_s": b.get("unattributed_s", 0.0),
        "delta_s": round(b.get("unattributed_s", 0.0)
                         - a.get("unattributed_s", 0.0), 6),
        "delta_frac_pts": round(
            100 * (b.get("unattributed_frac", 0.0)
                   - a.get("unattributed_frac", 0.0)), 2)}
    return {"a": a.get("capture_dir"), "b": b.get("capture_dir"),
            "a_total_s": a.get("total_s", 0.0),
            "b_total_s": b.get("total_s", 0.0),
            "rows": rows}


def render_diff(d: dict) -> str:
    lines = [f"== phase diff  A={d.get('a')} ({d.get('a_total_s'):.4f}s)"
             f"  B={d.get('b')} ({d.get('b_total_s'):.4f}s)"]
    rows = sorted(d.get("rows", {}).items(),
                  key=lambda kv: -abs(kv[1]["delta_s"]))
    for name, r in rows:
        label = name if name == "unattributed" else "tt." + name
        lines.append(f"  {label:<13} {r['a_s']:>9.4f}s -> "
                     f"{r['b_s']:>9.4f}s   "
                     f"{r['delta_s']:+.4f}s "
                     f"({r['delta_frac_pts']:+.1f} pts)")
    return "\n".join(lines)


# ------------------------------------------------------------ log input


def prof_entries(path: str) -> list:
    """The profEntry bodies of a JSONL record stream (newest last)."""
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "profEntry" in rec:
                out.append(rec["profEntry"])
    return out


def _entry_to_attr(entry: dict) -> dict:
    """A profEntry body re-shaped into the attribute() result shape so
    render()/diff() serve both inputs."""
    phases = {}
    for name, d in (entry.get("phases") or {}).items():
        phases[name] = {"seconds": d.get("s", 0.0),
                        "frac": d.get("frac", 0.0),
                        "top_ops": d.get("top_ops", [])}
    total = entry.get("totalSeconds", 0.0)
    return {"capture_dir": entry.get("dir", "?"),
            "trace_files": [], "n_events": entry.get("n_events", 0),
            "total_s": total, "phases": phases,
            "unattributed_s": entry.get("unattributedSeconds", 0.0),
            "unattributed_frac": entry.get("unattributedFrac", 0.0),
            "unattributed_top_ops": []}


def _load_input(path: str) -> dict:
    """One `tt hotspots` input: a capture dir (or trace file) is
    attributed fresh; a JSONL log yields its NEWEST profEntry."""
    if os.path.isdir(path):
        return attribute(path)
    if path.endswith((".json.gz", ".trace.json")):
        return attribute(path)
    entries = prof_entries(path)
    if entries:
        return _entry_to_attr(entries[-1])
    # not a log with profEntries — try it as a raw trace file
    return attribute(path)


# ------------------------------------------------------------------ CLI


def main_hotspots(argv) -> int:
    """`tt hotspots <capture-dir|log.jsonl> [--top K] [--json]` /
    `tt hotspots --diff A B` — ranked phase/op table from a capture
    dir or a log's profEntry records; --diff prints per-phase deltas
    between two captures. Stdlib-only and device-free, like
    `tt trace` (the capture may live on a machine with no jax)."""
    args = list(argv)
    top_k, as_json, diff_pair, inputs = 3, False, None, []
    i = 0
    while i < len(args):
        a = args[i]
        if a in ("-h", "--help"):
            print("usage: tt hotspots <capture-dir|records.jsonl> "
                  "[--top K] [--json]\n"
                  "       tt hotspots --diff A B [--json]\n\n"
                  "rank device time by tt.* phase from a jax.profiler "
                  "capture dir (plugins/profile/...) or from a log's "
                  "profEntry records; --diff prints per-phase deltas "
                  "B - A (each side a capture dir or log)")
            return 0
        if a == "--top":
            if i + 1 >= len(args):
                raise SystemExit("flag --top needs a value")
            top_k = int(args[i + 1])
            i += 2
            continue
        if a == "--json":
            as_json = True
            i += 1
            continue
        if a == "--diff":
            if i + 2 >= len(args):
                raise SystemExit("--diff needs two inputs: A B")
            diff_pair = (args[i + 1], args[i + 2])
            i += 3
            continue
        inputs.append(a)
        i += 1
    try:
        if diff_pair is not None:
            d = diff(_load_input(diff_pair[0]),
                     _load_input(diff_pair[1]))
            print(json.dumps(d) if as_json else render_diff(d))
            return 0
        if len(inputs) != 1:
            raise SystemExit("usage: tt hotspots "
                             "<capture-dir|records.jsonl> [--top K] "
                             "[--json]  (or --diff A B)")
        attr = _load_input(inputs[0])
        print(json.dumps(attr) if as_json
              else render(attr, top_k=top_k))
        return 0
    except FileNotFoundError as e:
        print(f"tt hotspots: {e}", file=sys.stderr)
        return 1
