"""timetabling_ga_tpu — a TPU-native memetic-GA framework for university
course timetabling (UCTP, Metaheuristics-Network `.tim` formulation).

Re-designed from scratch for TPU (JAX/XLA) with the capabilities of the
reference MPI+OpenMP C++ solver (nelilepo/timetabling-ga-mpi-openmp):

- Population lives on-device as dense int32 tensors ``(P, E)`` slots/rooms
  (reference: ``vector<pair<int,int>>`` per Solution, Solution.h:36).
- Fitness (hard/soft constraint violations) is one jit+vmap tensor program
  whose inner contractions ride the MXU (reference: O(E^2) scalar loops,
  Solution.cpp:63-170).
- Room assignment is a fixed-iteration parallel priority matching over the
  (timeslot, room) grid (reference: per-slot augmenting-path max matching
  with greedy fallback, Solution.cpp:772-891).
- Local search is a batched K-candidate hill climb under ``lax.scan``
  (reference: sequential first-improvement sweeps, Solution.cpp:471-769).
- The MPI island model becomes a mesh axis: ``shard_map`` over ``island``,
  bidirectional ring migration via ``lax.ppermute``, global best via
  ``pmin`` (reference: MPI_Sendrecv ring + MPI_Allreduce, ga.cpp:479-541).
"""

# The public API is lazy (PEP 562): importing the package must NOT pull
# in jax, so the device-free surfaces — `tt trace` / `tt stats`
# (obs/trace_export.py, obs/logstats.py) and `python -m
# timetabling_ga_tpu.cli -h` — work on a machine with no accelerator
# stack at all (the log may have been copied anywhere). `import
# timetabling_ga_tpu as tt; tt.load_tim(...)` resolves on first touch
# exactly as before.
_EXPORTS = {
    "Problem": "timetabling_ga_tpu.problem",
    "dump_tim": "timetabling_ga_tpu.problem",
    "load_tim": "timetabling_ga_tpu.problem",
    "load_tim_file": "timetabling_ga_tpu.problem",
    "compute_hcv": "timetabling_ga_tpu.ops.fitness",
    "compute_scv": "timetabling_ga_tpu.ops.fitness",
    "compute_penalty": "timetabling_ga_tpu.ops.fitness",
    "batch_penalty": "timetabling_ga_tpu.ops.fitness",
    "GAConfig": "timetabling_ga_tpu.ops.ga",
    "PopState": "timetabling_ga_tpu.ops.ga",
    "init_population": "timetabling_ga_tpu.ops.ga",
    "assign_rooms": "timetabling_ga_tpu.ops.rooms",
    "batch_assign_rooms": "timetabling_ga_tpu.ops.rooms",
    "batch_parallel_assign_rooms": "timetabling_ga_tpu.ops.rooms",
    "batch_local_search": "timetabling_ga_tpu.ops.local_search",
    "sweep_local_search": "timetabling_ga_tpu.ops.sweep",
    "init_lahc": "timetabling_ga_tpu.ops.lahc",
    "lahc_steps": "timetabling_ga_tpu.ops.lahc",
    "make_mesh": "timetabling_ga_tpu.parallel",
    "init_island_population": "timetabling_ga_tpu.parallel",
    "make_island_runner": "timetabling_ga_tpu.parallel",
    "RunConfig": "timetabling_ga_tpu.runtime",
    "parse_args": "timetabling_ga_tpu.runtime",
    "run": "timetabling_ga_tpu.runtime",
}

__version__ = "0.1.0"


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib
    obj = getattr(importlib.import_module(mod), name)
    globals()[name] = obj      # cache: subsequent access skips this hook
    return obj


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
