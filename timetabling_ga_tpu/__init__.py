"""timetabling_ga_tpu — a TPU-native memetic-GA framework for university
course timetabling (UCTP, Metaheuristics-Network `.tim` formulation).

Re-designed from scratch for TPU (JAX/XLA) with the capabilities of the
reference MPI+OpenMP C++ solver (nelilepo/timetabling-ga-mpi-openmp):

- Population lives on-device as dense int32 tensors ``(P, E)`` slots/rooms
  (reference: ``vector<pair<int,int>>`` per Solution, Solution.h:36).
- Fitness (hard/soft constraint violations) is one jit+vmap tensor program
  whose inner contractions ride the MXU (reference: O(E^2) scalar loops,
  Solution.cpp:63-170).
- Room assignment is a fixed-iteration parallel priority matching over the
  (timeslot, room) grid (reference: per-slot augmenting-path max matching
  with greedy fallback, Solution.cpp:772-891).
- Local search is a batched K-candidate hill climb under ``lax.scan``
  (reference: sequential first-improvement sweeps, Solution.cpp:471-769).
- The MPI island model becomes a mesh axis: ``shard_map`` over ``island``,
  bidirectional ring migration via ``lax.ppermute``, global best via
  ``pmin`` (reference: MPI_Sendrecv ring + MPI_Allreduce, ga.cpp:479-541).
"""

from timetabling_ga_tpu.problem import (
    Problem, dump_tim, load_tim, load_tim_file)
from timetabling_ga_tpu.ops.fitness import (
    compute_hcv,
    compute_scv,
    compute_penalty,
    batch_penalty,
)
from timetabling_ga_tpu.ops.ga import GAConfig, PopState, init_population
from timetabling_ga_tpu.ops.rooms import (
    assign_rooms, batch_assign_rooms, batch_parallel_assign_rooms)
from timetabling_ga_tpu.ops.local_search import batch_local_search
from timetabling_ga_tpu.ops.sweep import sweep_local_search
from timetabling_ga_tpu.ops.lahc import init_lahc, lahc_steps
from timetabling_ga_tpu.parallel import (
    make_mesh, init_island_population, make_island_runner)
from timetabling_ga_tpu.runtime import RunConfig, parse_args, run

__version__ = "0.1.0"
